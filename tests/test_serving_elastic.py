"""Elastic disaggregated fleet (paddle_tpu/serving/fleet.py, ISSUE 11):

* Prefill/decode tiers — admissions land on a prefill-tier replica and
  MIGRATE at first token to a decode-tier replica through the journaled
  resume path (PR 8's mechanism on purpose instead of on failure):
  outputs token-identical to sequential generate(), zero journaled
  tokens re-decoded (progress deltas concatenate exactly to the done
  record), journal DFA green including the J009 version fence.
* Autoscaling — a burst spawns replicas (queue-depth pressure through
  the warm refill() machinery, supervisor backoff gating), a sustained
  lull drains + retires them (in-flight hedged from the journal); zero
  requests lost through a full scale-up -> scale-down cycle; fleet
  totals stay monotonic across retirement (stats fold).
* Live weight rollout — roll_weights() consumes a CRC-verified
  checkpoint (the sentinel's known-good step by default), swaps
  replicas one at a time behind a rolling drain, records the weight
  version on every response, and ABORTS with the fleet untouched when
  the candidate fails verification.
"""

import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.analysis.protocol_lint import verify_journal
from paddle_tpu.models import transformer as T
from paddle_tpu.serving import (
    RequestJournal,
    RolloutAborted,
    ServingFleet,
    save_weights,
)


@pytest.fixture(scope="module")
def model():
    cfg = T.TransformerConfig(vocab=64, dim=32, heads=4, layers=2,
                              max_len=64)
    return cfg, T.init_params(cfg, jax.random.PRNGKey(0))


def _oracle(params, cfg, prompt, max_new):
    return np.asarray(
        T.generate(params, jnp.asarray(prompt)[None], cfg, max_new)
    )[0]


def _requests(cfg, n, seed=0, t_lo=4, t_hi=10, n_lo=3, n_hi=6):
    rng = np.random.RandomState(seed)
    return [
        (rng.randint(0, cfg.vocab,
                     rng.randint(t_lo, t_hi + 1)).astype(np.int32),
         int(rng.randint(n_lo, n_hi + 1)))
        for _ in range(n)
    ]


def _audit_no_redecode(jpath):
    """Per rid: accepted progress deltas concatenate EXACTLY to the
    done record — a migrated request that re-decoded a journaled token
    would journal it twice and fail here."""
    done, prog = {}, {}
    for rec in RequestJournal._read(jpath):
        if rec["kind"] == "done":
            done[rec["rid"]] = rec["tokens"]
        elif rec["kind"] == "progress":
            prog.setdefault(rec["rid"], []).extend(rec["tokens"])
    for rid, toks in done.items():
        assert prog.get(rid, []) == toks, (
            "rid %d: journaled progress != done tokens (re-decode "
            "or double-prepend)" % rid)
    return done


def test_tier_migration_token_identity(model, tmp_path):
    """The disaggregation tentpole: every request admits on the
    prefill tier, migrates at first token, finishes on the decode
    tier — outputs identical to generate(), no token re-decoded,
    journal green (incl. the version side-band on assigns)."""
    cfg, params = model
    jpath = str(tmp_path / "tier.jsonl")
    fleet = ServingFleet(
        params, cfg, n_replicas=2, journal_path=jpath,
        replica_tier=["prefill", "decode"],
        heartbeat_timeout_s=120.0, monitor_interval_s=0.02,
        engine_kw={"max_slots": 4})
    try:
        reqs = _requests(cfg, 4)
        hs = [fleet.submit(p, n) for p, n in reqs]
        for h, (p, n) in zip(hs, reqs):
            out = h.result(timeout=300)
            np.testing.assert_array_equal(out,
                                          _oracle(params, cfg, p, n))
        st = fleet.stats()
        assert st["migrations"] >= 1, st
        assert st["lost"] == 0, st
        # migrated requests rode the resume path on purpose
        assert st["resumed_requests"] >= 1, st
    finally:
        fleet.close()
    done = _audit_no_redecode(jpath)
    assert len(done) == 4
    assert verify_journal(jpath, expect_closed=True) == []
    # the tier side-band landed on assign records
    tiers = [rec.get("tier") for rec in RequestJournal._read(jpath)
             if rec["kind"] == "assign"]
    assert "prefill" in tiers and "decode" in tiers, tiers


def test_no_decode_tier_no_migration(model):
    """Migration is gated on a live decode-capable target: a fleet
    whose only replica is prefill-tier just serves the request itself
    (survival beats tier placement)."""
    cfg, params = model
    fleet = ServingFleet(
        params, cfg, n_replicas=1, max_replicas=1,
        replica_tier=["prefill"], heartbeat_timeout_s=120.0,
        engine_kw={"max_slots": 2})
    try:
        p = np.arange(1, 6, dtype=np.int32)
        out = fleet.submit(p, 4).result(timeout=300)
        np.testing.assert_array_equal(out, _oracle(params, cfg, p, 4))
        assert fleet.stats()["migrations"] == 0
    finally:
        fleet.close()


def test_autoscale_up_down_cycle_no_losses(model, tmp_path):
    """A burst scales the fleet up (held-back slot spawns under the
    cool-down gate), the lull scales it back down (graceful drain ->
    journal hedge -> retire), and nothing is lost or duplicated.
    Retired replicas' work stays in the monotonic totals."""
    cfg, params = model
    jpath = str(tmp_path / "scale.jsonl")
    fleet = ServingFleet(
        params, cfg, n_replicas=1, min_replicas=1, max_replicas=2,
        journal_path=jpath, heartbeat_timeout_s=120.0,
        monitor_interval_s=0.02, scale_up_open_per_replica=1,
        scale_down_idle_s=0.3, scale_cooldown_s=0.05,
        engine_kw={"max_slots": 2})
    try:
        reqs = _requests(cfg, 6, seed=1)
        hs = [fleet.submit(p, n) for p, n in reqs]
        for h, (p, n) in zip(hs, reqs):
            out = h.result(timeout=300)
            np.testing.assert_array_equal(out,
                                          _oracle(params, cfg, p, n))
        st = fleet.stats()
        assert st["replicas_spawned"] >= 1, st
        tokens_at_peak = st["tokens_out"]
        # the lull: sustained low load retires the extra replica
        deadline = time.monotonic() + 30.0
        while fleet.stats()["replicas_live"] > 1:
            assert time.monotonic() < deadline, fleet.stats()
            time.sleep(0.02)
        st = fleet.stats()
        assert st["replicas_retired"] >= 1, st
        assert st["lost"] == 0, st
        # monotonic across retirement: the retired incarnation's
        # tokens folded into the cumulative base
        assert st["tokens_out"] >= tokens_at_peak, st
        # the fleet still serves after the cycle
        p, n = reqs[0]
        out = fleet.submit(p, n).result(timeout=300)
        np.testing.assert_array_equal(out, _oracle(params, cfg, p, n))
    finally:
        fleet.close()
    _audit_no_redecode(jpath)
    assert verify_journal(jpath, expect_closed=True) == []


def test_scale_down_respects_min_and_tier_coverage(model):
    """The scaler never retires below min_replicas and never retires
    the last replica of a configured tier (breaking disaggregation is
    worse than running one replica over target)."""
    cfg, params = model
    fleet = ServingFleet(
        params, cfg, n_replicas=2, min_replicas=1, max_replicas=2,
        replica_tier=["prefill", "decode"],
        heartbeat_timeout_s=120.0, monitor_interval_s=0.02,
        scale_down_idle_s=0.2, scale_cooldown_s=0.05,
        engine_kw={"max_slots": 2})
    try:
        with fleet._cond:
            live = [i for i in range(fleet.max_replicas)
                    if fleet._state[i] == "live"]
            # both replicas are the last of their tier: no victim
            assert fleet._scale_down_victim_locked(live) is None
    finally:
        fleet.close()


def test_roll_weights_from_sentinel_known_good(model, tmp_path):
    """The continuous-deployment loop: training promotes a known-good
    step (sentinel.json), serving rolls onto it with no argument —
    CRC walk first, rolling swap, every post-rollout response stamped
    with the new version, journal J009-green."""
    cfg, params = model
    ckpt = str(tmp_path / "ckpt")
    jpath = str(tmp_path / "roll.jsonl")
    save_weights(params, ckpt, step=3)
    with open(os.path.join(ckpt, "sentinel.json"), "w") as f:
        json.dump({"known_good": {"step": 3}}, f)
    fleet = ServingFleet(
        params, cfg, n_replicas=2, journal_path=jpath, ckpt_dir=ckpt,
        heartbeat_timeout_s=120.0, monitor_interval_s=0.02,
        engine_kw={"max_slots": 2})
    try:
        p = np.arange(1, 7, dtype=np.int32)
        pre = fleet.submit(p, 4)
        out = pre.result(timeout=300)
        assert pre.weights_version == 0
        rep = fleet.roll_weights()  # no argument: the known-good step
        assert rep["version"] == 3 and rep["previous_version"] == 0
        st = fleet.stats()
        assert st["weights_version"] == 3
        assert st["rollouts_completed"] == 1
        assert all(r["weights_version"] == 3 for r in st["replicas"]
                   if r["state"] == "live"), st
        post = fleet.submit(p, 4)
        np.testing.assert_array_equal(post.result(timeout=300), out)
        assert post.weights_version == 3
    finally:
        fleet.close()
    # version fence on disk: done records carry their assignment's
    # version, and the DFA (incl. J009) stays green
    recs = list(RequestJournal._read(jpath))
    vers = {r["rid"]: r.get("weights_version")
            for r in recs if r["kind"] == "done"}
    assert sorted(vers.values()) == [0, 3], vers
    assert verify_journal(jpath, expect_closed=True) == []


def test_roll_weights_corrupt_candidate_aborts_untouched(model,
                                                         tmp_path):
    """The abort contract: a candidate that fails its CRC walk raises
    RolloutAborted BEFORE any replica is drained — same incarnations,
    old version everywhere, fleet still serving."""
    cfg, params = model
    ckpt = str(tmp_path / "ckpt")
    save_weights(params, ckpt, step=1)
    # corrupt one weight shard of the candidate
    step_dir = os.path.join(ckpt, "step_0000000001")
    victim = sorted(f for f in os.listdir(step_dir)
                    if f.endswith(".npy"))[0]
    with open(os.path.join(step_dir, victim), "r+b") as f:
        f.seek(16)
        f.write(b"\xff\x00\xff\x00")
    fleet = ServingFleet(
        params, cfg, n_replicas=2, ckpt_dir=ckpt,
        heartbeat_timeout_s=120.0, engine_kw={"max_slots": 2})
    try:
        incarnations = [r["incarnation"]
                        for r in fleet.stats()["replicas"]]
        with pytest.raises(RolloutAborted) as ei:
            fleet.roll_weights(ckpt_step=1)
        assert "verification" in str(ei.value)
        st = fleet.stats()
        assert st["rollout_aborts"] == 1 and not st["rollouts_completed"]
        assert st["weights_version"] == 0
        assert [r["incarnation"] for r in st["replicas"]] \
            == incarnations  # nobody was swapped
        assert all(r["weights_version"] == 0 for r in st["replicas"])
        # no known-good promoted at all also aborts (nothing to trust)
        fleet.ckpt_dir = str(tmp_path / "empty")
        os.makedirs(fleet.ckpt_dir, exist_ok=True)
        with pytest.raises(RolloutAborted):
            fleet.roll_weights()
        assert fleet.stats()["rollout_aborts"] == 2
        # still serving, still on version 0
        p = np.arange(1, 5, dtype=np.int32)
        h = fleet.submit(p, 3)
        np.testing.assert_array_equal(h.result(timeout=300),
                                      _oracle(params, cfg, p, 3))
        assert h.weights_version == 0
    finally:
        fleet.close()


def test_rollout_migrate_policy_hedges_in_flight(model, tmp_path):
    """policy='migrate': a swapped replica's in-flight request is
    hedged to a survivor from the journal with token-level resume —
    the output is unchanged and no journaled token is re-decoded."""
    cfg, params = model
    jpath = str(tmp_path / "mig.jsonl")
    fleet = ServingFleet(
        params, cfg, n_replicas=2, journal_path=jpath,
        heartbeat_timeout_s=120.0, monitor_interval_s=0.02,
        engine_kw={"max_slots": 2})
    try:
        p = np.arange(2, 8, dtype=np.int32)
        n = 12
        h = fleet.submit(p, n)
        # wait until some tokens are journaled, then roll mid-decode
        deadline = time.monotonic() + 60.0
        while len(fleet._journal.progress_of(h.rid)) < 2:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        rep = fleet.roll_weights(params=params, version=5,
                                 policy="migrate")
        assert rep["policy"] == "migrate"
        np.testing.assert_array_equal(h.result(timeout=300),
                                      _oracle(params, cfg, p, n))
        st = fleet.stats()
        assert st["weights_version"] == 5
    finally:
        fleet.close()
    _audit_no_redecode(jpath)
    assert verify_journal(jpath, expect_closed=True) == []


def test_operator_scale_down_and_refill_retired(model):
    """scale_down(i) retires a live replica on request (journal-hedge
    + drain); refill() of the retired slot spawns a fresh incarnation
    against the fleet's CURRENT weight version."""
    cfg, params = model
    fleet = ServingFleet(
        params, cfg, n_replicas=2, heartbeat_timeout_s=120.0,
        monitor_interval_s=0.02, engine_kw={"max_slots": 2})
    try:
        assert fleet.scale_down(1)
        deadline = time.monotonic() + 30.0
        while fleet.stats()["replicas"][1]["state"] != "retired":
            assert time.monotonic() < deadline, fleet.stats()
            time.sleep(0.02)
        st = fleet.stats()
        assert st["replicas_retired"] == 1 and st["replicas_live"] == 1
        assert not fleet.scale_down(1)  # already retired: no-op
        fleet.refill(1)
        deadline = time.monotonic() + 30.0
        while fleet.stats()["replicas_live"] < 2:
            assert time.monotonic() < deadline, fleet.stats()
            time.sleep(0.02)
        assert fleet.stats()["replicas"][1]["incarnation"] == 2
        p = np.arange(1, 5, dtype=np.int32)
        np.testing.assert_array_equal(
            fleet.submit(p, 3).result(timeout=300),
            _oracle(params, cfg, p, 3))
    finally:
        fleet.close()


def test_tier_beats_slo_no_migration_ping_pong(model):
    """Tier placement outranks the SLO preference: with the only
    decode-tier replica in a DIFFERENT SLO class, a migrated request
    must still land there (tier filter first, SLO preference within)
    — narrowing by SLO first would bounce the migration between
    prefill replicas forever, re-prefilling the growing prefix on
    every hop (review round-3 repro)."""
    cfg, params = model
    fleet = ServingFleet(
        params, cfg, n_replicas=3,
        replica_tier=["prefill", "prefill", "decode"],
        replica_slo=["interactive", "interactive", "batch"],
        heartbeat_timeout_s=120.0, monitor_interval_s=0.02,
        engine_kw={"max_slots": 2})
    try:
        p = np.arange(1, 7, dtype=np.int32)
        h = fleet.submit(p, 8, slo="interactive")
        np.testing.assert_array_equal(h.result(timeout=300),
                                      _oracle(params, cfg, p, 8))
        st = fleet.stats()
        # exactly one hop: prefill tier -> the (batch-class) decode
        # replica; a ping-pong would inflate this towards max_new
        assert st["migrations"] == 1, st
        assert st["resubmitted"] == 1, st
        assert h.replica == "r2", h.replica
    finally:
        fleet.close()


def test_roll_weights_refuses_foreign_checkpoint(model, tmp_path):
    """A raw training save_checkpoint scope (arbitrary entry names) is
    refused at load with a message naming the REAL mismatch — publish
    serving weight sets with save_weights — never a silent misload or
    a misleading leaf-count complaint."""
    from paddle_tpu.distributed.checkpoint import save_checkpoint

    cfg, params = model
    ckpt = str(tmp_path / "ckpt")

    class _Scope(object):
        def __init__(self, arrays):
            self._arrays = arrays

        def keys(self):
            return self._arrays.keys()

        def get(self, name):
            return self._arrays[name]

    save_checkpoint(_Scope({"fc_0.w_0": np.ones((4, 4), np.float32)}),
                    ckpt, step=1)
    fleet = ServingFleet(params, cfg, n_replicas=1, ckpt_dir=ckpt,
                         heartbeat_timeout_s=120.0,
                         engine_kw={"max_slots": 2})
    try:
        with pytest.raises(RolloutAborted, match="save_weights"):
            fleet.roll_weights(ckpt_step=1)
        st = fleet.stats()
        assert st["rollout_aborts"] == 1
        assert st["weights_version"] == 0
    finally:
        fleet.close()


def test_elastic_knob_validation(model):
    """Loud constructor errors: bound ordering, tier names, per-slot
    list lengths, rollout policy."""
    cfg, params = model
    with pytest.raises(ValueError, match="min_replicas"):
        ServingFleet(params, cfg, n_replicas=2, min_replicas=3)
    with pytest.raises(ValueError, match="max_replicas"):
        ServingFleet(params, cfg, n_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="unknown tier"):
        ServingFleet(params, cfg, n_replicas=1,
                     replica_tier=["verify"])
    with pytest.raises(ValueError, match="per SLOT"):
        ServingFleet(params, cfg, n_replicas=1, max_replicas=2,
                     replica_tier=["prefill"])
    with pytest.raises(ValueError, match="rollout_policy"):
        ServingFleet(params, cfg, n_replicas=1,
                     rollout_policy="yolo")
    fleet = ServingFleet(params, cfg, n_replicas=1,
                         heartbeat_timeout_s=120.0,
                         engine_kw={"max_slots": 2})
    try:
        with pytest.raises(ValueError, match="ckpt_dir"):
            fleet.roll_weights()  # no ckpt_dir, no params=
        with pytest.raises(ValueError, match="policy"):
            fleet.roll_weights(params=params, policy="yolo")
    finally:
        fleet.close()
