"""fluid.optimizer.ModelAverage: in-graph sliding-window parameter
averaging with apply/restore swap (reference
parameter/AverageOptimizer.cpp — the exact sum_1/sum_2/sum_3 window
algorithm, verified against a numpy oracle)."""

import os

import numpy as np

import paddle_tpu.fluid as fluid


def _build(rate=0.25, min_w=5, max_w=10000):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(x=fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        ma = fluid.optimizer.ModelAverage(
            average_window=rate, min_average_window=min_w,
            max_average_window=max_w,
        ).build(main)
    return main, startup, loss, ma


def _oracle_average(history, rate, min_w, max_w, k_max=16384):
    """Numpy oracle of AverageOptimizer.cpp:60-115: returns the value
    apply() must produce after training through `history` iterates."""
    z = np.zeros_like(history[0], dtype=np.float64)
    s1, s2, s3 = z.copy(), z.copy(), z.copy()
    na = ona = nu = 0
    for h in history:
        nu += 1
        na += 1
        s1 = s1 + h
        if nu % k_max == 0:
            s2, s1 = s2 + s1, z.copy()
        if na >= min_w and na >= min(max_w, nu * rate):
            s3, s1, s2 = s1 + s2, z.copy(), z.copy()
            ona, na = na, 0
    return (s1 + s2 + s3) / (na + ona)


def test_average_tracks_params_and_applies():
    rate, min_w, max_w = 0.25, 5, 10000
    main, startup, loss, ma = _build(rate, min_w, max_w)
    rng = np.random.RandomState(0)
    W = rng.randn(4, 1).astype(np.float32)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        w_name = main.global_block().all_parameters()[0].name
        history = []
        for _ in range(60):
            xv = rng.randn(16, 4).astype(np.float32)
            yv = (xv @ W).astype(np.float32)
            exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
            history.append(np.asarray(scope.get(w_name)).copy())

        live = np.asarray(scope.get(w_name)).copy()
        steps = float(np.ravel(np.asarray(
            scope.get(ma._steps_name)))[0])
        assert steps == 60.0

        with ma.apply(scope=scope):
            applied = np.asarray(scope.get(w_name)).copy()
        restored = np.asarray(scope.get(w_name))

        # restore puts the live weights back exactly
        np.testing.assert_array_equal(restored, live)
        # the applied value matches the reference window algorithm
        # (60 steps at rate 0.25 crosses several window shifts, so the
        # sum_3 path and counter resets are all exercised)
        want = _oracle_average(history, rate, min_w, max_w)
        np.testing.assert_allclose(applied, want, rtol=1e-4, atol=1e-5)
        # and it differs from the raw last iterate (it is an average)
        assert not np.allclose(applied, live)


def test_average_window_shifts_bound_history():
    """The averaged value reflects only the last [W, 2W] iterates: with
    rate=1.0 (window == num_updates, never shifts) the average equals
    the full-history mean; with a small max window it must NOT."""
    rate, min_w, max_w = 1.0, 1, 10 ** 9
    main, startup, loss, ma = _build(rate, min_w, max_w)
    rng = np.random.RandomState(1)
    W = rng.randn(4, 1).astype(np.float32)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        w_name = main.global_block().all_parameters()[0].name
        history = []
        for _ in range(30):
            xv = rng.randn(16, 4).astype(np.float32)
            yv = (xv @ W).astype(np.float32)
            exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
            history.append(np.asarray(scope.get(w_name)).copy())
        with ma.apply(scope=scope):
            applied = np.asarray(scope.get(w_name)).copy()
    # rate=1.0: na >= nu*1.0 holds every step, so the window shifts
    # each step — oracle confirms, and the oracle IS the reference
    want = _oracle_average(history, rate, min_w, max_w)
    np.testing.assert_allclose(applied, want, rtol=1e-4, atol=1e-5)


def test_average_window_mapping():
    from paddle_tpu.fluid.optimizer import ModelAverage

    ma = ModelAverage(average_window=0.5, max_average_window=1000)
    assert ma.average_window == 0.5
    assert ma.max_average_window == 1000
    assert ma.min_average_window == 100  # default
    ma2 = ModelAverage.from_spec(
        type("S", (), {"average_window": 0.05, "max_average_window": 500})()
    )
    assert ma2.average_window == 0.05
    # reference: minAverageWindow = min(10000, max_average_window)
    assert ma2.min_average_window == 500


def test_averaged_eval_loss_is_sane():
    """Evaluating under ma.apply() on a noisy-SGD run: the averaged
    weights' loss is finite and close to (or better than) the live
    weights' on the true relation."""
    main, startup, loss, ma = _build(rate=0.3, min_w=3)
    infer = None
    rng = np.random.RandomState(3)
    W = rng.randn(4, 1).astype(np.float32)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        for _ in range(80):
            xv = rng.randn(8, 4).astype(np.float32)
            yv = (xv @ W + 0.3 * rng.randn(8, 1)).astype(np.float32)
            exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])

        xv = rng.randn(64, 4).astype(np.float32)
        yv = (xv @ W).astype(np.float32)

        def eval_loss():
            return float(np.ravel(exe.run(
                main, feed={"x": xv, "y": yv}, fetch_list=[loss]
            )[0])[0])

        # NOTE eval_loss() runs a TRAIN step (mutates params slightly);
        # good enough to compare magnitudes
        with ma.apply(scope=scope):
            avg_loss = eval_loss()
        assert np.isfinite(avg_loss) and avg_loss < 1.0


def test_opt_out_and_premature_apply():
    import pytest

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(
            input=x, size=3,
            param_attr=fluid.ParamAttr(do_model_average=False),
        )
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(x=fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        ma = fluid.optimizer.ModelAverage(average_window=200).build(main)

    # opted-out param has no avg slot
    opted_out = [
        p.name for p in main.global_block().all_parameters()
        if getattr(p, "do_model_average", None) is False
    ]
    assert opted_out and all(n not in ma._param_names for n in opted_out)
    assert len(ma._param_names) >= 1

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        with pytest.raises(RuntimeError, match="before any training"):
            with ma.apply(scope=scope):
                pass

    # build outside the right guard is rejected
    with pytest.raises(ValueError, match="program_guard"):
        fluid.optimizer.ModelAverage().build(main)


def test_v2_trainer_model_average():
    """v2 surface: optimizer(model_average=ModelAverage(...)) makes
    test() and save_parameter_to_tar run on averaged weights."""
    import io as _io

    import paddle_tpu.v2 as paddle
    from paddle_tpu.v2.optimizer import ModelAverage as V2MA

    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1,
                           act=paddle.activation.Linear())
    cost = paddle.layer.mse_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    opt = paddle.optimizer.Momentum(
        momentum=0.9, learning_rate=5e-2,
        model_average=V2MA(average_window=0.05, max_average_window=500),
    )
    trainer = paddle.trainer.SGD(cost=cost, parameters=params,
                                 update_equation=opt)
    assert trainer._model_average is not None
    assert trainer._model_average.average_window == 0.05
    # reference-derived min window: min(10000, max_average_window=500)
    assert trainer._model_average.min_average_window == 500

    rng = np.random.RandomState(0)
    W = rng.randn(4, 1).astype(np.float32)

    def reader():
        for _ in range(40):
            xv = rng.randn(4).astype(np.float32)
            yield xv, (xv @ W).astype(np.float32)

    # eval/export BEFORE any training falls back to live weights
    pre = trainer.test(paddle.batch(reader, 8))
    assert np.isfinite(pre.cost)

    trainer.train(paddle.batch(reader, 8), num_passes=3)

    # test() runs on averages and restores live weights afterwards
    w_name = trainer._topology.main_program.global_block().all_parameters()[0].name
    live = np.asarray(params.scope.get(w_name)).copy()
    res = trainer.test(paddle.batch(reader, 8))
    np.testing.assert_array_equal(
        np.asarray(params.scope.get(w_name)), live
    )
    assert np.isfinite(res.cost)

    # the exported tar carries the averaged weights, not the live ones
    buf = _io.BytesIO()
    trainer.save_parameter_to_tar(buf)
    buf.seek(0)
    loaded = paddle.parameters.Parameters.from_tar(buf)
    avg_name = w_name + fluid.optimizer.ModelAverage.SUM_SUFFIXES[0]
    assert avg_name in params.scope.keys()  # the sum slot trains along
    exported = loaded.get(w_name)
    assert not np.allclose(exported, live)  # averaged, not last iterate


def test_cli_settings_model_average_slots_in_checkpoint(tmp_path):
    """settings(model_average=...) through the CLI: EMA slots train
    along and land in the per-pass checkpoint."""
    import textwrap

    from paddle_tpu.trainer import run_config
    from paddle_tpu.distributed import checkpoint as ckpt

    cfg = tmp_path / "cfg.py"
    cfg.write_text(textwrap.dedent("""
        settings(batch_size=8, learning_rate=0.1,
                 learning_method=MomentumOptimizer(),
                 model_average=ModelAverage(average_window=0.05,
                                            max_average_window=200))
        x = data_layer(name='x', size=4)
        y = data_layer(name='y', size=2)
        p = fc_layer(input=x, size=2, act=SoftmaxActivation())
        outputs(classification_cost(input=p, label=y))
    """))
    save = str(tmp_path / "ck")
    out = run_config(str(cfg), num_passes=1, save_dir=save)
    assert np.isfinite(out["cost"])

    scope = fluid.Scope()
    got = ckpt.load_checkpoint(scope, os.path.join(save, "pass-00000"))
    avg_keys = [k for k in scope.keys() if k.endswith("@SUM_1")]
    assert avg_keys, sorted(scope.keys())
    steps = [k for k in scope.keys() if "model_average_steps" in k]
    assert steps and float(np.ravel(np.asarray(scope.get(steps[0])))[0]) > 0

    # --job=test on that checkpoint evaluates the AVERAGED weights
    out_t = run_config(
        str(cfg), job="test", num_passes=1,
        init_model_path=os.path.join(save, "pass-00000"),
    )
    assert np.isfinite(out_t["cost"])

