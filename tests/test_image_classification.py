"""Book test: CIFAR image classification — VGG16-BN and ResNet towers.

Parity with reference python/paddle/v2/fluid/tests/book/
test_image_classification.py: vgg16_bn_drop (nets.img_conv_group with
batchnorm+dropout) and resnet_cifar10 (conv_bn basicblocks with
elementwise_add shortcuts), trained with Adam, eval via a
clone(for_test=True) program. CIFAR is replaced by synthetic separable
images; the resnet depth is reduced for CI speed."""

import numpy as np

import paddle_tpu.fluid as fluid

pd = fluid.layers

CLASSDIM = 10
DATA_SHAPE = [3, 32, 32]


def resnet_cifar10(input, depth=8):
    def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu"):
        tmp = pd.conv2d(
            input=input,
            filter_size=filter_size,
            num_filters=ch_out,
            stride=stride,
            padding=padding,
            act=None,
            bias_attr=False,
        )
        return pd.batch_norm(input=tmp, act=act)

    def shortcut(input, ch_in, ch_out, stride):
        if ch_in != ch_out:
            return conv_bn_layer(input, ch_out, 1, stride, 0, None)
        return input

    def basicblock(input, ch_in, ch_out, stride):
        tmp = conv_bn_layer(input, ch_out, 3, stride, 1)
        tmp = conv_bn_layer(tmp, ch_out, 3, 1, 1, act=None)
        short = shortcut(input, ch_in, ch_out, stride)
        return pd.elementwise_add(x=tmp, y=short, act="relu")

    def layer_warp(block_func, input, ch_in, ch_out, count, stride):
        tmp = block_func(input, ch_in, ch_out, stride)
        for _ in range(1, count):
            tmp = block_func(tmp, ch_out, ch_out, 1)
        return tmp

    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input=input, ch_out=16, filter_size=3, stride=1, padding=1)
    res1 = layer_warp(basicblock, conv1, 16, 16, n, 1)
    res2 = layer_warp(basicblock, res1, 16, 32, n, 2)
    res3 = layer_warp(basicblock, res2, 32, 64, n, 2)
    pool = pd.pool2d(input=res3, pool_size=8, pool_type="avg", pool_stride=1)
    return pool


def vgg_bn_drop(input):
    """Book vgg16_bn_drop with fewer filters (same structure) for CI."""

    def conv_block(input, num_filter, groups, dropouts):
        return fluid.nets.img_conv_group(
            input=input,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * groups,
            conv_filter_size=3,
            conv_act="relu",
            conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts,
            pool_type="max",
        )

    conv1 = conv_block(input, 16, 2, [0.3, 0])
    conv2 = conv_block(conv1, 32, 2, [0.4, 0])
    drop = pd.dropout(x=conv2, dropout_prob=0.5)
    fc1 = pd.fc(input=drop, size=64, act=None)
    bn = pd.batch_norm(input=fc1, act="relu")
    drop2 = pd.dropout(x=bn, dropout_prob=0.5)
    fc2 = pd.fc(input=drop2, size=64, act=None)
    return fc2


def synthetic_cifar(rng, n):
    """Class-separable images: class k has mean intensity k/CLASSDIM in a
    class-specific channel pattern."""
    labels = rng.randint(0, CLASSDIM, (n, 1)).astype(np.int64)
    imgs = rng.randn(n, *DATA_SHAPE).astype(np.float32) * 0.2
    for i, lab in enumerate(labels[:, 0]):
        imgs[i, lab % 3] += (lab + 1) / CLASSDIM
    return imgs, labels


def _run(net_type, steps, batch):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        images = pd.data(name="pixel", shape=DATA_SHAPE, dtype="float32")
        label = pd.data(name="label", shape=[1], dtype="int64")
        if net_type == "vgg":
            net = vgg_bn_drop(images)
        else:
            net = resnet_cifar10(images, 8)
        predict = pd.fc(input=net, size=CLASSDIM, act="softmax")
        cost = pd.cross_entropy(input=predict, label=label)
        avg_cost = pd.mean(x=cost)
        acc = pd.accuracy(input=predict, label=label)
        test_program = main.clone(for_test=True)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    imgs, labels = synthetic_cifar(rng, batch)
    losses = []
    for _ in range(steps):
        c, a = exe.run(
            main, feed={"pixel": imgs, "label": labels}, fetch_list=[avg_cost, acc]
        )
        losses.append(float(np.ravel(c)[0]))
    assert np.isfinite(losses).all(), losses
    # eval through the for_test clone (BN uses running stats, dropout off)
    c1, a1 = exe.run(
        test_program, feed={"pixel": imgs, "label": labels},
        fetch_list=[avg_cost, acc],
    )
    c2, _ = exe.run(
        test_program, feed={"pixel": imgs, "label": labels},
        fetch_list=[avg_cost, acc],
    )
    assert np.allclose(c1, c2), "for_test clone must be deterministic"
    return losses


def test_resnet():
    losses = _run("resnet", steps=12, batch=16)
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_vgg():
    losses = _run("vgg", steps=4, batch=8)


def test_vgg19_builder_graph():
    """The zoo's vgg19 (reference IntelOptimizedPaddle.md benches
    VGG-19) must emit the 16-conv layout (2+2+4+4+4) vs vgg16's 13
    (2+2+3+3+3) — graph-level check, no execution (224x224 is too
    heavy for CI)."""
    from paddle_tpu.models.vgg import vgg16, vgg19

    def conv_count(model_fn):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = pd.data(name="image", shape=[3, 224, 224],
                          dtype="float32")
            pred = model_fn(img, 1000)
        ops = [op.type for op in main.global_block().ops]
        assert pred.shape[-1] == 1000
        return sum(1 for t in ops if t == "conv2d")

    assert conv_count(vgg19) == 16
    assert conv_count(vgg16) == 13
