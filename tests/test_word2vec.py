"""Book test: word2vec N-gram model + inference-model round trip.

Parity with reference python/paddle/v2/fluid/tests/book/test_word2vec.py:
four context-word embeddings (shared 'shared_w' param), concat -> fc ->
softmax, trained with SGD; then save_inference_model/load_inference_model
and an inference run. imikolov is replaced by a synthetic corpus."""

import os
import tempfile

import numpy as np

import paddle_tpu.fluid as fluid

pd = fluid.layers

DICT_SIZE = 50
EMBED_SIZE = 16
HIDDEN_SIZE = 64
N = 5
BATCH = 32


def network(words):
    embs = []
    for i, w in enumerate(words):
        embs.append(
            pd.embedding(
                input=w,
                size=[DICT_SIZE, EMBED_SIZE],
                dtype="float32",
                param_attr="shared_w",
            )
        )
    concat_embed = pd.concat(input=embs, axis=1)
    hidden1 = pd.fc(input=concat_embed, size=HIDDEN_SIZE, act="sigmoid")
    predict_word = pd.fc(input=hidden1, size=DICT_SIZE, act="softmax")
    return predict_word


def synthetic_ngrams(rng, n):
    """Deterministic structure: next word = (sum of context) % DICT_SIZE."""
    ctx = rng.randint(0, DICT_SIZE, (n, N - 1))
    nxt = ctx.sum(axis=1) % DICT_SIZE
    return ctx.astype(np.int64), nxt.reshape(-1, 1).astype(np.int64)


def test_train_and_infer_roundtrip():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        first = pd.data(name="firstw", shape=[1], dtype="int64")
        second = pd.data(name="secondw", shape=[1], dtype="int64")
        third = pd.data(name="thirdw", shape=[1], dtype="int64")
        forth = pd.data(name="forthw", shape=[1], dtype="int64")
        next_word = pd.data(name="nextw", shape=[1], dtype="int64")
        predict_word = network([first, second, third, forth])
        cost = pd.cross_entropy(input=predict_word, label=next_word)
        avg_cost = pd.mean(x=cost)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    ctx, nxt = synthetic_ngrams(rng, BATCH)
    feed = {
        "firstw": ctx[:, 0:1],
        "secondw": ctx[:, 1:2],
        "thirdw": ctx[:, 2:3],
        "forthw": ctx[:, 3:4],
        "nextw": nxt,
    }
    losses = []
    for _ in range(30):
        (c,) = exe.run(main, feed=feed, fetch_list=[avg_cost])
        losses.append(float(np.ravel(c)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], (losses[0], losses[-1])

    # ---- save_inference_model / load_inference_model round trip --------
    with tempfile.TemporaryDirectory() as d:
        fluid.io.save_inference_model(
            d,
            ["firstw", "secondw", "thirdw", "forthw"],
            [predict_word],
            exe,
            main_program=main,
        )
        (
            inference_program,
            feed_target_names,
            fetch_targets,
        ) = fluid.io.load_inference_model(d, exe)
        assert feed_target_names == ["firstw", "secondw", "thirdw", "forthw"]
        (probs,) = exe.run(
            inference_program,
            feed={
                feed_target_names[0]: ctx[:1, 0:1],
                feed_target_names[1]: ctx[:1, 1:2],
                feed_target_names[2]: ctx[:1, 2:3],
                feed_target_names[3]: ctx[:1, 3:4],
            },
            fetch_list=fetch_targets,
        )
        assert probs.shape == (1, DICT_SIZE)
        assert np.isclose(probs.sum(), 1.0, atol=1e-4)

        # same feed through the training program's forward gives same probs
        (train_probs,) = exe.run(
            main,
            feed={
                "firstw": ctx[:1, 0:1],
                "secondw": ctx[:1, 1:2],
                "thirdw": ctx[:1, 2:3],
                "forthw": ctx[:1, 3:4],
                "nextw": nxt[:1],
            },
            fetch_list=[predict_word],
        )
        assert np.allclose(probs, train_probs, atol=1e-5)
