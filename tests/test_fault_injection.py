"""Fault-injection fixture (SURVEY 5.3: injectable preemptions make
recovery CI-testable): spec parsing, in-process faults, checkpoint
corruption, and an end-to-end CLI preemption + resume."""

import os
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.distributed import fault_injection as fi


def test_spec_parsing_and_exc():
    inj = fi.FaultInjector("exc@3")
    inj.tick()
    inj.tick()
    with pytest.raises(fi.FaultInjected):
        inj.tick()


def test_arm_adds_relative_faults_mid_run():
    """arm() schedules faults relative to the CURRENT step: drills warm
    up under no faults, then land one at a deterministic step of the
    measured phase (serving-fleet zombie drill)."""
    inj = fi.FaultInjector("")
    assert not inj.active
    for _ in range(5):
        inj.tick()
    inj.arm("exc@2")
    assert inj.active
    inj.tick()  # step 6
    with pytest.raises(fi.FaultInjected):
        inj.tick()  # step 7 == 5 + 2
    # absolute arming keeps the spec's raw indices
    inj2 = fi.FaultInjector("")
    inj2.tick()
    inj2.arm("exc@2", relative=False)
    with pytest.raises(fi.FaultInjected):
        inj2.tick()  # step 2


def test_delay_fault_sleeps():
    import time

    inj = fi.FaultInjector("delay@1:0.2")
    t0 = time.time()
    inj.tick()
    assert time.time() - t0 >= 0.2


def test_corrupt_file_flips_bytes(tmp_path):
    p = tmp_path / "blob.bin"
    p.write_bytes(b"abcdefgh")
    fi.corrupt_file(str(p), offset=-4)
    raw = p.read_bytes()
    assert raw[:4] == b"abcd" and raw[4] != ord("e")


def test_corrupt_fault_breaks_checkpoint_crc(tmp_path):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.distributed import checkpoint as ckpt

    scope = fluid.executor.Scope()
    scope.set("w", np.arange(8, dtype=np.float32))
    d = str(tmp_path / "ck")
    ckpt.save_checkpoint(scope, d, step=1)
    import glob

    (npy,) = glob.glob(os.path.join(d, "step_*", "w*.npy"))
    inj = fi.FaultInjector("corrupt@2:%s" % npy)
    inj.tick()
    inj.tick()  # fires: flips checkpoint bytes
    with pytest.raises((IOError, ValueError)):
        ckpt.load_checkpoint(fluid.executor.Scope(), d)


def test_netsplit_fault_opens_window_and_drops_connections():
    import time

    from paddle_tpu.distributed import (
        Coordinator, CoordinatorServer, RemoteCoordinator,
    )

    assert not fi.netsplit_active()
    server = CoordinatorServer(Coordinator()).start()
    try:
        cli = RemoteCoordinator(server.address, retry_deadline_s=5.0,
                                backoff_base_s=0.02)
        assert cli.ping() == "pong"
        inj = fi.FaultInjector("netsplit@1:0.4")
        inj.tick()
        assert fi.netsplit_active()
        # the partition drops the live connection; the call must ride it
        # out on backoff and land AFTER the window closes
        t0 = time.monotonic()
        assert cli.ping() == "pong"
        assert time.monotonic() - t0 >= 0.2
        assert not fi.netsplit_active()
        cli.close()
    finally:
        server.stop()


def test_slow_fault_gray_window():
    """slow@N:dur[/per] (ISSUE 8): from step N every tick COMPLETES
    but stalls `per` seconds, until `dur` wall-seconds pass — a gray
    failure: liveness checks see progress, latency targets die. The
    deterministic driver for the serving fleet's demotion drills."""
    import time

    inj = fi.FaultInjector("slow@2:0.4/0.08")
    t0 = time.monotonic()
    inj.tick()  # before the window: fast
    assert time.monotonic() - t0 < 0.05 and not inj.slowed
    t1 = time.monotonic()
    inj.tick()  # window opens: this tick already stalls
    inj.tick()
    assert inj.slowed and time.monotonic() - t1 >= 0.16
    time.sleep(0.4)
    assert not inj.slowed  # window closed: healthy again
    t2 = time.monotonic()
    inj.tick()
    assert time.monotonic() - t2 < 0.05
    # a bad dur/per fails at parse time, not N steps later — including
    # signs (time.sleep(-x) would crash the serving step mid-drill)
    with pytest.raises(ValueError):
        fi.FaultInjector("slow@2:forever")
    with pytest.raises(ValueError):
        fi.FaultInjector("slow@2:1.0/x")
    with pytest.raises(ValueError):
        fi.FaultInjector("slow@2:-1.0")
    with pytest.raises(ValueError):
        fi.FaultInjector("slow@2:1.0/-0.1")


def test_garble_fault_sticky_silent():
    """garble@N (ISSUE 15): SILENT and STICKY — from step N on the
    consuming serving engine perturbs every emitted token to a
    wrong-but-finite vocab id (a faulty core keeps computing wrong).
    The injector itself never raises, sleeps, or kills: only a
    known-answer canary mismatch can see this fault."""
    inj = fi.FaultInjector("garble@2")
    inj.tick()
    assert not inj.garbled
    inj.tick()
    assert inj.garbled
    for _ in range(5):
        inj.tick()
    assert inj.garbled  # sticky until the incarnation is replaced
    # a fresh injector (the quarantine's replacement engine) is clean
    assert not fi.FaultInjector("").garbled


def test_flip_fault_pending_until_consumed():
    """flip@N (ISSUE 15): armed at step N, consumed ONCE by the
    engine's take_flip() — and re-armable (rearm_flip) when nothing
    was resident to corrupt, so the fault lands on the first real
    block instead of evaporating on an idle engine."""
    inj = fi.FaultInjector("flip@2")
    inj.tick()
    assert not inj.take_flip()
    inj.tick()
    assert inj.take_flip()
    assert not inj.take_flip()  # one-shot: consumed
    inj.rearm_flip()            # nothing resident: engine re-arms
    assert inj.take_flip()
    inj.tick()
    assert not inj.take_flip()  # later steps do not re-fire


def test_hang_and_netsplit_spec_parsing():
    # hang parses (do NOT tick to its step — it spins forever)
    inj = fi.FaultInjector("hang@7")
    for _ in range(6):
        inj.tick()
    assert inj.step == 6
    # a bad netsplit duration fails at parse time, not N steps later
    with pytest.raises(ValueError):
        fi.FaultInjector("netsplit@2:forever")
    with pytest.raises(ValueError):
        fi.FaultInjector("sploit@2")


def test_cli_preemption_and_resume(tmp_path):
    """PADDLE_FAULT=kill@N preempts the REAL trainer CLI mid-pass; the
    per-pass checkpoint from the completed pass resumes cleanly."""
    cfg = tmp_path / "cfg.py"
    cfg.write_text(textwrap.dedent("""
        settings(batch_size=8, learning_rate=0.1,
                 learning_method=MomentumOptimizer())
        x = data_layer(name='x', size=4)
        y = data_layer(name='y', size=2)
        p = fc_layer(input=x, size=2, act=SoftmaxActivation())
        outputs(classification_cost(input=p, label=y))
    """))
    save = str(tmp_path / "ckpt")
    env = dict(os.environ)
    # 32 batches/pass; pass 1's save (batch 64) JOINS pass 0's async
    # writer first, so by batch 65 pass-00000 is committed — killing at
    # batch 70 (mid pass 3) is deterministic, where a kill landing
    # before the first join point raced the background writer and
    # sometimes found NO committed pass at all (flake under load)
    env["PADDLE_FAULT"] = "kill@70"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable, "-m", "paddle_tpu.trainer", "--job=train",
            "--config=%s" % cfg, "--num_passes=4", "--log_period=8",
            "--save_dir=%s" % save, "--saving_period=1",
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == -signal.SIGKILL, (
        proc.returncode, proc.stdout[-500:], proc.stderr[-500:],
    )
    # pass 0's async save must be COMMITTED (not just the dir created):
    # only resume from a pass whose checkpoint actually loads — the
    # SIGKILL may land while a later pass's writer is mid-commit
    from paddle_tpu.distributed import checkpoint as ckpt

    passes = sorted(d for d in os.listdir(save) if d.startswith("pass-"))
    assert "pass-00000" in passes, passes
    committed = [
        p for p in passes
        if ckpt.latest_step(os.path.join(save, p)) is not None
    ]
    assert committed, passes

    from paddle_tpu.trainer import run_config

    out = run_config(
        str(cfg), num_passes=1,
        init_model_path=os.path.join(save, committed[-1]),
    )
    assert np.isfinite(out["cost"])


def test_store_fault_spec_parsing():
    """store_corrupt@N / store_trunc@N (ISSUE 16) parse like any other
    kind — and a typo'd store kind fails at parse time."""
    inj = fi.FaultInjector("store_corrupt@2")
    assert inj.active
    inj2 = fi.FaultInjector("store_trunc@1")
    assert inj2.active
    with pytest.raises(ValueError):
        fi.FaultInjector("store_smudge@2")


def test_store_fault_counts_records_not_steps():
    """Store faults fire on the Nth PUT (store_tick), one-shot, and
    are invisible to the step clock — tick() never consumes them."""
    inj = fi.FaultInjector("store_corrupt@2")
    for _ in range(10):
        inj.tick()  # steps do not advance the store counter
    assert inj.store_tick() is None          # record 1
    assert inj.store_tick() == "corrupt"     # record 2: fires
    assert inj.store_tick() is None          # one-shot: consumed
    inj2 = fi.FaultInjector("store_trunc@1")
    assert inj2.store_tick() == "trunc"


def test_store_fault_arm_is_relative_to_record_counter():
    """arm() shifts store faults by the RECORD counter, not the step
    counter: a drill warms the store under no faults, then lands the
    fault on a deterministic upcoming record."""
    inj = fi.FaultInjector("")
    for _ in range(7):
        inj.tick()          # step clock way ahead
    assert inj.store_tick() is None
    assert inj.store_tick() is None          # 2 records spilled
    inj.arm("store_trunc@2")                 # 2 records from NOW
    assert inj.store_tick() is None          # record 3
    assert inj.store_tick() == "trunc"       # record 4 == 2 + 2
    assert inj.store_tick() is None
