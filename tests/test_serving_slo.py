"""Gray-failure tolerance + request-SLO layer (ISSUE 8,
paddle_tpu/serving — fleet.py/engine.py, distributed/fault_injection.py):

* Per-request deadlines — journaled with the spec, enforced at every
  queue hop (submit, routing, prefill chunk, decode); expiry is a
  terminal journal VERDICT (`expired`), surfaced as `DeadlineExceeded`,
  and the scheduler stops spending decode steps the moment the budget
  dies. A deadline dead on arrival is refused BEFORE the
  `FleetSaturated` shed (overload metrics never absorb client-side
  lateness — the ISSUE 8 fix).
* Token-level resume — emitted tokens are journaled incrementally
  (batched, flush-deferred); failover/demotion resubmits
  prompt + emitted to survivors, which prefill (aliasing what the pool
  holds) and re-decode ZERO already-emitted tokens, with the sampling
  key schedule continued at the resume index — outputs token-identical
  to an uninterrupted run, greedy and sampled.
* Gray-failure demotion — a replica that heartbeats but stalls
  (slow@N:dur fault: every step completes, late) is demoted on a
  step-latency-EWMA health score with hysteresis, its work hedged to
  survivors, then probed and RESTORED under the same incarnation (warm
  pool, no fresh spawn); a single transient pause must not flap it.
* Chaos drill matrix — exc/delay/slow faults against the fleet, all
  holding the journal invariant: after close, every journaled rid is
  terminal (done / rejected / expired), never silently open.
* Journal compaction — the file rewrites down to meta + the open set
  on the rotation threshold; recover()/reopen see identical state.
"""

import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.distributed.fault_injection import FaultInjector
from paddle_tpu.models import transformer as T
from paddle_tpu.serving import (
    DeadlineExceeded,
    FleetSaturated,
    FleetTimeout,
    RequestJournal,
    ServingEngine,
    ServingFleet,
)


@pytest.fixture(scope="module")
def model():
    cfg = T.TransformerConfig(vocab=64, dim=32, heads=4, layers=2,
                              max_len=64)
    return cfg, T.init_params(cfg, jax.random.PRNGKey(0))


def _oracle(params, cfg, prompt, max_new):
    return np.asarray(
        T.generate(params, jnp.asarray(prompt)[None], cfg, max_new)
    )[0]


def _requests(cfg, n=5, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        t = int(rng.randint(4, 13))
        out.append((rng.randint(0, cfg.vocab, (t,)).astype(np.int32),
                    int(rng.randint(8, 13))))
    return out


def _warm_all_buckets(fleet, cfg, n_replicas=2):
    """Compile every shape the drills can hit on EVERY replica before
    any fault is armed or any health judgement runs (the README sizing
    rule: a first compile is one long silent step, indistinguishable
    from gray slowness from outside). _requests prompts are 4..12
    tokens -> pow-2 prefill buckets 8 and 16; one wave per bucket,
    n_replicas concurrent requests each, spread by least-loaded
    routing."""
    for L in (8, 16):
        ws = [fleet.submit(np.arange(1, L + 1, dtype=np.int32), 4,
                           seed=k) for k in range(n_replicas)]
        for h in ws:
            h.result(timeout=180)
    time.sleep(0.3)  # EWMAs settle post-compile


# ---------------------------------------------------------------------------
# engine: deadlines, resume, cancel
# (the slow@ fault kind itself is pinned in test_fault_injection.py)
# ---------------------------------------------------------------------------

def test_engine_expires_at_every_hop_and_stops_decoding(model):
    """A queued request with a spent deadline expires before admission;
    a decoding one expires before the next batched step — and the
    engine stops spending decode steps on it (the counter freezes)."""
    cfg, params = model
    eng = ServingEngine(params, cfg, max_slots=1)
    # queued expiry: deadline already dead at the first step
    h = eng.submit(np.arange(1, 6, dtype=np.int32), 10,
                   deadline_at=time.monotonic() - 1.0)
    eng.step()
    assert h.done and h.finish_reason == "expired" and h.tokens == []
    assert eng.metrics.expired == 1
    # decode expiry: budget dies mid-generation
    h2 = eng.submit(np.arange(1, 6, dtype=np.int32), 50,
                    deadline_at=time.monotonic() + 0.2)
    while not h2.done:
        assert eng.step()
    assert h2.finish_reason == "expired"
    assert 0 < len(h2.tokens) < 50  # partial verdict, not silent hang
    steps_at_expiry = eng.metrics.decode_steps
    assert not eng.step()  # nothing left: no decode steps spent on it
    assert eng.metrics.decode_steps == steps_at_expiry
    assert eng.metrics.expired == 2
    assert eng.kv_blocks_in_use == 0  # expiry freed the slot's blocks


@pytest.mark.slow  # 5 engine builds; greedy resume identity is pinned
                   # tier-1 by the serving_slo bench contract
def test_engine_token_level_resume_identity_greedy_and_sampled(model):
    """Resume = prompt + emitted as prefill context, key schedule
    continued at the resume index: outputs are token-identical to the
    uninterrupted run and the resumed engine decodes ONLY the
    remainder (re-decode zero, by construction and by counter)."""
    cfg, params = model
    p = np.arange(1, 10, dtype=np.int32)
    # (temperature, seed, resume cuts): greedy exercises the early and
    # the maximal cut, sampled pins the fold_in schedule continuation
    for temp, seed, cuts in ((0.0, 0, (1, 7)), (0.9, 7, (3,))):
        eng = ServingEngine(params, cfg, max_slots=2)
        full = eng.submit(p, 8, temperature=temp, seed=seed).result()
        for cut in cuts:
            eng2 = ServingEngine(params, cfg, max_slots=2)
            resume = list(full[len(p):len(p) + cut])
            h = eng2.submit(p, 8, temperature=temp, seed=seed,
                            resume_tokens=resume)
            np.testing.assert_array_equal(h.result(), full)
            assert len(h.tokens) == 8 - cut  # only the remainder
            assert eng2.metrics.resumed_requests == 1
            assert eng2.metrics.resume_tokens_reused == cut
            # the already-emitted tokens were PREFILLED, never decoded:
            # one decode step per newly emitted token minus the
            # prefill-emitted first token
            assert eng2.metrics.decode_steps <= 8 - cut


def test_engine_resume_validation_and_run_path(model):
    cfg, params = model
    eng = ServingEngine(params, cfg, max_slots=1)
    with pytest.raises(ValueError):  # nothing left to decode
        eng.submit(np.arange(1, 5, dtype=np.int32), 3,
                   resume_tokens=[1, 2, 3])
    # run() (not just result()) returns the FULL sequence for resumed
    # requests — resumed tokens must not vanish from the middle
    p = np.arange(1, 8, dtype=np.int32)
    full = eng.submit(p, 6).result()
    eng2 = ServingEngine(params, cfg, max_slots=1)
    h = eng2.submit(p, 6, resume_tokens=list(full[len(p):len(p) + 2]))
    out = eng2.run()
    np.testing.assert_array_equal(out[h.rid], full)


def test_engine_cancel_frees_slot_and_blocks(model):
    cfg, params = model
    eng = ServingEngine(params, cfg, max_slots=2)
    p = np.arange(1, 8, dtype=np.int32)
    want = _oracle(params, cfg, p, 6)
    h1 = eng.submit(p, 6)
    h2 = eng.submit(np.arange(2, 9, dtype=np.int32), 30)
    eng.step()  # both admitted and decoding
    assert eng.cancel(h2.rid)
    assert h2.done and h2.finish_reason == "cancelled"
    assert eng.metrics.cancelled == 1
    assert not eng.cancel(h2.rid)  # already finished: no-op
    np.testing.assert_array_equal(h1.result(), want)  # neighbor unharmed
    assert not eng.step()
    assert eng.kv_blocks_in_use == 0  # cancel freed its blocks


# ---------------------------------------------------------------------------
# fleet: deadlines end to end
# ---------------------------------------------------------------------------

def test_expired_on_arrival_beats_fleet_saturated(model):
    """The ISSUE 8 fix: a request whose deadline is already spent is
    refused as `DeadlineExceeded` BEFORE the max_pending shed — shed
    metrics must not conflate overload with client-side lateness —
    and is journaled in NEITHER case."""
    cfg, params = model
    fleet = ServingFleet(params, cfg, n_replicas=1, max_pending=1,
                         heartbeat_timeout_s=60.0,
                         engine_kw={"max_slots": 1})
    try:
        p = np.arange(1, 8, dtype=np.int32)
        a = fleet.submit(p, 30)  # fills max_pending
        with pytest.raises(DeadlineExceeded):  # NOT FleetSaturated
            fleet.submit(p, 5, deadline_s=0.0)
        with pytest.raises(FleetSaturated):
            fleet.submit(p, 5)
        a.result(timeout=120)
        st = fleet.stats()
        assert st["expired_on_arrival"] == 1 and st["shed"] == 1, st
        assert st["expired"] == 0 and st["submitted"] == 1, st
        assert st["lost"] == 0, st
    finally:
        fleet.close()


def test_fleet_deadline_expires_midflight_with_journal_verdict(model,
                                                               tmp_path):
    """A replica stalls (injected delay) past a request's budget: the
    request is terminally `expired` in the journal — a verdict, never
    a silent hang — result() raises DeadlineExceeded carrying the
    partial tokens, and recover() sees nothing open."""
    cfg, params = model
    journal = str(tmp_path / "j.jsonl")
    inj = FaultInjector("")
    fleet = ServingFleet(params, cfg, n_replicas=1, journal_path=journal,
                         heartbeat_timeout_s=60.0,
                         engine_kw={"max_slots": 1},
                         engine_kw_for=lambda i: {"fault_injector": inj})
    try:
        p = np.arange(1, 8, dtype=np.int32)
        w = fleet.submit(p, 4)  # warm: compiles before the drill
        w.result(timeout=180)
        inj.arm("delay@2:0.6")
        h = fleet.submit(p, 40, deadline_s=0.25)
        with pytest.raises(DeadlineExceeded) as ei:
            h.result(timeout=120)
        assert ei.value.rid == h.rid
        st = fleet.stats()
        assert st["expired"] == 1 and st["lost"] == 0, st
        lines = [json.loads(l) for l in open(journal)]
        assert any(r["kind"] == "expired" and r["rid"] == h.rid
                   for r in lines)
        assert RequestJournal.recover(journal) == []
        # the fleet still serves within-budget requests afterwards
        h2 = fleet.submit(p, 4, deadline_s=60.0)
        np.testing.assert_array_equal(
            h2.result(timeout=120), _oracle(params, cfg, p, 4))
    finally:
        fleet.close()


def test_fleet_timeout_carries_operator_context(model):
    """Satellite: result(timeout=) raises FleetTimeout naming the rid,
    journal state, and assigned replica — a slow request is
    distinguishable from a lost one."""
    cfg, params = model
    fleet = ServingFleet(params, cfg, n_replicas=1,
                         heartbeat_timeout_s=60.0,
                         engine_kw={"max_slots": 1})
    try:
        p = np.arange(1, 8, dtype=np.int32)
        h = fleet.submit(p, 30)
        with pytest.raises(FleetTimeout) as ei:
            h.result(timeout=0.001)
        e = ei.value
        assert isinstance(e, TimeoutError)  # old callers keep working
        assert e.rid == h.rid
        assert e.state in ("queued", "assigned", "decoding", "open")
        assert "journal state" in str(e)
        h.result(timeout=120)  # then it completes fine
        assert fleet.stats()["lost"] == 0
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# fleet: token-level resume across failover
# ---------------------------------------------------------------------------

@pytest.mark.slow  # two-replica fleet + kill drill; the re-decode-zero
                   # journal audit is pinned tier-1 by the serving_slo
                   # bench contract
def test_failover_resumes_at_token_level_no_redecode(model, tmp_path):
    """r0 is killed AFTER its request has journaled emitted tokens: the
    survivor is submitted prompt + emitted, decodes only the remainder
    (journal-audited: per rid, progress deltas concatenate EXACTLY to
    the done record — a re-decoded token would appear twice), and the
    output is token-identical to uninterrupted generate()."""
    cfg, params = model
    journal = str(tmp_path / "j.jsonl")
    fleet = ServingFleet(params, cfg, n_replicas=2, journal_path=journal,
                         heartbeat_timeout_s=60.0,
                         engine_kw={"max_slots": 2})
    try:
        p0 = np.arange(1, 10, dtype=np.int32)
        p1 = np.arange(2, 10, dtype=np.int32)
        h0 = fleet.submit(p0, 12)          # least-loaded: lands on r0
        h1 = fleet.submit(p1, 12, seed=3, temperature=0.8)  # on r1
        deadline = time.monotonic() + 120
        while h0.emitted < 2:  # wait for journaled progress on r0
            assert time.monotonic() < deadline
            time.sleep(0.005)
        fleet.kill_replica(0)
        np.testing.assert_array_equal(
            h0.result(timeout=180), _oracle(params, cfg, p0, 12))
        h1.result(timeout=180)
        st = fleet.stats()
        assert st["failovers"] == 1 and st["lost"] == 0, st
        assert st["resumed_requests"] >= 1, st
        assert st["resumed_tokens"] >= 2, st
        assert h0.replica == "r1"  # the survivor answered
        lines = [json.loads(l) for l in open(journal)]
        done = {r["rid"]: r["tokens"] for r in lines if r["kind"] == "done"}
        prog, sources = {}, {}
        for r in lines:
            if r["kind"] == "progress":
                prog.setdefault(r["rid"], []).extend(r["tokens"])
                sources.setdefault(r["rid"], set()).add(
                    (r["replica"], r["incarnation"], r["gen"]))
        # re-decode zero: every journaled token appears exactly once
        for rid, toks in done.items():
            assert prog.get(rid, []) == toks, (rid, prog.get(rid), toks)
        # h0 really was served by two incarnations (resume exercised)
        assert len(sources[h0.rid]) >= 2, sources
        assert RequestJournal.recover(journal) == []
    finally:
        fleet.close()


def _crashed_journal(path, rid, prompt, max_new, emitted, eos_id=None):
    """Write the journal a front-door CRASH leaves behind: an open rid
    with assigned progress and no terminal record."""
    jr = RequestJournal(path)
    spec = {"prompt": [int(t) for t in prompt], "max_new_tokens": max_new,
            "temperature": 0.0, "eos_id": eos_id, "seed": 0,
            "publish_len": None, "slo": "interactive",
            "deadline_s": None, "submit_unix": time.time()}
    jr.submit(rid, spec)
    jr.assign(rid, "r0", 0, 0)
    jr.progress(rid, "r0", 0, 0, emitted)
    jr.close()
    return spec


def test_front_door_restart_resume_via_submit(model, tmp_path):
    """The documented restart workflow end-to-end: recover() +
    recover_progress() from a crashed front door's journal, resubmit
    through ServingFleet.submit(resume_tokens=...) — the new fleet
    prefill-aliases the emitted prefix, re-decodes ZERO already-emitted
    tokens (journal-audited on the NEW file), and the output is
    token-identical to uninterrupted generate()."""
    cfg, params = model
    p = np.arange(1, 10, dtype=np.int32)
    full = _oracle(params, cfg, p, 12)
    cut = 5
    emitted = [int(t) for t in full[len(p):len(p) + cut]]
    j1 = str(tmp_path / "crashed.jsonl")
    _crashed_journal(j1, 7, p, 12, emitted)
    open_set = RequestJournal.recover(j1)
    prog = RequestJournal.recover_progress(j1)
    assert [r for r, _ in open_set] == [7] and prog[7] == emitted
    j2 = str(tmp_path / "restarted.jsonl")
    fleet = ServingFleet(params, cfg, n_replicas=1, journal_path=j2,
                         engine_kw={"max_slots": 2})
    try:
        (rid, s), = open_set
        h = fleet.submit(np.asarray(s["prompt"], np.int32),
                         s["max_new_tokens"],
                         temperature=s["temperature"],
                         eos_id=s["eos_id"], seed=s["seed"],
                         publish_len=s["publish_len"], slo=s["slo"],
                         resume_tokens=prog[rid])
        assert h.emitted == cut  # operator context starts at the prefix
        np.testing.assert_array_equal(h.result(timeout=180), full)
        st = fleet.stats()
        assert st["resumed_requests"] == 1, st
        assert st["resumed_tokens"] == cut, st
        # the replica PREFILLED the prefix instead of decoding it
        rst = st["replicas"][0]["stats"]
        assert rst["resumed_requests"] == 1, rst
        assert rst["resume_tokens_reused"] == cut, rst
    finally:
        fleet.close()
    lines = [json.loads(l) for l in open(j2)]
    done = {r["rid"]: r["tokens"] for r in lines if r["kind"] == "done"}
    prog2, sources = {}, set()
    for r in lines:
        if r["kind"] == "progress":
            prog2.setdefault(r["rid"], []).extend(r["tokens"])
            sources.add(r["replica"])
    (rid2, toks), = done.items()
    assert toks == [int(t) for t in full[len(p):]]
    # re-decode zero: prefix (from "__restart__") + new deltas
    # concatenate EXACTLY to the done record — a re-decoded token
    # would appear twice
    assert prog2[rid2] == toks
    assert "__restart__" in sources
    assert RequestJournal.recover(j2) == []


def test_restart_resume_finished_prefix_and_validation(model, tmp_path):
    """A recovered prefix that already reached its budget (or eos)
    means the crashed fleet FINISHED the request and only lost the done
    record: submit(resume_tokens=...) completes it straight from the
    journal with zero engine work. A prefix longer than the budget is
    refused loudly."""
    cfg, params = model
    p = np.arange(1, 8, dtype=np.int32)
    fleet = ServingFleet(params, cfg, n_replicas=1,
                         journal_path=str(tmp_path / "j2.jsonl"),
                         engine_kw={"max_slots": 2})
    try:
        with pytest.raises(ValueError, match="resume_tokens longer"):
            fleet.submit(p, 3, resume_tokens=[1, 2, 3, 4])
        # budget-complete prefix: done on arrival, no routing
        done_toks = [5, 9, 11]
        h = fleet.submit(p, 3, resume_tokens=done_toks)
        np.testing.assert_array_equal(
            h.result(timeout=30), np.concatenate([p, done_toks]))
        assert h.replica == "__restart__"
        # eos-terminated prefix under budget: same verdict
        h2 = fleet.submit(p, 8, eos_id=11, resume_tokens=done_toks)
        np.testing.assert_array_equal(
            h2.result(timeout=30), np.concatenate([p, done_toks]))
        st = fleet.stats()
        assert st["completed"] == 2 and st["lost"] == 0, st
        # zero engine work: nothing was routed, decoded, or prefilled
        assert st["tokens_out"] == 0 and st["prefill_tokens_computed"] == 0
        assert st["resumed_requests"] == 0, st  # no decode was resumed
    finally:
        fleet.close()
    jl = str(tmp_path / "j2.jsonl")
    assert RequestJournal.recover(jl) == []  # both rids terminal


def test_rate_veto_reference_is_the_healthy_replica(model):
    """Review regression: with BOTH replicas busy (both rate samples
    fresh), the rate veto's fleet reference must be the healthy
    replica's rate, not the gray replica's own trickle — rate polarity
    is the INVERSE of latency, so a lower-median reference would let
    the sick replica judge itself healthy forever. Drives _health_sweep
    directly (under the fleet lock) with forged evidence: r0 gray
    (slow EWMA, trickle rate), r1 healthy, both busy."""
    from paddle_tpu.serving.fleet import _DEMOTED, _LIVE
    cfg, params = model
    fleet = ServingFleet(params, cfg, n_replicas=2,
                         heartbeat_timeout_s=60.0,
                         slow_replica_factor=4.0,
                         slow_min_duration_s=0.2,
                         probe_interval_s=60.0,
                         engine_kw={"max_slots": 2})
    try:
        with fleet._cond:
            now = time.monotonic()
            for i, (ewma, rate, toks) in enumerate(
                    [(0.9, 2.0, 50), (0.1, 100.0, 500)]):
                fleet._beats[i] = now
                fleet._rep_stats[i] = {
                    "step_ewma_s": ewma, "busy": True,
                    "tokens_out": toks, "prefill_tokens_computed": 0}
                fleet._rate[i] = rate
                fleet._watermark[i] = (now, toks)
                fleet._stall_since[i] = None
                fleet._slow_since[i] = None
            fleet._health_sweep(now)  # arms the hysteresis clock on r0
            assert fleet._state[0] == _LIVE  # not before the window
            later = now + fleet.slow_min_duration_s + 0.01
            for i, toks in ((0, 50), (1, 500)):
                fleet._beats[i] = later  # evidence stays fresh
                fleet._watermark[i] = (later, toks)
            fleet._health_sweep(later)
            assert fleet._state[0] == _DEMOTED, fleet._state
            assert fleet._state[1] == _LIVE, fleet._state
            assert fleet.demotions == 1
    finally:
        fleet.close()


@pytest.mark.slow  # two-replica fleet + kill + full-bucket warmup
def test_route_falls_back_to_demoted_when_last_live_dies(model,
                                                         tmp_path):
    """Review regression: the last LIVE replica dying while the other
    is DEMOTED must not terminally reject the fleet's requests — the
    demoted replica is alive, warm, and heartbeating (parked by our own
    health verdict), so routing falls back to it: its in-flight +
    resubmitted requests complete token-identically, lost == 0."""
    from paddle_tpu.serving.fleet import _DEMOTED
    cfg, params = model
    journal = str(tmp_path / "j.jsonl")
    fleet = ServingFleet(params, cfg, n_replicas=2, journal_path=journal,
                         heartbeat_timeout_s=60.0,
                         probe_interval_s=60.0,  # no restore mid-test
                         engine_kw={"max_slots": 2})
    try:
        _warm_all_buckets(fleet, cfg, n_replicas=2)
        with fleet._cond:
            fleet._demote_locked(0)
            assert fleet._state[0] == _DEMOTED
        p = np.arange(1, 10, dtype=np.int32)
        h1 = fleet.submit(p, 10)           # routed to r1, the last live
        fleet.kill_replica(1)
        # failover re-routes h1 onto the demoted (only alive) replica,
        # and a brand-new submit routes there too instead of raising
        np.testing.assert_array_equal(
            h1.result(timeout=180), _oracle(params, cfg, p, 10))
        p2 = np.arange(3, 11, dtype=np.int32)
        h2 = fleet.submit(p2, 8)
        np.testing.assert_array_equal(
            h2.result(timeout=180), _oracle(params, cfg, p2, 8))
        st = fleet.stats()
        assert st["lost"] == 0 and st["failovers"] == 1, st
    finally:
        fleet.close()
    assert RequestJournal.recover(journal) == []


def test_fence_refuses_superseded_report_after_route_back(model, tmp_path):
    """Review regression (generation-fence hole): a demote ->
    survivor-death -> route-back-to-the-demoted-replica cycle makes the
    journal's latest assignment name the SAME (replica, incarnation)
    pair as the superseded submission, so the (replica, incarnation)
    fence alone would absorb the old submission's progress into the
    mirror the new holder resumes from and accept its completion with
    the resume prefix duplicated. The in-flight fence (reports count
    only for work the fleet currently tracks on that replica — demotion
    clears it, the re-routed copy waits in the inbox) refuses both.
    Drives _absorb_progress/_accept directly under the fleet lock with
    forged journal state — the race is deterministic here."""
    from paddle_tpu.serving.fleet import FleetHandle
    cfg, params = model
    fleet = ServingFleet(params, cfg, n_replicas=2,
                         journal_path=str(tmp_path / "j.jsonl"),
                         heartbeat_timeout_s=60.0, probe_interval_s=60.0,
                         engine_kw={"max_slots": 2})
    try:
        prompt = np.arange(1, 5, dtype=np.int32)
        spec = {"prompt": [int(t) for t in prompt], "max_new_tokens": 8,
                "temperature": 0.0, "eos_id": None, "seed": 0,
                "publish_len": None, "slo": "interactive",
                "deadline_s": None, "submit_unix": time.time()}
        with fleet._cond:
            rep0 = fleet._replicas[0]
            rid = fleet._next_rid
            fleet._next_rid += 1
            h = FleetHandle(rid, prompt, spec, "interactive", fleet=fleet)
            fleet._handles[rid] = h
            fleet._open.add(rid)
        journal = fleet._journal
        journal.submit(rid, spec)
        with fleet._cond:
            # r0 (gen 0) holds the request and journals two tokens
            journal.assign(rid, rep0.name, rep0.incarnation, 0)
            fleet._in_flight[0][rid] = h
            fleet._absorb_progress(rep0, [(rid, [7, 8])])
            assert journal.progress_of(rid) == [7, 8]
            assert h.emitted == 2
        # land the deferred progress records on disk BEFORE forging the
        # route-back assignment: the real fleet writes strictly in
        # mirror order (everything rides _pending_journal FIFO), and
        # the journal DFA audit rightly reads a gen-0 progress record
        # appearing after the gen-2 assign as a fence violation
        fleet._flush_journal()
        with fleet._cond:
            # demotion hedges it away (in-flight cleared), the survivor
            # dies, and routing falls BACK here: the latest assignment
            # names (r0, incarnation) again under a bumped generation,
            # with the re-routed copy still in the inbox carrying the
            # two-token resume prefix
            del fleet._in_flight[0][rid]
            h.generation = 2
            h.resume = [7, 8]
            journal.assign(rid, rep0.name, rep0.incarnation, 2)
            # the SUPERSEDED submission's late reports now arrive from
            # a matching (replica, incarnation) pair:
            before = fleet.zombie_refused
            fleet._absorb_progress(rep0, [(rid, [9])])
            assert journal.progress_of(rid) == [7, 8], \
                "superseded progress absorbed into the resume mirror"
            assert h.emitted == 2
            fleet._accept(rid, [7, 8, 9], "", rep0, accepted=True)
            assert fleet.zombie_refused == before + 1
            assert not h.done and h.tokens is None
            assert rid in fleet._open  # still the new holder's to finish
    finally:
        fleet.close()


def test_probe_admission_failure_does_not_wedge_or_journal(model, tmp_path):
    """Review regression: a health probe the engine refuses at
    admission must behave as a FAILED PROBE — probe slot cleared, next
    probe scheduled, nothing journaled for the negative rid, rejected
    count untouched — not write rid -1 to the durable table and leave
    `_probes[i]` set forever (no probe would ever be sent again: a
    healthy replica stuck DEMOTED for the fleet's lifetime). The probe
    spec is also sized to the engine's own admission limits so a
    small-context fleet can probe at all."""
    from paddle_tpu.serving.fleet import _DEMOTED
    cfg, params = model
    jpath = str(tmp_path / "j.jsonl")
    fleet = ServingFleet(params, cfg, n_replicas=2, journal_path=jpath,
                         heartbeat_timeout_s=60.0, probe_interval_s=60.0,
                         engine_kw={"max_slots": 2, "max_len": 4})
    try:
        with fleet._cond:
            fleet._demote_locked(0)
            fleet._send_probe_locked(0)
            ph = fleet._probes[0]
            assert ph is not None
            # sized within the engine's admission rule (max_len=4)
            assert 1 + ph.spec["max_new_tokens"] <= 4
            # drive the admission-failure path manually, AFTER the
            # handshake handoff: _sync_locked moves a dispatched probe
            # from the inbox into _in_flight, so a failed probe must
            # clean that entry too (a leaked negative rid blocks
            # DRAINING->DRAINED forever and inflates routing load)
            fleet._inbox[0].clear()
            fleet._in_flight[0][ph.rid] = ph
        fleet._reject(ph.rid, ValueError("admission refused"))
        with fleet._cond:
            assert fleet._probes[0] is None            # slot cleared
            assert fleet._probe_at[0] > time.monotonic()  # rescheduled
            assert ph.rid not in fleet._handles
            assert ph.rid not in fleet._in_flight[0]   # no leak
            assert fleet._state[0] == _DEMOTED  # still parked, probeable
        assert fleet.rejected == 0
        assert fleet.stats()["lost"] == 0
    finally:
        fleet.close()
    for line in open(jpath):
        rec = json.loads(line)
        assert rec.get("rid", 0) >= 0, rec  # probes never reach the journal


def test_probe_sized_to_replica_override_limits(model):
    """Review regression: probe sizing must use the PER-REPLICA
    composed engine kwargs, not the base kw — a replica whose
    engine_kw_for override shrinks the context would otherwise fail
    every probe at admission and stay demoted forever."""
    cfg, params = model
    fleet = ServingFleet(
        params, cfg, n_replicas=2, heartbeat_timeout_s=60.0,
        probe_interval_s=60.0, engine_kw={"max_slots": 2},
        engine_kw_for=lambda i: {"max_len": 4} if i == 0 else {})
    try:
        with fleet._cond:
            fleet._demote_locked(0)
            fleet._send_probe_locked(0)
            ph0 = fleet._probes[0]
            fleet._demote_locked(1)
            fleet._send_probe_locked(1)
            ph1 = fleet._probes[1]
        # replica 0's override (max_len=4) caps its probe; replica 1
        # probes at the base limits
        assert 1 + ph0.spec["max_new_tokens"] <= 4
        assert ph1.spec["max_new_tokens"] > ph0.spec["max_new_tokens"]
    finally:
        fleet.close()


def test_reject_locked_idempotent_no_double_count(model, tmp_path):
    """Review regression: close()'s open-request sweep and submit()'s
    close-race branch can both reach _reject_locked for the SAME rid —
    the second pass must be a no-op (one `rejected` count, one terminal
    journal record), or stats()['lost'] goes negative and the durable
    table holds duplicate terminal records."""
    cfg, params = model
    jpath = str(tmp_path / "j.jsonl")
    fleet = ServingFleet(params, cfg, n_replicas=1, journal_path=jpath,
                         heartbeat_timeout_s=60.0)
    try:
        from paddle_tpu.serving.fleet import FleetHandle
        with fleet._cond:
            rid = fleet._next_rid
            fleet._next_rid += 1
            spec = {"prompt": [1], "max_new_tokens": 1,
                    "temperature": 0.0, "eos_id": None, "seed": 0,
                    "publish_len": 0, "slo": None, "deadline_s": None,
                    "submit_unix": time.time()}
            h = FleetHandle(rid, np.array([1], np.int32), spec, None,
                            fleet=fleet)
            fleet._handles[rid] = h
            fleet._open.add(rid)
            fleet.submitted += 1
        fleet._journal.submit(rid, spec)
        with fleet._cond:
            fleet._reject_locked(rid, "fleet closed")
            fleet._reject_locked(rid, "fleet closed")  # the race's 2nd hit
            assert fleet.rejected == 1
        fleet._flush_journal()
        assert fleet.stats()["lost"] == 0
    finally:
        fleet.close()
    recs = [json.loads(l) for l in open(jpath)]
    rejects = [r for r in recs if r.get("kind") == "rejected"
               and r.get("rid") == rid]
    assert len(rejects) == 1, rejects


# ---------------------------------------------------------------------------
# fleet: gray-failure demotion / probe / restore
# ---------------------------------------------------------------------------

@pytest.mark.slow  # real gray window (1.6s slow@) + probe/restore wait;
                   # demote+restore-same-incarnation is pinned tier-1 by
                   # the serving_slo bench contract
def test_gray_slow_replica_demoted_probed_restored_warm(model):
    """The ISSUE 8 acceptance drill: r0 gray-slows (heartbeating, every
    step stalls — slow@); the monitor demotes it on the step-latency
    health score, its open requests complete on the survivor
    (token-identical), and after the window it is probed and restored
    under the SAME incarnation — warm pool, no fresh spawn."""
    cfg, params = model
    reqs = _requests(cfg, n=4, seed=3)
    inj = FaultInjector("")  # inert until armed post-warm-up
    fleet = ServingFleet(
        params, cfg, n_replicas=2, heartbeat_timeout_s=60.0,
        monitor_interval_s=0.05, slow_replica_factor=4.0,
        slow_min_duration_s=0.3, probe_interval_s=0.15,
        engine_kw={"max_slots": 2},
        engine_kw_for=lambda i: (
            {"fault_injector": inj} if i == 0 else {}))
    try:
        # warm BOTH replicas, EVERY bucket, first (first-compile
        # latency is the documented false-demotion hazard: never score
        # a cold replica)
        _warm_all_buckets(fleet, cfg)
        inj.arm("slow@2:1.6/0.2")  # gray window: 1.6s of 0.2s steps
        hs = [fleet.submit(p, 16) for p, _ in reqs]
        for h in hs:
            h.result(timeout=120)
        st = fleet.stats()
        assert st["demotions"] == 1, st
        assert st["lost"] == 0 and st["duplicate_refused"] == 0, st
        for h, (p, _) in zip(hs, reqs):
            np.testing.assert_array_equal(
                np.asarray(h.tokens, np.int32),
                _oracle(params, cfg, p, 16)[len(p):])
        # after the window: probed back to life, SAME incarnation
        deadline = time.monotonic() + 60
        while fleet.stats()["replicas"][0]["state"] != "live":
            assert time.monotonic() < deadline, fleet.stats()
            time.sleep(0.05)
        st = fleet.stats()
        assert st["restores"] == 1 and st["probes_sent"] >= 1, st
        assert st["replicas"][0]["incarnation"] == 1, st  # warm, no respawn
        assert st["failovers"] == 0, st  # demoted, never declared dead
        # the restored replica serves again
        h2 = fleet.submit(*reqs[1])
        np.testing.assert_array_equal(
            h2.result(timeout=120), _oracle(params, cfg, *reqs[1]))
    finally:
        fleet.close()


def test_single_transient_pause_does_not_flap(model):
    """Hysteresis: one GC-pause-shaped stall (delay@ — a single long
    step) spikes the EWMA once, healthy steps decay it well inside
    `slow_min_duration_s`, and the replica is never demoted."""
    cfg, params = model
    inj = FaultInjector("")
    fleet = ServingFleet(
        params, cfg, n_replicas=2, heartbeat_timeout_s=60.0,
        monitor_interval_s=0.05, slow_replica_factor=4.0,
        slow_min_duration_s=1.0, probe_interval_s=0.15,
        engine_kw={"max_slots": 2},
        engine_kw_for=lambda i: (
            {"fault_injector": inj} if i == 0 else {}))
    try:
        p = np.arange(3, 12, dtype=np.int32)
        _warm_all_buckets(fleet, cfg)
        inj.arm("delay@2:0.4")  # ONE transient pause mid-request
        hs = [fleet.submit(p, 24), fleet.submit(p, 24, seed=1)]
        for h in hs:
            h.result(timeout=120)
        time.sleep(0.5)  # several more health sweeps on settled EWMAs
        st = fleet.stats()
        assert st["demotions"] == 0 and st["restores"] == 0, st
        assert st["lost"] == 0, st
        assert st["replicas"][0]["state"] == "live", st
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# chaos drill matrix: the journal invariant under every fault kind
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec", ["exc@4", "delay@3:0.5", "slow@3:1.0/0.1"])
def test_chaos_matrix_journal_invariant(model, tmp_path, spec):
    """PADDLE_FAULT kinds against the fleet (exc = in-process crash —
    the kill analog whose SIGKILL form runs in the subprocess drill —
    delay = straggler, slow = gray): under each, every request
    completes token-identically and the journal invariant holds —
    after close, every journaled rid is terminal (done / rejected /
    expired), never silently open."""
    cfg, params = model
    reqs = _requests(cfg, n=5, seed=11)
    oracle = [_oracle(params, cfg, p, n) for p, n in reqs]
    journal = str(tmp_path / "j.jsonl")
    inj = FaultInjector("")  # inert until the fleet is warm
    fleet = ServingFleet(
        params, cfg, n_replicas=2, journal_path=journal,
        heartbeat_timeout_s=60.0, monitor_interval_s=0.05,
        slow_replica_factor=4.0, slow_min_duration_s=0.3,
        engine_kw={"max_slots": 2},
        engine_kw_for=lambda i: (
            {"fault_injector": inj} if i == 0 else {}))
    try:
        _warm_all_buckets(fleet, cfg)
        inj.arm(spec)  # fault steps count from the warmed state
        hs = [fleet.submit(p, n, deadline_s=120.0) for p, n in reqs]
        for h, want in zip(hs, oracle):
            np.testing.assert_array_equal(h.result(timeout=180), want)
        assert fleet.stats()["lost"] == 0
    finally:
        fleet.close()
    # the invariant: nothing is open after close, under ANY fault kind
    assert RequestJournal.recover(journal) == []
    lines = [json.loads(l) for l in open(journal)]
    submitted = {r["rid"] for r in lines if r["kind"] == "submit"}
    terminal = {r["rid"] for r in lines
                if r["kind"] in ("done", "rejected", "expired")}
    assert submitted <= terminal, submitted - terminal


def test_close_writes_terminal_records_for_open_requests(model, tmp_path):
    """The invariant's hardest edge: requests still open when the
    fleet closes get terminal `rejected` records — never left silently
    open for every future recover() to resubmit."""
    cfg, params = model
    journal = str(tmp_path / "j.jsonl")
    fleet = ServingFleet(params, cfg, n_replicas=1, journal_path=journal,
                         heartbeat_timeout_s=60.0,
                         engine_kw={"max_slots": 1})
    p = np.arange(1, 8, dtype=np.int32)
    hs = [fleet.submit(p, 40), fleet.submit(p, 40, seed=1)]
    fleet.close()
    for h in hs:
        assert h.done and h.error is not None
    assert RequestJournal.recover(journal) == []
    lines = [json.loads(l) for l in open(journal)]
    rejects = [r for r in lines if r["kind"] == "rejected"]
    assert {r["rid"] for r in rejects} == {h.rid for h in hs}


# ---------------------------------------------------------------------------
# journal compaction (host-only)
# ---------------------------------------------------------------------------

def test_journal_compaction_rewrites_open_only(tmp_path):
    """Satellite: past the rotation threshold the file rewrites to
    meta + the open set; recover(), reopen (rid history preserved via
    the meta record), lost() with progress, and recover_progress()
    all see identical state after the compaction."""
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path, compact_every=20)
    for k in range(30):  # lifetime traffic: all terminal
        j.submit(k, {"p": [k]})
        j.assign(k, "r0", 1, 0)
        j.progress(k, "r0", 1, 0, [1, 2])
        j.complete(k, "r0", 1, 0, [1, 2])
    # two still-open requests, one with journaled progress
    j.submit(100, {"p": [1]})
    j.assign(100, "r0", 1, 2)
    j.progress(100, "r0", 1, 2, [5, 6])
    j.submit(101, {"p": [2]})
    assert j.compactions >= 1
    assert j.open_count() == 2
    j.compact()  # settle the tail traffic since the last auto rotation
    j.close()
    # the FILE holds exactly meta + the open set now: one meta, two
    # submits, rid 100's assign + progress
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 5, lines
    assert lines[0]["kind"] == "meta"
    assert RequestJournal.recover(path) == [(100, {"p": [1]}),
                                            (101, {"p": [2]})]
    assert RequestJournal.recover_progress(path) == {100: [5, 6]}
    # reopen: rid history continues past EVERYTHING ever issued, the
    # open mirror (incl. progress + assignment generation) resumes
    j2 = RequestJournal(path)
    assert j2.next_rid() == 102
    assert j2.open_count() == 2
    assert j2.lost("r0", 1) == [(100, {"p": [1]}, 2, [5, 6])]
    j2.complete(100, "r1", 1, 3, [5, 6, 7])
    j2.reject(101, "drill over")
    j2.close()
    assert RequestJournal.recover(path) == []


def test_journal_explicit_compact_and_small_open_set_guard(tmp_path):
    """compact() works on demand; the auto path refuses to rewrite
    when the file is mostly open records (a rewrite that cannot shrink
    the file must not run on every append)."""
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path, compact_every=4)
    for k in range(6):  # 6 open submits: > threshold but all live
        j.submit(k, {"p": [k]})
    before = j.compactions
    j.submit(6, {"p": [6]})
    assert j.compactions == before  # guard held: nothing to shrink
    for k in range(7):
        j.complete(k, "r0", 1, 0, [k])
    assert j.compactions > before  # terminals made the rewrite pay
    j.submit(7, {"p": [7]})
    assert j.compact()  # explicit request always rewrites
    j.close()
    assert [rid for rid, _ in RequestJournal.recover(path)] == [7]
    j2 = RequestJournal(path)
    assert j2.next_rid() == 8
    j2.close()


def test_compaction_never_fires_mid_batch(tmp_path):
    """Regression (review finding): write() appends DEFERRED records
    whose mirror effects already happened — a compaction firing
    mid-batch would snapshot the mirror (which includes the whole
    batch) and then append the remaining records on top, duplicating
    progress tokens in the file. Resume prefixes recovered after a
    restart must match the mirror exactly."""
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path, compact_every=4)
    j.submit(0, {"p": [0]})
    recs = [j.assign(0, "r0", 1, 0, defer=True)]
    # one batch of deferred progress records big enough to trip the
    # threshold mid-batch several times over
    for k in range(12):
        recs.append(j.progress(0, "r0", 1, 0, [k], defer=True))
    j.write(recs)
    assert j.progress_of(0) == list(range(12))
    j.close()
    # the FILE agrees with the mirror: no token appears twice
    assert RequestJournal.recover_progress(path) == {0: list(range(12))}
    j2 = RequestJournal(path)  # replay path agrees too
    assert j2.progress_of(0) == list(range(12))
    assert j2.lost("r0", 1) == [(0, {"p": [0]}, 0, list(range(12)))]
    j2.close()


def test_direct_append_defers_compaction_to_outstanding_batch(tmp_path):
    """Regression (review finding): a DIRECT append (submit — the
    fleet journals it outside its scheduler lock) can cross the
    rotation threshold while another thread still holds
    mirror-applied-but-unwritten deferred records. Compacting there
    snapshots the mirror (which already includes the deferred progress
    tokens) and the later write() appends the same deltas on top —
    duplicated tokens in the file, corrupt restart resume prefixes.
    Rotation must WAIT for the outstanding batch."""
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path, compact_every=4)
    j.submit(50, {"p": [50]})
    recs = [j.assign(50, "r0", 1, 0, defer=True),
            j.progress(50, "r0", 1, 0, [7, 8], defer=True)]
    before = j.compactions
    # terminal direct traffic: crosses the threshold AND satisfies the
    # shrink guard (one open request) many times over
    for k in range(100, 110):
        j.submit(k, {"p": [k]})
        j.complete(k, "r0", 1, 0, [k])
    assert j.compactions == before  # held: batch still outstanding
    assert j.compact() is False     # explicit request refused too
    j.write(recs)                   # batch lands -> rotation allowed
    assert j.compactions > before
    # the LIVE object's is_done() stays truthful across the rotation
    # (the terminal records left the file, not the mirror)
    assert j.is_done(105)
    j.close()
    # the file agrees with the mirror: rid 50's tokens appear ONCE
    assert RequestJournal.recover_progress(path) == {50: [7, 8]}
    j2 = RequestJournal(path)
    assert j2.lost("r0", 1) == [(50, {"p": [50]}, 0, [7, 8])]
    j2.close()


def test_restored_replica_republishes_routing_summary(model):
    """Regression (review finding): demotion clears the routing
    summary; the pool is warm and UNCHANGED across restore, so the
    replica's revision cache would never resend it and affinity
    routing would treat the restored replica as cold forever."""
    cfg, params = model
    fleet = ServingFleet(
        params, cfg, n_replicas=2, heartbeat_timeout_s=60.0,
        monitor_interval_s=0.05, slow_replica_factor=4.0,
        slow_min_duration_s=0.3, probe_interval_s=0.1,
        engine_kw={"max_slots": 2, "prefix_cache_tokens": 256,
                   "prefix_block_tokens": 4})
    try:
        p = np.arange(1, 17, dtype=np.int32)
        h = fleet.submit(p, 4, publish_len=16)  # least-loaded -> r0
        h.result(timeout=180)
        deadline = time.monotonic() + 30
        while not fleet._summaries[0]:  # published summary lands async
            assert time.monotonic() < deadline
            time.sleep(0.02)
        before = set(fleet._summaries[0])
        with fleet._cond:
            fleet._demote_locked(0)
        assert fleet.stats()["replicas"][0]["state"] == "demoted"
        assert not fleet._summaries[0]  # demotion cleared it
        deadline = time.monotonic() + 60
        while fleet.stats()["replicas"][0]["state"] != "live":
            assert time.monotonic() < deadline, fleet.stats()
            time.sleep(0.05)
        deadline = time.monotonic() + 30
        while not fleet._summaries[0]:  # the refresh must repopulate it
            assert time.monotonic() < deadline, "summary never resent"
            time.sleep(0.02)
        assert set(fleet._summaries[0]) == before  # warm pool, same keys
    finally:
        fleet.close()


def test_fleet_journal_compaction_under_traffic(model, tmp_path):
    """End-to-end: a fleet configured with journal_compact_every keeps
    the file bounded by in-flight work while serving — and the
    post-close journal still recovers to empty."""
    cfg, params = model
    journal = str(tmp_path / "j.jsonl")
    fleet = ServingFleet(params, cfg, n_replicas=1, journal_path=journal,
                         journal_compact_every=25,
                         heartbeat_timeout_s=60.0,
                         engine_kw={"max_slots": 2})
    try:
        p = np.arange(1, 8, dtype=np.int32)
        for _ in range(4):
            hs = [fleet.submit(p, 8), fleet.submit(p, 8, seed=1)]
            for h in hs:
                h.result(timeout=120)
        assert fleet._journal.compactions >= 1
        assert fleet.stats()["lost"] == 0
    finally:
        fleet.close()
    assert RequestJournal.recover(journal) == []
    assert len(list(open(journal))) <= 25 + 4  # bounded, not lifetime
