"""Pipeline parallelism (GPipe microbatch schedule over a 'pipe' axis).

Beyond-reference capability (SURVEY.md §2.2 lists PP as absent; nearest
reference machinery is ParallelNeuralNetwork's per-layer device threads).
The sequential `reference_pipeline` is the oracle: the rotating-buffer
ppermute schedule must reproduce it exactly, forward and gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.parallel import (
    gpipe_pipeline,
    make_mesh,
    reference_pipeline,
)


def _stage(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _setup(S, B, D, seed=0):
    rng = np.random.RandomState(seed)
    params = {
        "w": jnp.asarray(rng.randn(S, D, D).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(S, D).astype(np.float32) * 0.1),
    }
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))
    return params, x


def test_pipeline_matches_sequential_forward_and_grad():
    mesh = make_mesh({"pipe": 4})
    params, x = _setup(S=4, B=16, D=8)
    out = gpipe_pipeline(_stage, params, x, mesh, n_microbatches=4)
    ref = reference_pipeline(_stage, params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    g_pp = jax.grad(lambda p: jnp.sum(
        gpipe_pipeline(_stage, p, x, mesh, n_microbatches=4) ** 2))(params)
    g_sq = jax.grad(lambda p: jnp.sum(
        reference_pipeline(_stage, p, x) ** 2))(params)
    for k in g_pp:
        np.testing.assert_allclose(
            np.asarray(g_pp[k]), np.asarray(g_sq[k]), atol=1e-4)


def test_pipeline_eight_stages_more_microbatches():
    mesh = make_mesh({"pipe": 8})
    params, x = _setup(S=8, B=32, D=4, seed=1)
    out = gpipe_pipeline(_stage, params, x, mesh, n_microbatches=8)
    ref = reference_pipeline(_stage, params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_validates_shapes():
    mesh = make_mesh({"pipe": 4})
    params, x = _setup(S=3, B=16, D=8)  # wrong stage count
    with pytest.raises(ValueError):
        gpipe_pipeline(_stage, params, x, mesh)
    params, x = _setup(S=4, B=15, D=8)  # indivisible batch
    with pytest.raises(ValueError):
        gpipe_pipeline(_stage, params, x, mesh, n_microbatches=4)
