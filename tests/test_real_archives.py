"""Real-download decode proof (r4 verdict #7; reference
python/paddle/v2/dataset/common.py:37 md5-checked download).

The zero-egress harness only ever feeds the readers synthesised
real-FORMAT files; these tests run against GENUINE archives when an
operator points ``PADDLE_TPU_DATA_HOME`` at a reference-layout download
cache (``<home>/mnist/train-images-idx3-ubyte.gz`` etc.). Each test
md5-verifies the archive against the reference checksum first — a
synthesized stand-in never matches, so off-harness these skip rather
than false-pass — then decodes real samples and trains a few fluid
steps on them.
"""

import os

import numpy as np
import pytest

from paddle_tpu.v2.dataset import cifar, common, imdb, mnist


def _genuine(path, md5):
    """Present AND byte-identical to the published archive."""
    return os.path.exists(path) and common.md5file(path) == md5


def _require(path, md5, what):
    if not os.path.exists(path):
        pytest.skip("no %s archive at %s (set PADDLE_TPU_DATA_HOME to a "
                    "real download cache)" % (what, path))
    if common.md5file(path) != md5:
        pytest.skip("%s at %s is not the genuine download (md5 mismatch "
                    "vs reference checksum)" % (what, path))


def _train_few_steps(samples, dim, n_classes):
    """Train a softmax classifier on decoded samples for a few steps;
    the loss must be finite and decrease is not required (2 steps)."""
    import paddle_tpu.fluid as fluid

    xs = np.stack([np.asarray(s[0], np.float32) for s in samples])
    ys = np.asarray([[int(s[1])] for s in samples], np.int64)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[dim], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        pred = fluid.layers.fc(input=x, size=n_classes, act="softmax")
        loss = fluid.layers.mean(
            x=fluid.layers.cross_entropy(input=pred, label=y)
        )
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for _ in range(3):
            (lv,) = exe.run(main, feed={"x": xs, "y": ys},
                            fetch_list=[loss])
    assert np.isfinite(np.ravel(lv)).all()


def test_real_mnist_decodes_and_trains():
    d = os.path.join(common.DATA_HOME, "mnist")
    _require(os.path.join(d, "train-images-idx3-ubyte.gz"),
             mnist.TRAIN_IMAGE_MD5, "MNIST train images")
    _require(os.path.join(d, "train-labels-idx1-ubyte.gz"),
             mnist.TRAIN_LABEL_MD5, "MNIST train labels")
    samples = []
    for s in mnist.train()():
        samples.append(s)
        if len(samples) == 64:
            break
    assert len(samples) == 64
    for img, label in samples:
        assert img.shape == (784,)
        assert -1.0 - 1e-6 <= float(img.min()) <= float(img.max()) <= 1.0 + 1e-6
        assert 0 <= label <= 9
    # the genuine train split holds 60000 samples; the synthetic only 512
    n = sum(1 for _ in mnist.train()())
    assert n == 60000, n
    _train_few_steps(samples, 784, 10)


def test_real_cifar10_decodes_and_trains():
    path = os.path.join(common.DATA_HOME, "cifar", "cifar-10-python.tar.gz")
    _require(path, cifar.CIFAR10_MD5, "CIFAR-10")
    samples = []
    for s in cifar.train10()():
        samples.append(s)
        if len(samples) == 64:
            break
    for img, label in samples:
        assert np.asarray(img).shape == (3072,)
        assert 0 <= label <= 9
    _train_few_steps(samples, 3072, 10)


def test_real_imdb_decodes_and_trains():
    path = os.path.join(common.DATA_HOME, "imdb", "aclImdb_v1.tar.gz")
    _require(path, imdb.MD5, "IMDB")
    w = imdb.word_dict()
    assert len(w) > 10000  # genuine vocabulary is ~90k; synthetic ~30
    samples = []
    for ids, label in imdb.train(w)():
        assert label in (0, 1)
        assert all(0 <= i < len(w) for i in ids)
        samples.append((ids, label))
        if len(samples) == 32:
            break
    assert len(samples) == 32


def test_skip_logic_rejects_synthetic_standins(tmp_path, monkeypatch):
    """Runs EVERYWHERE: a synthesised real-format archive must NOT pass
    the genuine-md5 gate — proving the tests above can't false-pass on
    this harness's stand-ins."""
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    mnist.fetch()  # synthesises real-FORMAT files into the fake home
    img = os.path.join(str(tmp_path), "mnist", "train-images-idx3-ubyte.gz")
    assert os.path.exists(img)
    assert not _genuine(img, mnist.TRAIN_IMAGE_MD5)
