"""Expert parallelism (MoE over an 'expert' mesh axis).

A beyond-reference capability (SURVEY.md §2.2 lists EP as absent from
the 2018 codebase): Switch-style top-1 routing, [E, C, D] dispatch
buffers, two lax.all_to_all hops inside shard_map. The single-device
`reference_moe` is the oracle; with ample capacity the sharded path must
match it exactly, forward and gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.parallel import (
    expert_parallel_moe,
    make_mesh,
    moe_capacity,
    reference_moe,
)


def _params(rng, D, H, E):
    return (
        jnp.asarray(rng.randn(D, E).astype(np.float32) * 0.1),
        jnp.asarray(rng.randn(E, D, H).astype(np.float32) * 0.1),
        jnp.asarray(rng.randn(E, H).astype(np.float32) * 0.01),
        jnp.asarray(rng.randn(E, H, D).astype(np.float32) * 0.1),
        jnp.asarray(rng.randn(E, D).astype(np.float32) * 0.01),
    )


def test_moe_matches_oracle_forward_and_grad():
    mesh = make_mesh({"expert": 8})
    rng = np.random.RandomState(0)
    N, D, H, E = 64, 16, 32, 8
    x = jnp.asarray(rng.randn(N, D).astype(np.float32))
    params = _params(rng, D, H, E)

    out = expert_parallel_moe(x, *params, mesh=mesh, capacity=N)
    ref = reference_moe(x, *params)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    g_sh = jax.grad(
        lambda p: jnp.sum(expert_parallel_moe(x, *p, mesh=mesh,
                                              capacity=N) ** 2)
    )(params)
    g_rf = jax.grad(lambda p: jnp.sum(reference_moe(x, *p) ** 2))(params)
    for a, b in zip(g_sh, g_rf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_moe_two_experts_per_device():
    """E > mesh size: each device owns E/n experts."""
    mesh = make_mesh({"expert": 4})
    rng = np.random.RandomState(1)
    N, D, H, E = 32, 8, 16, 8
    x = jnp.asarray(rng.randn(N, D).astype(np.float32))
    params = _params(rng, D, H, E)
    out = expert_parallel_moe(x, *params, mesh=mesh, capacity=N)
    ref = reference_moe(x, *params)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_moe_capacity_drop_zeroes_overflow():
    """With capacity 1 per shard-expert, overflow tokens pass through
    with ZERO expert output (Switch truncation) — never garbage."""
    mesh = make_mesh({"expert": 2})
    rng = np.random.RandomState(2)
    N, D, H, E = 16, 4, 8, 2
    # all-positive tokens + all-ones gate column 0: every token's expert-0
    # logit is positive while expert 1's is 0 -> all route to expert 0
    x = jnp.asarray(np.abs(rng.randn(N, D)).astype(np.float32) + 0.1)
    gw = jnp.zeros((D, E), jnp.float32).at[:, 0].set(1.0)
    _, w1, b1, w2, b2 = _params(rng, D, H, E)
    out = np.asarray(expert_parallel_moe(
        x, gw, w1, b1, w2, b2, mesh=mesh, capacity=1))
    # exactly 1 kept token per shard (2 shards) -> 2 nonzero rows
    nonzero = (np.abs(out).sum(axis=1) > 1e-7).sum()
    assert nonzero == 2, nonzero
    # kept rows equal the oracle's rows for those tokens
    ref = np.asarray(reference_moe(x, gw, w1, b1, w2, b2))
    kept = np.abs(out).sum(axis=1) > 1e-7
    np.testing.assert_allclose(out[kept], ref[kept], atol=1e-5)


def test_moe_rejects_indivisible():
    mesh = make_mesh({"expert": 8})
    x = jnp.zeros((8, 4))
    gw = jnp.zeros((4, 6))  # 6 experts over 8 shards
    with pytest.raises(ValueError):
        expert_parallel_moe(x, gw, jnp.zeros((6, 4, 8)), jnp.zeros((6, 8)),
                            jnp.zeros((6, 8, 4)), jnp.zeros((6, 4)),
                            mesh=mesh)
    assert moe_capacity(64, 8, 1.25) == 10
