"""The bench measurement protocol itself (r3/r4 falsifiability asks +
r4 verdict #9 compile-time budget): pure-python tests of bench._diff_time
— no device, no model, just the timing contract the driver's records
rely on."""

import time

import numpy as np
import pytest

import bench


class FakeRunner(object):
    """run_at(steps) stub with controllable per-step cost + warm cost."""

    def __init__(self, per_step=0.004, first_extra=0.05, overhead=0.0):
        self.calls = []
        self.per_step = per_step
        self.first_extra = first_extra
        self.overhead = overhead  # additive per-call cost (tunnel RTT)

    def __call__(self, steps):
        extra = self.first_extra if steps not in [
            s for s, _ in self.calls
        ] else 0.0
        self.calls.append((steps, extra))
        time.sleep(steps * self.per_step + extra + self.overhead)


def test_diff_time_record_carries_protocol_fields():
    r = FakeRunner()
    dt, info = bench._diff_time(r, 2, 6, return_info=True,
                                scale_steps=False)
    # the per-step estimate lands near the configured cost
    assert 0.5 * r.per_step < dt < 3.0 * r.per_step
    # r4 falsifiability fields
    assert info["steps"] == [2, 6]
    assert set(info["raw_chunk_s"]) == {"2", "6"}
    assert all(
        len(v) >= bench.TIMING_CHUNKS for v in info["raw_chunk_s"].values()
    )
    assert set(info["spread"]) == {"2", "6"}
    assert isinstance(info["stable"], bool)
    # r4 verdict #9: trace+compile budget column — the warm pass is the
    # only one that pays compile, and its extra cost must be visible
    assert set(info["warm_s"]) == {"2", "6"}
    assert info["warm_s"]["2"] >= r.first_extra * 0.5
    # warm includes the first-run extra; steady chunks must not
    assert min(info["raw_chunk_s"]["2"]) < r.first_extra + 2 * 0.004 * 2


def test_diff_time_single_outlier_trimmed_stable(monkeypatch):
    """One gross tunnel stall among >=4 chunks must not flip the
    verdict: the worst chunk is dropped (visibly) for the flag.
    SPREAD_LIMIT is widened so host scheduler jitter on these small
    sleeps cannot register as a second outlier (timing-flake guard)."""
    monkeypatch.setattr(bench, "SPREAD_LIMIT", 0.3)
    r = FakeRunner(per_step=0.02, first_extra=0.01)
    calls = {"n": 0}

    def run_at(s):
        calls["n"] += 1
        if calls["n"] == 5:  # one timed chunk stalls hard (~10x chunk)
            time.sleep(0.4)
        r(s)

    _, info = bench._diff_time(run_at, 2, 6, return_info=True,
                               scale_steps=False)
    assert info["stable"] is True
    assert info["outliers_dropped"]
    s_hit = next(iter(info["outliers_dropped"]))
    assert info["spread"][s_hit] > bench.SPREAD_LIMIT
    assert info["spread_trimmed"][s_hit] <= bench.SPREAD_LIMIT
    # the raw audit trail keeps the stalled chunk
    assert max(info["raw_chunk_s"][s_hit]) > 0.4


def test_diff_time_repeated_outliers_stay_unstable(monkeypatch):
    """Two stalls in one count cannot be trimmed away — the record
    honestly reports stable=false."""
    monkeypatch.setattr(bench, "SPREAD_LIMIT", 0.3)
    r = FakeRunner(per_step=0.02, first_extra=0.01)
    calls = {"n": 0}

    def run_at(s):
        calls["n"] += 1
        if calls["n"] in (5, 11):
            time.sleep(0.4)
        r(s)

    _, info = bench._diff_time(run_at, 2, 6, return_info=True,
                               scale_steps=False)
    assert info["stable"] is False


def test_diff_time_smooth_drift_not_trimmed():
    """Run-to-run drift just past the gate is NOT a stall: with no
    chunk grossly above the median, nothing is trimmed and the record
    stays stable=false."""
    drifts = iter([0.0, 0.01, 0.02, 0.03, 0.04, 0.05] * 4)

    def run_at(s):
        time.sleep(s * 0.05 + next(drifts))

    _, info = bench._diff_time(run_at, 2, 6, return_info=True,
                               scale_steps=False)
    assert info["stable"] is False
    assert "outliers_dropped" not in info


def test_best_banked_headline_points_at_stable_record():
    """On an outage day the bench_error line references the best banked
    stable headline from the committed evidence file, labeled as not
    being this run's measurement."""
    rec = bench._last_banked_headline()
    assert rec is not None
    assert rec["value"] > 0
    assert rec["unit"] == "images/sec"
    assert rec["source"] == "BENCH_r05_builder.jsonl"
    assert "NOT this run's measurement" in rec["note"]
    # selection is best-of-stable, not file order: no stable record in
    # the file exceeds the one chosen (path anchored to bench.__file__,
    # NOT the CWD — pytest may be launched from anywhere)
    import json as _json
    import os as _os

    path = _os.path.join(
        _os.path.dirname(_os.path.abspath(bench.__file__)),
        "BENCH_r05_builder.jsonl",
    )
    vals = [
        r.get("value", 0)
        for r in (
            _json.loads(l) for l in open(path) if l.strip()
        )
        if r.get("metric") == "resnet50_train_images_per_sec_per_chip"
        and r.get("stable")
    ]
    assert vals and max(vals) == rec["value"]


def test_best_banked_headline_never_raises(tmp_path, monkeypatch):
    """The helper feeds the watchdog's must-exit path: malformed,
    value-less, or binary-corrupted evidence must degrade to partial
    data or None, never an exception."""
    evil = tmp_path / "BENCH_r05_builder.jsonl"
    evil.write_bytes(
        b'{"metric": "resnet50_train_images_per_sec_per_chip", '
        b'"stable": true}\n'  # stable but no value
        b"not json at all\n"
        b'{"metric": "resnet50_train_images_per_sec_per_chip", '
        b'"stable": true, "value": 100.0, "unit": "images/sec"}\n'
        b"\xff\xfe binary garbage \x00\n"
    )
    real_join = bench.os.path.join
    monkeypatch.setattr(
        bench.os.path, "join",
        lambda *a: str(evil) if a[-1] == "BENCH_r05_builder.jsonl"
        else real_join(*a))
    rec = bench._last_banked_headline()
    assert rec is not None and rec["value"] == 100.0


def test_best_banked_headline_is_cwd_independent(tmp_path, monkeypatch):
    """The helper must resolve the evidence file relative to
    bench.__file__, never the CWD: the watchdog's must-exit path can run
    with any working directory (regression for the rule now also
    followed by test_best_banked_headline_points_at_stable_record)."""
    monkeypatch.chdir(tmp_path)  # no BENCH_r05_builder.jsonl here
    rec = bench._last_banked_headline()
    assert rec is not None and rec["value"] > 0


def test_diff_time_drops_sub10ms_probe_from_seeds(monkeypatch):
    """A sub-10 ms probe is the r3 memoized/ack-only signature: it must
    neither drive chunk scaling NOR be merged into raw[] as a steady
    chunk (ADVICE r5 — it deflated dt_min and inflated spread)."""
    monkeypatch.setattr(bench, "MIN_CHUNK_S", 0.10)
    monkeypatch.setattr(bench, "SPREAD_LIMIT", 10.0)  # one round exactly
    r = FakeRunner(per_step=0.001, first_extra=0.01)
    _, info = bench._diff_time(r, 2, 6, return_info=True)
    assert info["chunk_scale"] == 1  # no scaling off the suspect probe
    # raw[] holds ONLY the timed loop's chunks; the ~2 ms probe was
    # dropped instead of seeding the low count
    assert len(info["raw_chunk_s"]["2"]) == bench.TIMING_CHUNKS
    assert len(info["raw_chunk_s"]["6"]) == bench.TIMING_CHUNKS


def test_diff_time_prescale_probe_not_reused_at_final_count(monkeypatch):
    """When the solved scale lands s_lo exactly on base_hi (here (2,6)
    at scale 3 -> s_lo == 6), the pre-scale base_hi probe must NOT be
    merged into raw[s_lo]: it predates the floor verification and could
    consume the single-outlier trim allowance (ADVICE r5). Only the
    post-scale verification probe is reused."""
    monkeypatch.setattr(bench, "MIN_CHUNK_S", 0.12)
    monkeypatch.setattr(bench, "SPREAD_LIMIT", 10.0)  # one round exactly
    r = FakeRunner(per_step=0.02, first_extra=0.01)
    _, info = bench._diff_time(r, 2, 6, return_info=True)
    assert info["chunk_scale"] == 3
    assert info["steps"] == [6, 18]
    # s_lo == 6 == base_hi: TIMING_CHUNKS timed chunks + the ONE
    # post-scale verification probe — the pre-scale probe at 6 is gone
    assert len(info["raw_chunk_s"]["6"]) == bench.TIMING_CHUNKS + 1
    assert len(info["raw_chunk_s"]["18"]) == bench.TIMING_CHUNKS


def test_diff_time_inversion_raises():
    """A pathological runner where more steps are FASTER must be
    rejected, not silently recorded (timing inversion guard)."""

    def weird(steps):
        time.sleep(0.06 if steps == 2 else 0.01)

    with pytest.raises(AssertionError, match="timing inversion"):
        bench._diff_time(weird, 2, 6, return_info=True, scale_steps=False)


def test_diff_time_scales_short_chunks(monkeypatch):
    """r5: a chunk shorter than MIN_CHUNK_S cannot pass the spread gate
    against additive tunnel jitter, so the counts are scaled up until
    the low chunk reaches the floor (run_at must accept any count)."""
    monkeypatch.setattr(bench, "MIN_CHUNK_S", 0.10)
    r = FakeRunner(per_step=0.012, first_extra=0.01)
    dt, info = bench._diff_time(r, 2, 6, return_info=True)
    # probes: t(2)~0.024s, t(6)~0.072s -> per_step 0.012, overhead 0
    # -> scale ceil(0.10/0.024) = 5
    scale = info["chunk_scale"]
    assert scale > 1
    assert info["steps"] == [2 * scale, 6 * scale]
    assert set(info["raw_chunk_s"]) == {str(2 * scale), str(6 * scale)}
    # the converged low chunk actually reaches the floor
    assert min(info["raw_chunk_s"][str(2 * scale)]) >= 0.8 * 0.10
    # the estimate still lands near the configured per-step cost
    assert 0.5 * r.per_step < dt < 3.0 * r.per_step
    # the scaled counts were warmed (compile budget stays visible);
    # the original low count's warm is kept for the audit trail
    assert str(2 * scale) in info["warm_s"]
    assert str(6 * scale) in info["warm_s"]


def test_diff_time_rescales_against_call_overhead(monkeypatch):
    """Per-call overhead inflates a naive single-probe scale
    (undershooting the floor by (scale-1)*overhead); the two-point
    solve separates overhead from per-step cost and must land the low
    chunk on the floor anyway."""
    monkeypatch.setattr(bench, "MIN_CHUNK_S", 0.2)
    r = FakeRunner(per_step=0.005, first_extra=0.0, overhead=0.05)
    _, info = bench._diff_time(r, 2, 6, return_info=True)
    scale = info["chunk_scale"]
    # naive ceil(floor/probe) from t(2)=0.06s would pick 4 -> chunk
    # 0.09s; the solve must go further (exact answer: 15)
    assert scale > 4
    assert min(info["raw_chunk_s"][str(2 * scale)]) >= 0.8 * 0.2


def test_diff_time_corrects_stalled_hi_probe(monkeypatch):
    """A stall during the s_hi probe inflates the fitted per-step cost,
    so the solved scale undershoots the floor; the post-scale
    verification probe must catch it and rescale once."""
    monkeypatch.setattr(bench, "MIN_CHUNK_S", 0.2)
    per_s_calls = {}

    def run_at(s):
        per_s_calls[s] = per_s_calls.get(s, 0) + 1
        extra = 0.01 if per_s_calls[s] == 1 else 0.0  # compile on warm
        if s == 6 and per_s_calls[s] == 2:
            extra += 0.3  # the probe call at s_hi stalls
        time.sleep(s * 0.01 + extra)

    _, info = bench._diff_time(run_at, 2, 6, return_info=True)
    scale = info["chunk_scale"]
    # solve off the stalled pair picks ~2; the verified chunk (0.04 s)
    # forces the correction to ceil(2*0.2/0.04) = 10
    assert scale >= 8
    assert info["steps"] == [2 * scale, 6 * scale]
    assert min(info["raw_chunk_s"][str(2 * scale)]) >= 0.8 * 0.2


def test_diff_time_suspect_probe_does_not_scale(monkeypatch):
    """A probe under 10 ms is the r3 memoized/ack-only signature: scaling
    off it would saturate at MAX_CHUNK_SCALE and waste the side budget,
    so the requested counts are kept instead."""
    monkeypatch.setattr(bench, "MIN_CHUNK_S", 1.0)
    # ~2 ms probe: suspect (under 10 ms) yet above the sleep-scheduler
    # noise floor, so the timed chunks still order correctly — with the
    # suspect probe no longer seeding raw[], a 0.1 ms/step runner sat
    # entirely inside scheduler jitter and inverted the differencing
    r = FakeRunner(per_step=0.001, first_extra=0.0)
    _, info = bench._diff_time(r, 2, 6, return_info=True)
    assert info["chunk_scale"] == 1
    assert info["steps"] == [2, 6]


def test_input_pipeline_workload_prefetch_overlap(tmp_path, monkeypatch):
    """ISSUE 3 CI satellite: the `input_pipeline` workload runs green on
    the host backend, is deterministic in WHAT it delivers (checksums
    match between the two runs), and shows the prefetch-on loader-wait
    fraction strictly below prefetch-off on the same fixed-seed trace.
    The decode cost is pinned with the GIL-releasing sleep knob so the
    contrast is about the pipeline, not scheduler jitter."""
    monkeypatch.setenv("BENCH_DATA_DIR", str(tmp_path))
    rec = bench.bench_input_pipeline(
        n_shards=2, chunks_per_shard=3, records_per_chunk=32, batch=16,
        step_s=0.004, decode_sleep_s=0.0003)
    assert rec["prefetch_off"]["records"] == 2 * 3 * 32
    assert rec["prefetch_on"]["records"] == 2 * 3 * 32
    # prefetch must never change the delivered stream
    assert rec["prefetch_on"]["checksum"] == rec["prefetch_off"]["checksum"]
    # the acceptance inequality: overlap strictly cuts the wait share
    assert rec["wait_fraction_on"] < rec["wait_fraction_off"], rec
    assert rec["overlap_speedup"] > 1.0
    # record contract fields the driver's evidence trail relies on
    for k in ("batches_per_sec_on", "batches_per_sec_off", "trace",
              "num_workers", "prefetch_batches"):
        assert k in rec


def test_training_sentinel_workload_contract():
    """ISSUE 10 acceptance: the `training_sentinel` row cannot decay
    into a no-op — on the fixed-seed poisoned run the bench itself
    raises unless >=1 sentinel trip happens, every rollback lands on
    the last KNOWN-GOOD step (the next incarnation resumes exactly
    there), the poison chunk id appears in the quarantine journal
    exactly once (and is the ONLY chunk quarantined — attribution is
    exact on this trace), training completes with a finite committed
    loss curve bit-identical to a clean run that never saw the chunk,
    and, separately, resume with a corrupted LATEST checkpoint
    succeeds with zero manual intervention (bad dir renamed .corrupt,
    the failing CRC named, the walk-back landing one step earlier)."""
    rec = bench.bench_training_sentinel()
    assert rec["sentinel_trips"] >= 1
    assert rec["rollbacks_landed_on_known_good"]
    assert rec["quarantined_chunks"] == [rec["poison_chunk"]]
    assert rec["poison_journaled_once"]
    assert rec["curve_finite"] and np.isfinite(rec["final_loss"])
    assert rec["curve_matches_clean"]
    assert rec["record_stream_matches_clean"]
    assert rec["incarnations"] >= 3  # trip, replay-trip, recovery
    cr = rec["corrupt_resume"]
    assert cr["ok"]
    assert cr["walked_back_to"] < cr["corrupted_step"]
    assert cr["renamed_to"].endswith(".corrupt")
    assert "CRC" in cr["problem"]


def test_training_sentinel_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list
    (the registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"training_sentinel", bench_training_sentinel' in src


def test_serving_shared_prefix_workload_contract():
    """ISSUE 4 satellite: the `serving_shared_prefix` row cannot decay
    into a no-op — on the fixed-seed shared-header trace (tiny model,
    host backend) the cache-ON run computes STRICTLY fewer prefill
    tokens than cache-OFF at the same fixed per-token cost (the counted
    tokens, not wall time), the hit rate is positive, and the bench
    itself asserts greedy outputs identical between the two runs. A
    handful of requests are also checked against the sequential
    generate() oracle by the slow-marked companion drill below."""
    rec = bench.bench_serving_shared_prefix(
        n_requests=6, families=2, header_len=8, family_len=4,
        max_slots=2, dim=32, heads=4, layers_n=2, vocab=64, max_len=64,
        chunk_tokens=8, block_tokens=4, cache_tokens=64)
    assert rec["prefill_tokens_computed_on"] < \
        rec["prefill_tokens_computed_off"], rec
    assert rec["prefix_hit_rate"] > 0
    assert rec["prefix_tokens_saved"] > 0
    assert rec["decode_traces_on"] == 1


@pytest.mark.slow  # ~8s of sequential generate() oracles on top of the
# tier-1 contract above (which already pins on==off outputs in-bench)
def test_serving_shared_prefix_outputs_match_generate():
    """ISSUE 4 acceptance on the bench trace itself: requests built
    exactly like the workload's (same seed-0 draw order) decode to
    sequences bit-identical to sequential generate() through the
    prefix-cached chunked engine."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer as tlm

    # rebuild the deterministic request stream the bench derives from
    # seed 0 (header, families, arrival draws, then per-request draws)
    cfg = tlm.TransformerConfig(vocab=64, dim=32, heads=4, layers=2,
                                max_len=64)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    header = rng.randint(0, 64, 8).astype(np.int32)
    fam = [rng.randint(0, 64, 4).astype(np.int32) for _ in range(2)]
    rng.exponential(1.0 / 2.0, 6)  # the n_requests=6 arrival draws
    # precede the per-request draws in the bench's stream
    from paddle_tpu.serving import ServingEngine

    eng = ServingEngine(params, cfg, max_slots=2,
                        prefill_chunk_tokens=8, prefix_cache_tokens=64,
                        prefix_block_tokens=4)
    hs = []
    for _ in range(3):  # first 3 requests of the trace suffice
        f = int(rng.randint(2))
        tail = rng.randint(0, 64, int(rng.randint(4, 13))).astype(np.int32)
        prompt = np.concatenate([header, fam[f], tail])
        n = int(rng.randint(4, 11))
        hs.append((prompt, n, eng.submit(prompt, n, publish_len=12)))
        eng.run()  # sequentially, so request 2+ hits the pool
    assert eng.prefix_cache.stats()["hits"] >= 2
    for prompt, n, h in hs:
        want = np.asarray(
            tlm.generate(params, jnp.asarray(prompt)[None], cfg, n))[0]
        got = np.concatenate([h.prompt, np.asarray(h.tokens, np.int32)])
        np.testing.assert_array_equal(got, want)


def test_serving_fleet_workload_contract():
    """ISSUE 6 satellite: the `serving_fleet` row cannot decay into a
    no-op — on the fixed-seed shared-header trace (tiny model, host
    backend) the kill drill loses ZERO requests and answers none
    twice, exactly one failover happens, the pools actually reuse
    prefixes, and the bench itself raises unless outputs are
    token-identical across the single-replica, fleet+kill, and
    affinity-off runs. (The strict affinity-on > affinity-off reuse
    inequality is pinned by the dedicated no-kill drill in
    test_serving_fleet.py — here the kill erases one replica's pool
    mid-trace, so the cross-run contrast is reported, not asserted.)"""
    rec = bench.bench_serving_fleet(
        n_replicas=2, n_requests=6, families=2, header_len=8,
        family_len=4, max_slots=2, dim=32, heads=4, layers_n=2,
        vocab=64, max_len=64, chunk_tokens=8, block_tokens=4,
        cache_tokens=96)
    assert rec["requests_lost"] == 0, rec
    assert rec["duplicate_completions"] == 0, rec
    assert rec["failovers"] == 1, rec
    assert rec["resubmitted"] >= 0
    assert rec["completed"] == 6 + 2  # paced trace + warm wave
    assert rec["prefix_hit_rate_on"] > 0, rec
    assert rec["prefix_tokens_saved_affinity_on"] > 0, rec
    assert rec["kill_drill"]["replica"] == 0


def test_serving_paged_workload_contract():
    """ISSUE 7 acceptance: the `serving_paged` row cannot decay into a
    no-op — at ONE fixed KV budget on the fixed-seed Poisson trace the
    paged block pool holds STRICTLY more resident slots than the
    [S, max_len]-slab-equivalent engine, the speculative run reports an
    accept-rate (drafts were actually verified), the decode and
    spec-verify steps trace exactly once each, and the bench itself
    raises unless greedy outputs are token-identical across the slab,
    paged, and speculative runs (zero output divergence)."""
    rec = bench.bench_serving_paged(
        n_requests=6, max_slots=6, dim=32, heads=4, layers_n=2,
        vocab=64, max_len=64, block_tokens=4, budget_tokens=128,
        spec_draft_len=4)
    assert rec["slots_resident_paged"] > rec["slots_resident_slab"], rec
    assert rec["slots_resident_slab"] == 128 // 64  # the slab wall
    assert rec["spec_accept_rate"] is not None
    assert 0.0 <= rec["spec_accept_rate"] <= 1.0
    assert rec["spec_windows"] > 0
    assert rec["decode_traces_paged"] == 1
    assert rec["spec_verify_traces"] == 1
    # reservation discipline visible in the row: early-EOS/short tails
    # returned capacity, and the pool never exceeded its budget
    assert rec["peak_kv_blocks_in_use"] <= rec["kv_pool_blocks"]


def test_serving_paged_kernel_workload_contract():
    """ISSUE 13 acceptance: the `serving_paged_kernel` row cannot decay
    into a no-op — on the fixed-seed shared-header trace the fused
    (Pallas table-walk) run performs ZERO `_paged_view` gathers, keeps
    the one-compiled-step discipline (fused decode and spec-verify each
    traced exactly once), and the bench itself hard-raises unless
    greedy outputs are token-identical between the gather and fused
    runs (its divergence gate stays armed under -O)."""
    rec = bench.bench_serving_paged_kernel(
        n_requests=5, max_slots=3, dim=32, heads=4, layers_n=2,
        vocab=64, max_len=64, block_tokens=8, chunk_tokens=16,
        cache_tokens=256, spec_draft_len=4)
    assert rec["paged_view_calls_fused"] == 0, rec
    assert rec["decode_traces_fused"] == 1, rec
    assert rec["spec_verify_traces_fused"] == 1, rec
    assert rec["paged_kernel_fused"] == "fused"
    assert rec["paged_kernel_gather"] == "gather"
    # the reuse surface was actually exercised (aliasing + chunking):
    # a trace that stopped covering it would pass identity vacuously
    assert rec["prefill_traces_fused"] >= 1
    assert rec["tokens_out"] > 0


def test_serving_quant_workload_contract():
    """ISSUE 14 acceptance: the `serving_quant` row cannot decay into
    a no-op — at ONE fixed KV byte budget on the fixed-seed
    shared-header trace, int8 KV holds STRICTLY more resident slots
    than f32 (the bench itself hard-raises otherwise), every
    variant's greedy-prefix agreement vs the f32 run meets its armed
    quality gate (ditto), the pool multiplier reflects int8's ~4x
    blocks per byte, bytes-per-resident-token drops accordingly (with
    the scale side-band's overhead visible, not hidden), and the
    one-compiled-step discipline survives quantization."""
    rec = bench.bench_serving_quant(
        n_requests=6, max_slots=6, dim=32, heads=4, layers_n=2,
        vocab=64, max_len=64, block_tokens=8, chunk_tokens=16,
        cache_tokens=256)
    v = rec["variants"]
    assert v["int8"]["slots_resident"] > v["none"]["slots_resident"], rec
    assert v["int8"]["kv_pool_blocks"] > 3 * v["none"]["kv_pool_blocks"]
    # agreement met its gate for every variant (the bench raises on a
    # miss — these pin the record carries the evidence)
    for name, row in v.items():
        assert row["agreement_vs_f32"] >= row["agreement_gate"], (name, row)
    assert v["none"]["agreement_vs_f32"] == 1.0
    # bytes-per-resident-token: int8 payload is 1/4 f32's, plus the
    # per-block scale overhead (2 bands x layers x heads x 4B / Bt)
    f32_bpt = v["none"]["bytes_per_resident_token"]
    int8_bpt = v["int8"]["bytes_per_resident_token"]
    assert int8_bpt < f32_bpt / 3
    assert int8_bpt > f32_bpt / 4  # the scale side-band is not free
    assert rec["pool_multiplier_int8"] > 3
    assert v["weight_int8"]["weight_quant"] == "int8"
    assert v["weight_int8"]["kv_quant"] == "none"


def test_serving_quant_gate_stays_armed():
    """The quality gate is a hard raise, not a report: a floor no run
    can meet must blow up the bench (guards against the gate decaying
    into a logged number nobody checks)."""
    with pytest.raises(RuntimeError, match="quality gate"):
        bench.bench_serving_quant(
            n_requests=4, max_slots=4, dim=32, heads=4, layers_n=2,
            vocab=64, max_len=64, block_tokens=8, chunk_tokens=16,
            cache_tokens=256, agreement_gate=1.01)


def test_serving_quant_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list
    (the registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"serving_quant", bench_serving_quant' in src


def test_kv_bytes_per_token_cost_model():
    """ISSUE 14 satellite: bench_offline's bytes-per-token takes the
    storage dtype into account — int8 cuts the f32 payload 4x plus an
    explicit scale-amortisation term (never free), and the roofline
    record predicts a strictly higher HBM-bound tokens/s for int8
    weights + int8 KV than for the bf16/f32 baseline."""
    import bench_offline as bo

    f32 = bo.kv_bytes_per_token(2, 4, 8, "none", 8, act_itemsize=4)
    i8 = bo.kv_bytes_per_token(2, 4, 8, "int8", 8)
    assert f32 == 2 * 2 * 4 * 8 * 4
    assert i8 == 2 * 2 * 4 * 8 * 1 + 2 * 2 * 4 * 4 / 8.0
    assert f32 / 4 < i8 < f32 / 3
    rec = bo.offline_serving_quant_roofline(layers_n=2, dim=64, heads=4,
                                            vocab=256, S=4, context=64,
                                            block_tokens=8)
    base = rec["w_none_bf16__kv_none"]["pred_tokens_per_sec_hbm_bound"]
    best = rec["w_int8__kv_int8"]["pred_tokens_per_sec_hbm_bound"]
    assert best > base
    assert rec["pred_uplift_int8_over_bf16"] > 1.0


def test_serving_paged_kernel_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list
    (the registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"serving_paged_kernel", bench_serving_paged_kernel' in src


def test_serving_slo_workload_contract():
    """ISSUE 8 acceptance: the `serving_slo` row cannot decay into a
    no-op — on the fixed-seed Poisson trace of deadline-carrying
    interactive requests, ZERO requests expire under the gray-slow
    drill (the slowed replica is demoted and its work hedged to
    survivors with token-level resume), resumed requests re-decode
    zero already-emitted tokens (the bench audits the journal: per
    rid, progress deltas concatenate EXACTLY to the done record — a
    re-decoded token would appear twice — and raises otherwise), the
    replica is probed and restored under the SAME incarnation (warm
    pool, no fresh spawn), and the bench itself raises unless outputs
    are token-identical between the healthy and gray runs."""
    rec = bench.bench_serving_slo(n_requests=8)
    assert rec["expired_healthy"] == 0, rec
    assert rec["expired_gray"] == 0, rec
    assert rec["requests_lost"] == 0, rec
    assert rec["demotions_gray"] >= 1, rec
    assert rec["restores_gray"] >= 1, rec
    assert rec["restored_same_incarnation"], rec
    # token-level resume actually ran, and the journal audit (which
    # hard-raises on any re-decoded token) saw the multi-holder rids
    assert rec["resumed_requests"] >= 1, rec
    assert rec["resumed_rids_journal"] >= 1, rec
    assert rec["redecoded_tokens"] == 0, rec
    # the tail bound: gray p99 TTFT within healthy + the slow window
    assert rec["p99_ttft_gray_s"] is not None
    assert rec["p99_ttft_gray_s"] < \
        rec["p99_ttft_healthy_s"] + rec["p99_ttft_excess_bound_s"], rec


def test_serving_elastic_workload_contract():
    """ISSUE 11 acceptance: the `serving_elastic` row cannot decay
    into a no-op — on the fixed-seed Poisson burst of deadline-carrying
    requests, the elastic run must spawn >= 1 replica mid-burst and
    retire >= 1 after it (full scale-up -> scale-down cycle), migrate
    >= 1 request from the prefill tier to a decode tier at first token,
    complete exactly one mid-trace roll_weights onto a CRC-verified
    checkpoint, abort exactly one rollout on the corrupted candidate
    (fleet untouched — the bench hard-raises if any live replica left
    the rolled version), expire and lose NOTHING, and produce outputs
    token-identical to the static tiered fleet (the bench raises on
    any divergence, any duplicated rid, and any J-code — including the
    J009 mixed-version fence — from the journal replay)."""
    rec = bench.bench_serving_elastic(n_requests=8)
    assert rec["expired"] == 0, rec
    assert rec["requests_lost"] == 0, rec
    assert rec["replicas_spawned"] >= 1, rec
    assert rec["replicas_retired"] >= 1, rec
    assert rec["migrations"] >= 1, rec
    assert rec["rollouts_completed"] == 1, rec
    assert rec["rollout_aborts"] == 1, rec
    assert rec["outputs_identical_to_static"], rec
    # the rollout actually moved the fleet: version 1 responses exist
    # alongside pre-rollout version 0 ones, and the fleet ends on 1
    assert rec["weights_version_final"] == 1, rec
    assert 1 in rec["done_versions_seen"], rec
    # migrations rode the journaled resume path (tokens carried over)
    assert rec["resumed_requests"] >= 1, rec


def test_serving_multitenant_workload_contract():
    """ISSUE 12 acceptance: the `serving_multitenant` row cannot
    decay into a no-op — on the fixed-seed 3-tenant Poisson mix with
    one tenant bursting past its quota, the well-behaved
    deadline-class tenants record ZERO deadline misses, the burst is
    shed via TenantQuotaExceeded and never FleetSaturated (and the
    bench checks the journal holds exactly the accepted submits — a
    shed is never journaled), the 3-adapter-through-2-slot pool
    LRU-pages (>= 1 eviction), the zoo batch lane's Executor results
    match the direct run, and every tenant's outputs are
    token-identical to its per-tenant sequential run (all of these
    hard-raise in-bench; the assertions here pin the row's shape)."""
    rec = bench.bench_serving_multitenant(n_requests=6)
    assert rec["deadline_misses_well_behaved"] == 0, rec
    assert rec["requests_lost"] == 0, rec
    assert rec["quota_shed"] == 4, rec
    assert rec["hog_admitted"] == 2, rec
    assert rec["fleet_saturated_shed"] == 0, rec
    assert rec["adapter_evictions"] >= 1, rec
    assert rec["batch_jobs_completed"] == 3, rec
    assert rec["outputs_identical_per_tenant"], rec
    assert rec["zoo_results_match_executor"], rec
    # every tenant shows up in the per-tenant O(1) metrics
    assert set(rec["per_tenant"]) == {"alpha", "beta", "gamma",
                                      "hog", "zoo"}, rec
    assert rec["per_tenant"]["zoo"]["completed"] == 3, rec


def test_serving_integrity_workload_contract():
    """ISSUE 15 acceptance: the `serving_integrity` row cannot decay
    into a no-op — on the fixed-seed shared-header Poisson trace, the
    clean run must trip NOTHING (false-positive bar, with canaries
    actually completing), the garble@ drill must trip exactly once via
    a known-answer CANARY mismatch and the flip@ drill exactly once
    via a block FINGERPRINT mismatch, each quarantining the corrupt
    replica under a fresh incarnation, with outputs token-identical to
    the clean run (zero tainted tokens survive — the taint windows
    re-decoded on the healthy survivor), zero rids lost, and every
    journal green through the DFA --expect-closed including the J010
    taint fence (all of these hard-raise in-bench; the assertions here
    pin the row's shape)."""
    rec = bench.bench_serving_integrity(n_requests=6)
    assert rec["trips_clean"] == 0, rec
    assert rec["canaries_ok_clean"] >= 2, rec
    assert rec["trips_garble"] == 1, rec
    assert rec["trip_kind_garble"] == {"canary": 1}, rec
    assert rec["trips_flip"] == 1, rec
    assert rec["trip_kind_flip"] == {"fingerprint": 1}, rec
    assert rec["fp_mismatches_flip"] >= 1, rec
    assert rec["requests_lost"] == 0, rec
    assert rec["outputs_identical"], rec


def test_serving_kv_handoff_workload_contract():
    """ISSUE 16 acceptance: the `serving_kv_handoff` row cannot decay
    into a no-op — on the fixed-seed shared-header Poisson trace
    against ONE store directory, the cold phase must actually spill
    (>= 1 durable record), the tiered handoff phase must migrate >= 1
    request with tokens_recomputed_at_migration EXACTLY 0 and >= 1
    verified package import (re-prefill demoted to a counted
    fallback), the kill drill must leave the killed replica dead with
    nothing lost, and the warm-restarted fleet must warm >= 1 block
    from the store and serve the first shared-header request with
    strictly fewer prefill tokens than the cold phase's first request
    — all with outputs token-identical across the four phases and
    every journal green through the DFA --expect-closed including the
    J011 handoff fence (all of these hard-raise in-bench; the
    assertions here pin the row's shape)."""
    rec = bench.bench_serving_kv_handoff(n_requests=6)
    assert rec["store_records_after_cold"] >= 1, rec
    assert rec["store_spilled_blocks"] >= 1, rec
    assert rec["migrations_handoff"] >= 1, rec
    assert rec["handoff_packages"] >= 1, rec
    assert rec["handoff_imports"] >= 1, rec
    assert rec["tokens_recomputed_at_migration"] == 0, rec
    assert rec["store_warm_blocks"] >= 1, rec
    assert rec["warm_first_prefill_tokens"] \
        < rec["cold_first_prefill_tokens"], rec
    assert rec["outputs_identical"], rec


@pytest.mark.slow  # ~20s: engine compiles + 2-rate socket sweep +
# kill/disconnect drills; tier-1 keeps the registration pin below and
# the ScriptEngine socket drills in test_frontdoor.py
def test_serving_frontdoor_workload_contract():
    """ISSUE 18 acceptance: the `serving_frontdoor` row cannot decay
    into a no-op — on a fixed-seed 2-tenant open-loop sweep over REAL
    sockets, the wire answer must match the direct fleet answer, the
    sweep must exhibit a measurable capacity knee (goodput flat vs
    offered + typed sheds), the kill drill must fail over >= 1
    replica with zero lost/duplicated rids and zero stream-vs-result
    divergence, the disconnect drill must claw back >= 1 abandoned
    stream as a journaled cancel, and the journal must replay green
    through the DFA --expect-closed including the cancelled terminal
    (all hard-raised in-bench; the assertions here pin the row's
    shape). Shrunk knobs: 2 rates bracketing the knee, short windows
    — the knee is relative, the drills absolute."""
    rec = bench.bench_serving_frontdoor(sweep_duration_s=0.6,
                                        rate_factors=(0.25, 2.5))
    assert rec["knee_rate_rps"] is not None, rec
    assert rec["requests_lost"] == 0, rec
    assert rec["duplicates"] == 0, rec
    assert rec["stream_divergent"] == 0, rec
    assert rec["kill_failovers"] >= 1, rec
    assert rec["cancelled"] >= 1, rec
    assert rec["disconnect_cancels"] >= 1, rec
    assert rec["wire_vs_direct_identical"], rec
    assert len(rec["sweep"]) == 2, rec
    top = rec["sweep"][-1]
    assert sum(top["shed"].values()) >= 1, rec
    assert rec["baseline_shed_alice"] == 0, rec


def test_serving_frontdoor_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list
    (the registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"serving_frontdoor", bench_serving_frontdoor' in src


@pytest.mark.slow  # ~20s: 4 engine variants, each paying its compile
# on an unmeasured warm-up request; tier-1 keeps the registration pin
# below and the full identity sweep in test_serving_megabatch.py
def test_serving_megabatch_workload_contract():
    """ISSUE 19 acceptance: the `serving_megabatch` row cannot decay
    into a no-op — one fixed-seed mixed greedy/sampled Poisson trace
    replayed across (decode_window, async_dispatch) variants must be
    token-identical everywhere, trace decode exactly once per variant
    (hard-raised in-bench), and show host-overhead(K=8, async) below
    host-overhead(K=1, sync) — the measured amortization the tentpole
    claims. The assertions here pin the row's shape: the headline
    overhead pair, per-variant steps/token (strictly amortized at
    K=8) and band-upload counts (a steady window loop re-uploads
    nothing new per K)."""
    rec = bench.bench_serving_megabatch(n_requests=8, windows=(1, 8))
    assert rec["outputs_identical"], rec
    assert len(rec["variants"]) == 4, rec
    lo = rec["host_overhead_K8_async"]
    hi = rec["host_overhead_K1_sync"]
    assert lo is not None and hi is not None and lo < hi, rec
    for name, row in rec["variants"].items():
        assert row["host_overhead_frac"] is not None, (name, row)
        assert row["steps_per_token"] > 0, (name, row)
        assert row["band_uploads"] >= 0, (name, row)
    assert rec["variants"]["K8_sync"]["steps_per_token"] \
        < rec["variants"]["K1_sync"]["steps_per_token"], rec


def test_serving_megabatch_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list
    (the registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"serving_megabatch", bench_serving_megabatch' in src


def test_serving_kv_handoff_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list
    (the registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"serving_kv_handoff", bench_serving_kv_handoff' in src


def test_serving_integrity_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list
    (the registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"serving_integrity", bench_serving_integrity' in src


def test_serving_multitenant_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list
    (the registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"serving_multitenant", bench_serving_multitenant' in src


def test_serving_elastic_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list
    (the registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"serving_elastic", bench_serving_elastic' in src


def test_serving_slo_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list
    (the registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"serving_slo", bench_serving_slo' in src


def test_serving_paged_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list
    (the registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"serving_paged", bench_serving_paged' in src


def test_serving_fleet_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list
    (the registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"serving_fleet", bench_serving_fleet' in src


def test_serving_shared_prefix_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list
    (the registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"serving_shared_prefix", bench_serving_shared_prefix' in src


def test_input_pipeline_registered_in_bench_main():
    """The workload is wired into bench.main()'s side-workload list (the
    registration is what lands it in the driver's record)."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"input_pipeline", bench_input_pipeline' in src


def test_diff_time_no_scaling_above_floor(monkeypatch):
    """A chunk already at the floor keeps the requested counts — with a
    probe above the 10 ms suspect threshold, so this pins the floor
    comparison itself, not the suspect guard."""
    monkeypatch.setattr(bench, "MIN_CHUNK_S", 0.015)
    r = FakeRunner(per_step=0.012, first_extra=0.01)  # probe ~24 ms
    _, info = bench._diff_time(r, 2, 6, return_info=True)
    assert info["chunk_scale"] == 1
    assert info["steps"] == [2, 6]
