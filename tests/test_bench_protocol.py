"""The bench measurement protocol itself (r3/r4 falsifiability asks +
r4 verdict #9 compile-time budget): pure-python tests of bench._diff_time
— no device, no model, just the timing contract the driver's records
rely on."""

import time

import numpy as np
import pytest

import bench


class FakeRunner(object):
    """run_at(steps) stub with controllable per-step cost + warm cost."""

    def __init__(self, per_step=0.004, first_extra=0.05):
        self.calls = []
        self.per_step = per_step
        self.first_extra = first_extra

    def __call__(self, steps):
        extra = self.first_extra if steps not in [
            s for s, _ in self.calls
        ] else 0.0
        self.calls.append((steps, extra))
        time.sleep(steps * self.per_step + extra)


def test_diff_time_record_carries_protocol_fields():
    r = FakeRunner()
    dt, info = bench._diff_time(r, 2, 6, return_info=True)
    # the per-step estimate lands near the configured cost
    assert 0.5 * r.per_step < dt < 3.0 * r.per_step
    # r4 falsifiability fields
    assert info["steps"] == [2, 6]
    assert set(info["raw_chunk_s"]) == {"2", "6"}
    assert all(
        len(v) >= bench.TIMING_CHUNKS for v in info["raw_chunk_s"].values()
    )
    assert set(info["spread"]) == {"2", "6"}
    assert isinstance(info["stable"], bool)
    # r4 verdict #9: trace+compile budget column — the warm pass is the
    # only one that pays compile, and its extra cost must be visible
    assert set(info["warm_s"]) == {"2", "6"}
    assert info["warm_s"]["2"] >= r.first_extra * 0.5
    # warm includes the first-run extra; steady chunks must not
    assert min(info["raw_chunk_s"]["2"]) < r.first_extra + 2 * 0.004 * 2


def test_diff_time_inversion_raises():
    """A pathological runner where more steps are FASTER must be
    rejected, not silently recorded (timing inversion guard)."""

    def weird(steps):
        time.sleep(0.06 if steps == 2 else 0.01)

    with pytest.raises(AssertionError, match="timing inversion"):
        bench._diff_time(weird, 2, 6, return_info=True)
