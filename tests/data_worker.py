"""Supervised input-pipeline drill worker (driven by
tests/test_data_drill.py).

One logical job: N of these workers drain ONE coordinator chunk queue
through `paddle_tpu.data.DataLoader` (CoordinatedChunkSource), recording
every delivered record id. The job-level deliverable is the MULTISET of
record ids across all workers' histories: it must equal the dataset
exactly — every record once, no loss, no duplicates — no matter which
worker was killed when (the acceptance bar of ISSUE 3).

Protocol per batch (the fault injector ticks at the batch boundary, so
kill@N lands between batches, where resume must be exact):

    tick -> heartbeat -> next(loader) -> accumulate history ->
    checkpoint (atomic; loader cursor rides in `stateful`, history in
    `extra`) -> loader.commit()  (acks/progress flushed AFTER the
    checkpoint commits, the supervisor_worker pending_ack discipline)

On restart, `resume_or_init(..., stateful={"loader": loader})` restores
the exact cursor; the first commit() re-flushes any acks the crash cut
off. Lease timeouts are sized above the supervisor restart latency and
the loader's idle grace above the lease timeout, so a killed worker's
in-flight chunk is either reclaimed by its own resume or requeued to a
peer at the committed offset.

Usage: data_worker.py OUT_JSON CKPT_DIR COORD_ADDR SHARD_DIR
Env:   PADDLE_WORKER_ID   logical id (set by the Supervisor)
       PADDLE_FAULT       injected faults, e.g. kill@N (stripped on
                          restart by the Supervisor)
       DATA_BATCH         batch size (default 6)
       DATA_SEED          dataset shuffle seed (default 11)
       DATA_IDLE_GRACE_S  loader idle grace (default 8; must exceed the
                          coordinator lease timeout)
       DATA_STEP_SLEEP    extra seconds per batch (paces the drain so
                          kill@N lands mid-epoch)
"""

import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.data import CoordinatedChunkSource, DataLoader, ShardedDataset
from paddle_tpu.distributed import (
    RemoteCoordinator,
    checkpoint as ckpt,
    fault_injection as fi,
)

import pickle


def main():
    out_path, ckpt_dir, addr, shard_dir = sys.argv[1:5]
    wid = os.environ.get("PADDLE_WORKER_ID", "w?")
    batch = int(os.environ.get("DATA_BATCH", "6"))
    seed = int(os.environ.get("DATA_SEED", "11"))
    idle_grace = float(os.environ.get("DATA_IDLE_GRACE_S", "8.0"))
    step_sleep = float(os.environ.get("DATA_STEP_SLEEP", "0.02"))

    shard_paths = sorted(glob.glob(os.path.join(shard_dir, "*.rs")))
    dataset = ShardedDataset(shard_paths, decode_fn=pickle.loads, seed=seed)

    client = RemoteCoordinator(addr, retry_deadline_s=20.0,
                               backoff_base_s=0.05)
    client.register_worker(wid)
    injector = fi.default_injector()

    loader = DataLoader(
        dataset, batch_size=batch,
        source=CoordinatedChunkSource(client, idle_grace_s=idle_grace,
                                      poll_s=0.1),
        num_workers=2, auto_commit=False)

    scope = fluid.Scope()
    meta = ckpt.resume_or_init(scope, ckpt_dir,
                               stateful={"loader": loader})
    if meta is not None:
        resumed_from = int(meta["extra"]["step"])
        step = resumed_from
        history = list(meta["extra"]["history"])
        loader.commit()  # re-flush acks the crash may have cut off
    else:
        resumed_from = None
        step = 0
        history = []
        scope.set("acc", np.zeros((1,), np.float64))

    for ids, _vals in loader:
        injector.tick()
        client.heartbeat(wid, step=step)
        if step_sleep:
            time.sleep(step_sleep)
        history.extend(int(i) for i in ids.tolist())
        step += 1
        scope.set("acc", np.asarray(scope.get("acc"), np.float64)
                  + float(np.sum(ids)))
        ckpt.save_checkpoint(
            scope, ckpt_dir, step=step,
            extra={"step": step, "history": history, "worker": wid},
            stateful={"loader": loader}, keep_last=2)
        loader.commit()
    # trailing completion acks (chunks whose records all rode earlier,
    # already-checkpointed batches) surface at epoch end — flush them
    loader.commit()
    client.heartbeat(wid, step=step)
    loader.close()
    client.close()

    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({
            "worker": wid,
            "resumed_from": resumed_from,
            "steps_done": step,
            "history": history,
            "restart_count": int(os.environ.get("PADDLE_RESTART_COUNT",
                                                "0")),
        }, f)
    os.replace(tmp, out_path)


if __name__ == "__main__":
    main()
