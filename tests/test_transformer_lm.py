"""Long-context transformer LM over the 3-axis mesh: dp+tp+sp must compute
exactly the single-device math, and training must learn a synthetic
pattern."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu import parallel
from paddle_tpu.models import transformer as tlm


@pytest.fixture(scope="module")
def cfg():
    return tlm.TransformerConfig(vocab=32, dim=32, heads=4, layers=2,
                                 max_len=64)


def _tokens(rng, b, t, vocab):
    # learnable structure: next token = (token + 1) % vocab
    start = rng.randint(0, vocab, (b, 1))
    ar = (start + np.arange(t + 1)) % vocab
    return jnp.asarray(ar.astype(np.int32))


def test_seq_parallel_loss_matches_single_device(cfg):
    rng = np.random.RandomState(0)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    toks = _tokens(rng, 2, 16, cfg.vocab)

    ref = tlm.loss_fn(params, toks, cfg, mesh=None)
    mesh = parallel.make_mesh({"seq": 8})
    sp = tlm.loss_fn(params, toks, cfg, mesh=mesh, attn_impl="ring")
    np.testing.assert_allclose(float(sp), float(ref), rtol=1e-5)

    g_ref = jax.grad(tlm.loss_fn)(params, toks, cfg, mesh=None)
    g_sp = jax.grad(tlm.loss_fn)(params, toks, cfg, mesh=mesh)
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_sp)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=2e-4)


def test_tp_sharded_params_match(cfg):
    rng = np.random.RandomState(1)
    params = tlm.init_params(cfg, jax.random.PRNGKey(1))
    toks = _tokens(rng, 2, 16, cfg.vocab)
    ref = float(tlm.loss_fn(params, toks, cfg, mesh=None))

    mesh = parallel.make_mesh({"data": 2, "model": 4})
    specs = tlm.param_specs(cfg)
    sharded = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs,
        is_leaf=lambda x: isinstance(x, (jnp.ndarray, np.ndarray, P)),
    )
    got = float(jax.jit(
        lambda pr, tk: tlm.loss_fn(pr, tk, cfg, mesh=mesh)
    )(sharded, toks))
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_lm_trains_on_pattern(cfg):
    rng = np.random.RandomState(2)
    params = tlm.init_params(cfg, jax.random.PRNGKey(2))
    mesh = parallel.make_mesh({"seq": 8})
    step = jax.jit(tlm.make_train_step(cfg, lr=0.5, mesh=mesh))
    losses = []
    for i in range(30):
        toks = _tokens(rng, 8, 16, cfg.vocab)
        params, loss = step(params, toks)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


def test_switch_moe_lm_mesh_matches_single_device():
    """Switch-LM: MoE blocks sharded over an 8-device 'expert' axis
    compute the single-device oracle exactly (capacity set generous so
    no token drops, isolating the dispatch/all-to-all path)."""
    E = 8
    cfg = tlm.TransformerConfig(vocab=32, dim=32, heads=4, layers=2,
                                max_len=64, moe_experts=E, moe_every=2,
                                moe_capacity_factor=float(E))
    rng = np.random.RandomState(7)
    params = tlm.init_params(cfg, jax.random.PRNGKey(7))
    toks = _tokens(rng, 2, 16, cfg.vocab)
    assert "moe" in params["blocks"][1] and "w1" in params["blocks"][0]

    ref = float(tlm.loss_fn(params, toks, cfg, mesh=None))
    mesh = parallel.make_mesh({"expert": E})
    got = float(jax.jit(
        lambda p, t: tlm.loss_fn(p, t, cfg, mesh=mesh)
    )(params, toks))
    np.testing.assert_allclose(got, ref, rtol=1e-5)

    g_ref = jax.grad(tlm.loss_fn)(params, toks, cfg, mesh=None)
    g_ep = jax.grad(tlm.loss_fn)(params, toks, cfg, mesh=mesh)
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_ep)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=2e-4)


def test_switch_moe_lm_trains():
    cfg = tlm.TransformerConfig(vocab=16, dim=32, heads=4, layers=2,
                                max_len=32, moe_experts=4, moe_every=2)
    rng = np.random.RandomState(8)
    params = tlm.init_params(cfg, jax.random.PRNGKey(8))
    step = jax.jit(tlm.make_train_step(cfg, lr=0.3))
    toks = _tokens(rng, 8, 16, cfg.vocab)
    losses = []
    for _ in range(40):
        params, loss = step(params, toks)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
