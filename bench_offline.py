"""Offline perf artifact: AOT-compile the bench workloads for TPU v5e
WITHOUT a chip (VERDICT r4 next-#2 — perf evidence must survive tunnel
outages).

`jax.experimental.topologies` provides a v5e topology description that
the TPU compiler accepts on any host, so every workload here is lowered
and compiled by the REAL XLA:TPU pipeline (including Mosaic for the
Pallas flash-attention kernel — the compile path CI's interpret=True
mode never exercises). The artifact persists, per workload:

  hlo_sha256        fingerprint of the scheduled TPU HLO — changes iff
                    the compiled step changes, so perf-relevant diffs
                    are visible between on-chip bench windows
  flops / bytes_accessed   XLA:TPU cost analysis of the whole step
  roofline          cost-model step time on v5e (max of MXU time and
                    HBM time), predicted throughput, and the bound
  trace_s/compile_s trace+compile budget (VERDICT r4 next-#9)
  top_ops           largest per-op rows by attributed HBM traffic
                    (fluid/profiler.py parse_hlo_op_costs over the op
                    provenance tags lowering stamps into HLO metadata)

Run standalone (`python bench_offline.py`) or via bench.py, which
spawns it before device init so outage days still produce it. Writes
BENCH_offline_r05.json (override: BENCH_OFFLINE_PATH).

Reference anchors: benchmark/paddle/image/resnet.py:1 (headline
workload), benchmark/README.md:37,50,119 (baseline table).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

PEAK_FLOPS = 197e12  # TPU v5e bf16
HBM_BW = 819e9       # TPU v5e HBM bytes/s

TOPOLOGY = os.environ.get("BENCH_OFFLINE_TOPOLOGY", "v5e:2x4")
# repo-anchored, not cwd-relative: a bench.py run from elsewhere must
# still refresh the COMMITTED artifact
OUT_PATH = os.environ.get("BENCH_OFFLINE_PATH") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_offline_r05.json"
)
TOP_OPS = int(os.environ.get("BENCH_OFFLINE_TOP_OPS", "8"))


def _sds(tree):
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
        if hasattr(a, "shape")
        else a,
        tree,
    )


def _cost_record(lowered, t_trace, unit_name=None, units_per_step=None):
    """Compile a lowered computation and distill the offline record."""
    from paddle_tpu.fluid.profiler import parse_hlo_op_costs

    t0 = time.time()
    compiled = lowered.compile()
    compile_s = time.time() - t0
    txt = compiled.as_text()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    ca = ca or {}
    flops = float(ca.get("flops", 0.0))
    byts = float(ca.get("bytes accessed", 0.0))
    opt_s = float(ca.get("optimal_seconds", 0.0))
    rows = parse_hlo_op_costs(txt)
    top = sorted(rows.items(), key=lambda kv: -kv[1]["teq"])[:TOP_OPS]
    rec = {
        "hlo_sha256": hashlib.sha256(txt.encode()).hexdigest(),
        "hlo_instructions": sum(r["instructions"] for r in rows.values()),
        "flops": flops,
        "bytes_accessed": byts,
        "trace_s": round(t_trace, 2),
        "compile_s": round(compile_s, 2),
        "top_ops": [
            {"op": k, "bytes": v["bytes"], "flops": v["flops"],
             "instructions": v["instructions"]}
            for k, v in top
        ],
    }
    # the TPU compiler's own performance model: tighter than the naive
    # roofline (it knows fusion/VMEM prefetch; "bytes accessed" counts
    # every instruction operand and overcounts true HBM traffic)
    if opt_s > 0:
        rec["optimal_seconds"] = opt_s
        if unit_name and units_per_step:
            rec["pred_%s_optimal" % unit_name] = round(
                units_per_step / opt_s, 1
            )
    # flops can be negative when the step contains custom calls the cost
    # model cannot see through (Mosaic kernels) — report, don't predict
    if flops > 0 and byts > 0:
        t_roof = max(flops / PEAK_FLOPS, byts / HBM_BW)
        rec["roofline"] = {
            "ms": round(t_roof * 1e3, 3),
            "bound": "hbm" if flops / byts < PEAK_FLOPS / HBM_BW else "mxu",
            "ai_flops_per_byte": round(flops / byts, 1),
        }
        if unit_name and units_per_step:
            rec["roofline"]["pred_%s" % unit_name] = round(
                units_per_step / t_roof, 1
            )
    return rec, txt


def _lower_program_step(prog, cost, feed, mesh, scope):
    """Mirror the executor's sharded jit of a training program, but lower
    only (no execution — the mesh devices are topology descriptions)."""
    import jax

    from paddle_tpu.fluid.core.lowering import build_step_fn
    from paddle_tpu.fluid.executor import _mesh_jit_kwargs

    persist_names = sorted(v.name for v in prog.list_vars() if v.persistable)
    persist_in = {n: scope.get(n) for n in persist_names if n in scope}
    fn, persist_out = build_step_fn(
        prog,
        feed_names=list(feed),
        fetch_names=[cost.name],
        persist_names=persist_names,
        persist_in=list(persist_in),
    )
    kwargs = _mesh_jit_kwargs(
        mesh, prog, feed, list(persist_in), persist_out, [cost.name]
    )
    t0 = time.time()
    lowered = jax.jit(fn, donate_argnums=(0,), **kwargs).lower(
        _sds(persist_in), _sds(feed), jax.random.PRNGKey(0)
    )
    return lowered, time.time() - t0


def _init_params(prog_builder):
    """Build a program + run its startup on the host CPU backend, return
    (main, cost, scope). Params are initialised on CPU purely to obtain
    shapes/dtypes for AOT lowering."""
    import paddle_tpu.fluid as fluid

    main, startup, cost = prog_builder()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
    return main, cost, scope


def offline_resnet50(topo_devices, batch):
    import paddle_tpu.fluid as fluid
    from paddle_tpu import parallel
    from bench import _build_image_workload
    from paddle_tpu.models.resnet import resnet_imagenet

    main, cost, scope = _init_params(
        lambda: _build_image_workload(
            fluid, lambda i, c: resnet_imagenet(i, class_dim=c, depth=50),
            batch,
        )
    )
    feed = {
        "image": np.zeros((batch, 3, 224, 224), np.float32),
        "label": np.zeros((batch, 1), np.int32),
    }
    mesh = parallel.make_mesh({"data": 1}, devices=topo_devices[:1])
    lowered, t_trace = _lower_program_step(main, cost, feed, mesh, scope)
    rec, _ = _cost_record(lowered, t_trace, "img_per_sec", batch)
    rec["batch"] = batch
    return rec


def offline_resnet50_infer(topo_devices, batch=None):
    """The serving-side forward AOT-compiled for v5e — between-windows
    evidence for the inference row. Builds the SAME program as the
    on-chip bench (shared bench._build_image_infer_program) and honors
    the same BENCH_INFER_BATCH override, so the fingerprint always
    matches what the row measures. Baseline anchor:
    /root/reference/benchmark/IntelOptimizedPaddle.md:87."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import parallel
    from bench import _build_image_infer_program
    from paddle_tpu.models.resnet import resnet_imagenet

    batch = batch or int(os.environ.get("BENCH_INFER_BATCH", "16"))
    main, pred, scope = _init_params(lambda: _build_image_infer_program(
        fluid, lambda i, c: resnet_imagenet(i, class_dim=c, depth=50)))
    feed = {"image": np.zeros((batch, 3, 224, 224), np.float32)}
    mesh = parallel.make_mesh({"data": 1}, devices=topo_devices[:1])
    lowered, t_trace = _lower_program_step(main, pred, feed, mesh, scope)
    rec, _ = _cost_record(lowered, t_trace, "img_per_sec", batch)
    rec["batch"] = batch
    return rec


def offline_resnet50_dp(topo_devices, batch_per_chip):
    """The same train step data-parallel over all topology chips — the
    SPMD partitioner + ICI collectives compiled by the real TPU
    pipeline (the on-chip analogue of dryrun_multichip's CPU mesh)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import parallel
    from bench import _build_image_workload
    from paddle_tpu.models.resnet import resnet_imagenet

    n = len(topo_devices)
    batch = batch_per_chip * n
    main, cost, scope = _init_params(
        lambda: _build_image_workload(
            fluid, lambda i, c: resnet_imagenet(i, class_dim=c, depth=50),
            batch,
        )
    )
    feed = {
        "image": np.zeros((batch, 3, 224, 224), np.float32),
        "label": np.zeros((batch, 1), np.int32),
    }
    mesh = parallel.make_mesh({"data": n}, devices=topo_devices)
    lowered, t_trace = _lower_program_step(main, cost, feed, mesh, scope)
    rec, txt = _cost_record(lowered, t_trace, "img_per_sec", batch)
    rec["batch"] = batch
    rec["n_chips"] = n
    # count the collectives the partitioner inserted (the gradient
    # all-reduce story in one number)
    rec["collectives"] = _count_collectives(txt)
    return rec


def offline_flash_attention(topo_devices, B=4, T=4096, H=16, D=64):
    """Mosaic-compile the Pallas flash-attention kernel (fwd + bwd) —
    the interpret=False path CI cannot run — and the XLA full-matrix
    attention it replaces, for a cost-model comparison."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.parallel.flash_attention import flash_attention

    mesh = Mesh(np.asarray(topo_devices[:1]).reshape(1,), ("d",))
    rep = NamedSharding(mesh, P())
    q = jax.ShapeDtypeStruct((B, T, H, D), jnp.bfloat16)

    def fa_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True))

    def xla_loss(q, k, v):
        qt = q.transpose(0, 2, 1, 3)
        kt = k.transpose(0, 2, 1, 3)
        vt = v.transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * (D ** -0.5)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
        return jnp.sum(jnp.einsum("bhqk,bhkd->bhqd", p, vt))

    out = {"shape": [B, T, H, D]}
    for name, fn in (("flash_mosaic", fa_loss), ("xla_attention", xla_loss)):
        t0 = time.time()
        lowered = jax.jit(
            jax.grad(fn, argnums=(0, 1, 2)),
            in_shardings=(rep, rep, rep),
        ).lower(q, q, q)
        out[name], _ = _cost_record(lowered, time.time() - t0)
    # the falsifiable claim: Mosaic compilation of the Pallas kernel
    # SUCCEEDED for v5e (hlo_sha256 present) — runtime superiority still
    # needs the chip (bench.py flash_attention workload)
    out["mosaic_compiled"] = "hlo_sha256" in out["flash_mosaic"]
    return out


def offline_transformer_lm(topo_devices, B=8, T=1024, dim=512, heads=8,
                           layers_n=8, vocab=32000):
    """The long-context flagship LM train step (bench.py
    bench_transformer_lm) with the FLASH attention impl — on TPU the
    bench uses Mosaic flash; compiling the same composition offline
    keeps that path honest between on-chip windows."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.models import transformer as tlm

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=T,
                                dtype=jnp.bfloat16)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    step = tlm.make_train_step(cfg, lr=1e-3, attn_impl="flash")
    mesh = Mesh(np.asarray(topo_devices[:1]).reshape(1,), ("d",))
    rep = NamedSharding(mesh, P())
    toks = jax.ShapeDtypeStruct((B, T + 1), jnp.int32)
    t0 = time.time()
    lowered = jax.jit(step, in_shardings=(rep, rep)).lower(
        _sds(params), toks
    )
    rec, _ = _cost_record(lowered, time.time() - t0, "tokens_per_sec", B * T)
    rec["shape"] = {"B": B, "T": T, "dim": dim, "layers": layers_n}
    rec["attn_impl"] = "flash"
    return rec


def _count_collectives(txt):
    return {
        k: txt.count(k)
        for k in ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")
    }


def offline_resnet50_hybrid(topo_devices, batch_per_chip=16):
    """The full hybrid-mesh layout (dcn=2 slices x data x model=2 TP on
    the classifier fc) AOT-compiled over 8 v5e chips — the
    dryrun_multichip topology through the real TPU SPMD partitioner.
    The fc weight is sharded BEFORE minimize so the momentum slot
    inherits the spec (fluid/optimizer.py _add_accumulator)."""
    import paddle_tpu.fluid as fluid
    from jax.sharding import PartitionSpec as P

    from paddle_tpu import parallel
    from bench import AMP
    from paddle_tpu.models.resnet import resnet_imagenet

    n = len(topo_devices)
    batch = batch_per_chip * n
    ici_axes = {"data": n // 4, "model": 2}

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            image = fluid.layers.data(
                name="image", shape=[3, 224, 224], dtype="float32")
            label = fluid.layers.data(
                name="label", shape=[1], dtype="int64")
            predict = resnet_imagenet(image, class_dim=1000, depth=50)
            cost = fluid.layers.cross_entropy(input=predict, label=label)
            avg_cost = fluid.layers.mean(x=cost)
            # TP shard BEFORE minimize: optimizer slots inherit the spec
            for p in main.global_block().all_parameters():
                if len(p.shape) == 2 and p.shape[1] == 1000:
                    parallel.shard_parameter(p, P(None, "model"))
            opt = fluid.optimizer.Momentum(
                learning_rate=0.01, momentum=0.9)
            opt.minimize(avg_cost)
        main.amp = AMP
        return main, startup, avg_cost

    main, cost, scope = _init_params(build)
    feed = {
        "image": np.zeros((batch, 3, 224, 224), np.float32),
        "label": np.zeros((batch, 1), np.int32),
    }
    mesh = parallel.make_hybrid_mesh(
        {"dcn": 2}, ici_axes, devices=topo_devices
    )
    lowered, t_trace = _lower_program_step(main, cost, feed, mesh, scope)
    rec, txt = _cost_record(lowered, t_trace, "img_per_sec", batch)
    rec["batch"] = batch
    rec["mesh"] = dict({"dcn": 2}, **ici_axes)
    rec["collectives"] = _count_collectives(txt)
    return rec


def offline_lm_decode(topo_devices, B=8, T0=512, dim=512, heads=8,
                      layers_n=8, vocab=32000):
    """One cached decode step (the serving inner loop) AOT-compiled for
    v5e: the latency unit of bench_lm_decode, with its cost analysis."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.models import transformer as tlm

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=T0 + 256,
                                dtype=jnp.bfloat16)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    cache = tlm.init_kv_cache(cfg, B, max_len=T0 + 256)
    mesh = Mesh(np.asarray(topo_devices[:1]).reshape(1,), ("d",))
    rep = NamedSharding(mesh, P())

    def step(params, tok, cache):
        return tlm.decode_step(params, tok, T0, cache, cfg)

    t0 = time.time()
    lowered = jax.jit(step, in_shardings=(rep, rep, rep)).lower(
        _sds(params),
        jax.ShapeDtypeStruct((B,), jnp.int32),
        _sds(cache),
    )
    rec, _ = _cost_record(lowered, time.time() - t0, "tokens_per_sec", B)
    rec["shape"] = {"B": B, "cache_len": T0 + 256, "dim": dim,
                    "layers": layers_n}
    return rec


def offline_ring_attention_sp8(topo_devices, B=2, T_per=2048, H=8, D=64):
    """Ring attention (sequence parallelism) fwd+bwd over ALL topology
    chips — the long-context scaling story compiled by the real TPU
    SPMD pipeline: per-chip KV blocks stream around the ring via
    collective-permute while each chip holds T/n of the sequence."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import parallel

    n = len(topo_devices)
    mesh = parallel.make_mesh({"seq": n}, devices=topo_devices)
    T = T_per * n

    def loss(q, k, v):
        out = parallel.sequence_parallel_attention(
            q, k, v, mesh=mesh, impl="ring", causal=True
        )
        return jnp.sum(out.astype(jnp.float32))

    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P(None, "seq"))
    q = jax.ShapeDtypeStruct((B, T, H, D), jnp.bfloat16, sharding=sh)
    t0 = time.time()
    lowered = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(q, q, q)
    rec, txt = _cost_record(lowered, time.time() - t0)
    rec["shape"] = {"B": B, "T_global": T, "H": H, "D": D, "chips": n}
    rec["collectives"] = _count_collectives(txt)
    return rec


def offline_zigzag_sp8(topo_devices, B=2, T_per=2048, H=8, D=64):
    """Zigzag (striped) causal ring attention fwd+bwd over all topology
    chips (r5 beyond-reference: balances the causal mask so every chip
    does ~2 stripe-matmuls per ring step instead of the tail chip's 4
    — the lock-step critical path halves vs the contiguous layout)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import parallel

    n = len(topo_devices)
    mesh = parallel.make_mesh({"seq": n}, devices=topo_devices)
    T = T_per * n

    def loss(q, k, v):
        out = parallel.sequence_parallel_attention(
            q, k, v, mesh=mesh, impl="zigzag", causal=True
        )
        return jnp.sum(out.astype(jnp.float32))

    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P(None, "seq"))
    q = jax.ShapeDtypeStruct((B, T, H, D), jnp.bfloat16, sharding=sh)
    t0 = time.time()
    lowered = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(q, q, q)
    rec, txt = _cost_record(lowered, time.time() - t0)
    rec["shape"] = {"B": B, "T_global": T, "H": H, "D": D, "chips": n}
    rec["collectives"] = _count_collectives(txt)
    return rec


def offline_ulysses_flash_sp8(topo_devices, B=2, T_per=2048, H=8, D=64):
    """Ulysses sequence parallelism with the PALLAS flash kernel per
    shard (r5: sequence_parallel_attention impl='flash' routes here when
    heads divide the axis), fwd+bwd over all topology chips — proves
    the Mosaic kernel AND its pallas backward compile inside shard_map
    through the real TPU SPMD pipeline."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import parallel

    n = len(topo_devices)
    mesh = parallel.make_mesh({"seq": n}, devices=topo_devices)
    T = T_per * n

    def loss(q, k, v):
        # interpret=False explicitly: this host process runs on the CPU
        # backend, but the lowering targets the TPU topology — Mosaic,
        # not the interpreter, must land in the compiled module
        out = parallel.sequence_parallel_attention(
            q, k, v, mesh=mesh, impl="flash", causal=True,
            interpret=False,
        )
        return jnp.sum(out.astype(jnp.float32))

    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P(None, "seq"))
    q = jax.ShapeDtypeStruct((B, T, H, D), jnp.bfloat16, sharding=sh)
    t0 = time.time()
    lowered = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(q, q, q)
    rec, txt = _cost_record(lowered, time.time() - t0)
    rec["shape"] = {"B": B, "T_global": T, "H": H, "D": D, "chips": n}
    rec["collectives"] = _count_collectives(txt)
    rec["mosaic_in_shard_map"] = txt.count("tpu_custom_call")
    if not rec["mosaic_in_shard_map"]:
        rec["error"] = "pallas kernel missing from compiled module"
    return rec


def offline_switch_moe_ep8(topo_devices, tokens_per_chip=1024, Dm=512,
                           Hf=2048):
    """Switch-MoE FFN (expert parallelism) fwd+bwd over all topology
    chips: dispatch/return all-to-alls + per-chip expert matmuls,
    compiled by the real TPU SPMD pipeline."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import parallel

    n = len(topo_devices)
    mesh = parallel.make_mesh({"expert": n}, devices=topo_devices)
    N = tokens_per_chip * n

    def loss(x, gate_w, w1, b1, w2, b2):
        out = parallel.expert_parallel_moe(
            x, gate_w, w1, b1, w2, b2, mesh=mesh
        )
        return jnp.sum(out.astype(jnp.float32))

    from jax.sharding import NamedSharding, PartitionSpec as P

    xs = NamedSharding(mesh, P("expert"))
    es = NamedSharding(mesh, P("expert"))
    rep = NamedSharding(mesh, P())
    args = (
        jax.ShapeDtypeStruct((N, Dm), jnp.bfloat16, sharding=xs),
        jax.ShapeDtypeStruct((Dm, n), jnp.bfloat16, sharding=rep),
        jax.ShapeDtypeStruct((n, Dm, Hf), jnp.bfloat16, sharding=es),
        jax.ShapeDtypeStruct((n, Hf), jnp.bfloat16, sharding=es),
        jax.ShapeDtypeStruct((n, Hf, Dm), jnp.bfloat16, sharding=es),
        jax.ShapeDtypeStruct((n, Dm), jnp.bfloat16, sharding=es),
    )
    t0 = time.time()
    lowered = jax.jit(
        jax.grad(loss, argnums=tuple(range(6)))
    ).lower(*args)
    rec, txt = _cost_record(lowered, time.time() - t0)
    rec["shape"] = {"tokens": N, "d_model": Dm, "d_ff": Hf, "experts": n}
    rec["collectives"] = _count_collectives(txt)
    return rec


def kv_bytes_per_token(layers_n, heads, dh, kv_quant="none",
                       block_tokens=16, act_itemsize=4):
    """HBM bytes one cached token costs at a KV storage dtype: the
    per-block cost (models/transformer.kv_block_bytes — THE one
    formula, shared with the engine's allocator accounting and
    bench.py's byte-budget sizing) amortised over the block's tokens,
    so the quant scale side-bands show up fractionally (ISSUE 14)."""
    from paddle_tpu.models.transformer import kv_block_bytes

    return kv_block_bytes(layers_n, heads, dh, block_tokens, kv_quant,
                          act_itemsize=act_itemsize) \
        / float(block_tokens)


def offline_paged_attention_quant(topo_devices, S=32, H=8, dh=64,
                                  NB=256, Bt=32, maxb=32):
    """Mosaic AOT-compile check for the DEQUANTIZING paged-attention
    kernels (ISSUE 14, alongside PR 13's): the paged decode and
    verify kernels compiled by the real XLA:TPU pipeline for a v5e
    topology at bf16, f32, and int8 storage — int8 carries the
    per-(block, head) scale side-bands as scalar-prefetch operands,
    the compile path CI's interpret mode never exercises. Bt=32 keeps
    the int8 pool's block rows on the 32-row int8 sublane tile. The
    falsifiable claim per storage dtype: `tpu_custom_call` present in
    the compiled module (the kernel lowered to Mosaic, not a
    fallback), plus the HLO fingerprint and cost analysis for
    between-windows comparison."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.parallel.paged_attention import (
        paged_decode_attention, paged_verify_attention)

    mesh = Mesh(np.asarray(topo_devices[:1]).reshape(1,), ("d",))
    rep = NamedSharding(mesh, P())
    tables = jax.ShapeDtypeStruct((S, maxb), jnp.int32)
    pos = jax.ShapeDtypeStruct((S,), jnp.int32)
    sc = jax.ShapeDtypeStruct((NB, H), jnp.float32)
    out = {"shape": {"S": S, "H": H, "dh": dh, "NB": NB, "Bt": Bt,
                     "maxb": maxb}}
    all_mosaic = True
    for store in ("float32", "bfloat16", "int8"):
        pool = jax.ShapeDtypeStruct((NB, Bt, H, dh), jnp.dtype(store))
        qd = jax.ShapeDtypeStruct((S, H, dh), jnp.bfloat16)
        qv = jax.ShapeDtypeStruct((S, 4, H, dh), jnp.bfloat16)
        quant = store == "int8"
        for name, q, fn in (
            ("decode", qd, paged_decode_attention),
            ("verify", qv, paged_verify_attention),
        ):
            if quant:
                def wrapped(q, k, v, t, p, ks, vs, _fn=fn):
                    # interpret=False explicitly: the host backend is
                    # CPU but the lowering targets the TPU topology —
                    # Mosaic, not the interpreter, must land
                    return _fn(q, k, v, t, p, interpret=False,
                               k_scale=ks, v_scale=vs)
                args = (q, pool, pool, tables, pos, sc, sc)
            else:
                def wrapped(q, k, v, t, p, _fn=fn):
                    return _fn(q, k, v, t, p, interpret=False)
                args = (q, pool, pool, tables, pos)
            t0 = time.time()
            lowered = jax.jit(
                wrapped, in_shardings=(rep,) * len(args)).lower(*args)
            rec, txt = _cost_record(lowered, time.time() - t0)
            rec["mosaic_calls"] = txt.count("tpu_custom_call")
            all_mosaic = all_mosaic and rec["mosaic_calls"] > 0
            out["%s_%s" % (name, store)] = rec
    out["mosaic_compiled_all"] = all_mosaic
    if not all_mosaic:
        out["error"] = "a paged kernel variant fell off the Mosaic path"
    return out


def offline_serving_quant_roofline(layers_n=8, dim=512, heads=8,
                                   vocab=32000, S=32, context=512,
                                   block_tokens=32):
    """Analytic decode roofline at each serving storage dtype (ISSUE
    14 satellite): one batched decode step reads every weight byte
    once and every resident KV byte once — both terms now honest
    about storage dtype instead of assuming f32 everywhere. The
    predicted tokens/s are the HBM bound (the offline cost model
    already calls decode hbm-bound: lm_decode's cost analysis says
    ai ~ 2 flops/byte, far under the v5e ridge), so
    bytes-per-step / HBM_BW is the step-time floor and the
    measurement slot for the real contrast is PERF.md PR 14's."""
    dh = dim // heads
    # weight bytes: embed + pos (context table) + per-layer qkvo +
    # 2 MLP mats (mlp_mult 4) + norms, at the storage dtype
    n_params = (vocab * dim + 1024 * dim
                + layers_n * (4 * dim * dim + 8 * dim * dim + 4 * dim))
    out = {"shape": {"layers": layers_n, "dim": dim, "heads": heads,
                     "vocab": vocab, "slots": S, "context": context,
                     "block_tokens": block_tokens},
           "hbm_bw": HBM_BW, "n_params": n_params}
    for wq, w_item in (("none_bf16", 2), ("int8", 1)):
        for kvq in ("none", "int8", "fp8"):
            kv_tok = kv_bytes_per_token(layers_n, heads, dh, kvq,
                                        block_tokens,
                                        act_itemsize=2)  # bf16 serving
            step_bytes = n_params * w_item + S * context * kv_tok
            t = step_bytes / HBM_BW
            out["w_%s__kv_%s" % (wq, kvq)] = {
                "weight_bytes": n_params * w_item,
                "kv_bytes_per_token": round(kv_tok, 2),
                "kv_bytes_resident": int(S * context * kv_tok),
                "step_bytes": int(step_bytes),
                "pred_tokens_per_sec_hbm_bound": round(S / t, 1),
            }
    base = out["w_none_bf16__kv_none"]["pred_tokens_per_sec_hbm_bound"]
    best = out["w_int8__kv_int8"]["pred_tokens_per_sec_hbm_bound"]
    out["pred_uplift_int8_over_bf16"] = round(best / base, 2)
    return out


def offline_scaling_projection(batch_per_chip=32):
    """Cost-model projection of 1->16 chip weak scaling (BASELINE.json
    asks >=90% on a v5e-16; no multi-chip hardware exists here, so this
    is the best available evidence): the SAME per-chip batch compiled
    single-chip and data-parallel over a virtual v5e 4x4 topology, and
    efficiency = t_roof(1) / t_roof(16) from the per-device cost
    analysis (flops/bytes are per-device; dp adds the gradient
    all-reduces, which is exactly what degrades weak scaling)."""
    import jax
    from jax.experimental import topologies

    import paddle_tpu.fluid as fluid
    from paddle_tpu import parallel
    from bench import _build_image_workload
    from paddle_tpu.models.resnet import resnet_imagenet

    td16 = topologies.get_topology_desc(
        platform="tpu", topology_name="v5e:4x4")
    devs16 = list(np.asarray(td16.devices).ravel())

    out = {"batch_per_chip": batch_per_chip}
    preds = {}
    for n, devs in ((1, devs16[:1]), (16, devs16)):
        batch = batch_per_chip * n
        main, cost, scope = _init_params(
            lambda: _build_image_workload(
                fluid,
                lambda i, c: resnet_imagenet(i, class_dim=c, depth=50),
                batch,
            )
        )
        feed = {
            "image": np.zeros((batch, 3, 224, 224), np.float32),
            "label": np.zeros((batch, 1), np.int32),
        }
        mesh = parallel.make_mesh({"data": n}, devices=devs)
        lowered, t_trace = _lower_program_step(
            main, cost, feed, mesh, scope)
        rec, txt = _cost_record(lowered, t_trace, "img_per_sec", batch)
        rec["collectives"] = _count_collectives(txt)
        out["dp%d" % n] = rec
        preds[n] = rec.get("roofline", {}).get("ms")
    if preds.get(1) and preds.get(16):
        # weak scaling: per-chip work identical, so efficiency is the
        # single-chip step time over the 16-chip (per-device) step time.
        # CAVEAT: XLA's cost analysis does NOT charge interconnect time
        # for collectives, so this compute-side number can exceed 1.
        out["weak_scaling_efficiency_compute_only"] = round(
            preds[1] / preds[16], 4
        )
        # analytic ICI bound: ring all-reduce of the f32 gradients moves
        # 2*(n-1)/n * grad_bytes per chip; ~90 GB/s effective one-way
        # ICI per v5e chip (scaling-book order of magnitude). Reported
        # as the NO-overlap lower bound — XLA overlaps the reduce with
        # backward compute, so the real number sits between the two.
        grad_bytes = 25.6e6 * 4  # ResNet-50 params, f32 grads
        ici_bw = 90e9
        ar_ms = 2 * (15.0 / 16.0) * grad_bytes / ici_bw * 1e3
        out["allreduce_ici_ms_no_overlap"] = round(ar_ms, 3)
        out["weak_scaling_efficiency_no_overlap"] = round(
            preds[1] / (preds[16] + ar_ms), 4
        )
        out["target"] = 0.90  # BASELINE.json
    return out


def main():
    import jax

    # the artifact must build with the tunnel down: host backend only
    jax.config.update("jax_platforms", "cpu")
    from jax.experimental import topologies

    t_all = time.time()
    td = topologies.get_topology_desc(platform="tpu", topology_name=TOPOLOGY)
    topo_devices = list(np.asarray(td.devices).ravel())

    artifact = {
        "topology": TOPOLOGY,
        "n_topology_chips": len(topo_devices),
        "peak_flops": PEAK_FLOPS,
        "hbm_bw": HBM_BW,
        "workloads": {},
    }
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    jobs = [
        ("resnet50_train", lambda: offline_resnet50(topo_devices, batch)),
        ("resnet50_train_dp%d" % len(topo_devices),
         lambda: offline_resnet50_dp(topo_devices, batch_per_chip=32)),
        ("resnet50_infer", lambda: offline_resnet50_infer(topo_devices)),
        ("flash_attention", lambda: offline_flash_attention(topo_devices)),
        ("transformer_lm", lambda: offline_transformer_lm(topo_devices)),
        ("transformer_lm_large", lambda: offline_transformer_lm(
            topo_devices, B=8, T=2048, dim=1024, heads=16, layers_n=12)),
        ("transformer_lm_xl", lambda: offline_transformer_lm(
            topo_devices, B=2, T=2048, dim=2048, heads=16, layers_n=16)),
        ("ring_attention_sp%d" % len(topo_devices),
         lambda: offline_ring_attention_sp8(topo_devices)),
        ("ulysses_flash_sp%d" % len(topo_devices),
         lambda: offline_ulysses_flash_sp8(topo_devices)),
        ("zigzag_sp%d" % len(topo_devices),
         lambda: offline_zigzag_sp8(topo_devices)),
        ("switch_moe_ep%d" % len(topo_devices),
         lambda: offline_switch_moe_ep8(topo_devices)),
        ("resnet50_hybrid", lambda: offline_resnet50_hybrid(topo_devices)),
        ("lm_decode", lambda: offline_lm_decode(topo_devices)),
        # ISSUE 14: the dequantizing paged kernels Mosaic-compiled for
        # v5e (bf16 + f32 + int8 storage; int8 rides scale
        # scalar-prefetch operands) — the compile path CI's interpret
        # mode never exercises, alongside PR 13's flash/ulysses checks
        ("paged_attention_quant",
         lambda: offline_paged_attention_quant(topo_devices)),
        # ISSUE 14: decode byte roofline honest about KV/weight
        # storage dtype (it assumed f32/bf16 everywhere before)
        ("serving_quant_roofline",
         lambda: offline_serving_quant_roofline()),
        ("scaling_projection", lambda: offline_scaling_projection()),
    ]
    only = os.environ.get("BENCH_OFFLINE_ONLY")
    run_stamp = {"run_at": round(time.time(), 1),
                 "jax_version": jax.__version__}
    for name, fn in jobs:
        if only and name not in only.split(","):
            continue
        try:
            artifact["workloads"][name] = fn()
        except Exception as e:
            artifact["workloads"][name] = {
                "error": "%s: %s" % (type(e).__name__, e)
            }
        # provenance survives the merge: carried-forward records keep
        # their own stamp, so mixed-run artifacts are tellable apart
        artifact["workloads"][name].update(run_stamp)
        print(
            json.dumps({"offline_workload": name,
                        "ok": "error" not in artifact["workloads"][name]}),
            flush=True,
        )
    artifact["total_s"] = round(time.time() - t_all, 1)
    # entries merged from earlier runs keep their own run_at/compile_s;
    # total_s covers only THIS run's regenerated workloads, so a
    # BENCH_OFFLINE_ONLY refresh legitimately reports a small total
    # while carrying expensive carried-forward entries
    artifact["total_s_note"] = (
        "wall seconds of the run that last wrote this file (only the "
        "workloads it regenerated); per-entry compile_s/trace_s and "
        "run_at stamps are the per-workload truth"
    )
    # MERGE into the committed artifact: a partial run (BENCH_OFFLINE_ONLY,
    # or a failed workload) must not destroy the other workloads' HLO
    # fingerprints — they are the between-windows comparison baseline
    if os.path.exists(OUT_PATH):
        try:
            with open(OUT_PATH) as f:
                prev = json.load(f)
            merged = dict(prev.get("workloads", {}))
            merged.update(artifact["workloads"])
            artifact["workloads"] = merged
        except (ValueError, OSError):
            pass  # corrupt/missing previous artifact: write fresh
    with open(OUT_PATH, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({"offline_artifact": OUT_PATH,
                      "total_s": artifact["total_s"]}), flush=True)


if __name__ == "__main__":
    main()
