#!/usr/bin/env bash
# Repo static-analysis gate: program verifier, trace-hazard and
# lock-discipline linters, the band-lifecycle verifier (B-codes: every
# registered KV/slot band propagated at every lifecycle verb) and the
# mesh sharding-spec lint (S-codes: axis names, shard_map spec arity,
# host syncs on placed values, spec-vs-rank) — all via `--all` below —
# then the protocol gate: deterministic schedule exploration whose
# journals replay through the J-code journal verifier
# (paddle_tpu.analysis, ISSUEs 5 + 9 + 20).
#
# Exits non-zero on any finding not covered by
# paddle_tpu/analysis/baseline.txt, and on any J-code from the
# protocol gate's journals. Run it before committing; the tier-1
# suite enforces the same invariants
# (tests/test_static_analysis.py::test_repo_is_clean_modulo_baseline,
# tests/test_protocol_analysis.py).
#
# To accept a finding instead of fixing it:
#   python -m paddle_tpu.analysis --all --write-baseline
# then REPLACE every 'TODO: justify or fix' marker with a real one-line
# justification (a tier-1 test rejects TODO markers).
#
# PADDLE_TPU_LINT_BENCH=1 additionally runs the serving bench smokes
# under PADDLE_TPU_AUDIT_JOURNAL=1 (every ServingFleet.close() replays
# its live journal through the DFA) and re-verifies the kept bench
# journal with `analysis journal` — minutes of engine compiles, so
# opt-in rather than part of the default pre-commit loop.
set -euo pipefail
cd "$(dirname "$0")/.."
# the program entries import jax via fluid; lint runs host-only
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

python -m paddle_tpu.analysis --all "$@"

# pre-mesh gate (ISSUE 20): the two engines above also run standalone
# so a failure names its analyzer in CI logs; `--all` already includes
# them — these reuse the same baseline and cost milliseconds
python -m paddle_tpu.analysis bands
python -m paddle_tpu.analysis shard

# protocol gate (ISSUE 9 + 11 + 12 + 15): explore the tier-1 fleet
# scenarios — the PR-6 kill drill, the elastic transitions (scale-up
# mid-burst, drain-retire racing a completion, rollout swap racing a
# migration), the multi-tenant fairness race (a tenant burst vs a
# weighted SLA tenant through the WFQ dispatch hop, with a mid-burst
# kill), the integrity trip (a quarantine + taint-aware resume racing
# a completion handshake and a tier migration), and the durable-KV
# handoff race (a block package racing a store eviction on the source
# and an integrity trip on the target) — keep their per-schedule
# journals, and replay EACH through the journal verifier: a new J-code
# here (including the J009 version fence, the typed tenant side-band,
# the J010 taint fence, and the J011 handoff fence) fails the gate
# exactly like a new lint finding
jdir="$(mktemp -d)"
trap 'rm -rf "$jdir"' EXIT
python -m paddle_tpu.analysis explore --scenario submit_kill \
    --max-schedules 6 --journal-dir "$jdir"
for sc in scale_up_mid_burst drain_retire_race rollout_migration \
        tenant_fairness integrity_trip kv_handoff_race \
        stream_disconnect_race; do
    python -m paddle_tpu.analysis explore --scenario "$sc" \
        --max-schedules 4 --journal-dir "$jdir"
done
shopt -s nullglob
journals=("$jdir"/*.jsonl)
if [ "${#journals[@]}" -eq 0 ]; then
    echo "protocol gate: explorer produced no journals" >&2
    exit 1
fi
# quiet on success; a J-code must surface its findings AND a copy of
# the offending journal that survives the EXIT trap's cleanup
verify_journal() {
    local j="$1" out keep
    if ! out="$(python -m paddle_tpu.analysis journal "$j" \
            --expect-closed)"; then
        keep="$(mktemp "${TMPDIR:-/tmp}/paddle_tpu_jfail_XXXXXX.jsonl")"
        cp "$j" "$keep"
        echo "$out"
        echo "protocol gate: J-codes in $(basename "$j")" \
             "(journal preserved at $keep)" >&2
        return 1
    fi
}
for j in "${journals[@]}"; do
    verify_journal "$j"
done
echo "protocol gate: ${#journals[@]} explorer journal(s) verified"

if [ "${PADDLE_TPU_LINT_BENCH:-0}" = "1" ]; then
    bdir="$jdir/bench"
    mkdir -p "$bdir"
    # the serving bench smokes directly (bench.py's main() always runs
    # the resnet headline first — far too heavy for a lint gate); the
    # audit env var makes every fleet close() replay its own journal
    PADDLE_TPU_AUDIT_JOURNAL=1 PADDLE_TPU_KEEP_JOURNAL_DIR="$bdir" \
        python -c "import bench; \
bench.bench_serving_fleet(); bench.bench_serving_slo()"
    bench_journals=("$bdir"/*.jsonl)
    if [ "${#bench_journals[@]}" -eq 0 ]; then
        echo "protocol gate: bench smoke produced no journals" >&2
        exit 1
    fi
    for j in "${bench_journals[@]}"; do
        verify_journal "$j"
    done
    echo "protocol gate: ${#bench_journals[@]} bench journal(s) verified"
fi
