#!/usr/bin/env bash
# Repo static-analysis gate: program verifier + trace-hazard and
# lock-discipline linters (paddle_tpu.analysis, ISSUE 5).
#
# Exits non-zero on any finding not covered by
# paddle_tpu/analysis/baseline.txt. Run it before committing; the
# tier-1 suite enforces the same invariant
# (tests/test_static_analysis.py::test_repo_is_clean_modulo_baseline).
#
# To accept a finding instead of fixing it:
#   python -m paddle_tpu.analysis --all --write-baseline
# then REPLACE every 'TODO: justify or fix' marker with a real one-line
# justification (a tier-1 test rejects TODO markers).
set -euo pipefail
cd "$(dirname "$0")/.."
# the program entries import jax via fluid; lint runs host-only
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
exec python -m paddle_tpu.analysis --all "$@"
