"""VGG16 benchmark in Fluid — port of the reference cluster workload
definition (/root/reference/benchmark/cluster/vgg16/vgg16_fluid.py; the
BASELINE.md cluster tables name this script).

Deliberate port of benchmark CLIENT code (the workload definition), not
framework code. Differences from the reference, by design:

* `--parallel` wraps the model in `fluid.layers.ParallelDo` — on this
  framework that lowers to mesh data-parallel SPMD execution (the
  reference ran a scope-per-GPU sub-block, parallel_do_op.cc:27).
* `--local False` uses the DistributeTranspiler shim + jax.distributed
  multi-host mesh instead of gRPC pservers; PSERVER role is meaningless
  under SPMD (dense DP = psum over the mesh) and exits with a notice.
* datasets come from paddle_tpu.v2.dataset (hermetic synthetic data).
"""

from __future__ import print_function

import argparse
import os
import time

import numpy as np

import paddle_tpu.v2 as paddle
import paddle_tpu.fluid as fluid


def str2bool(v):
    if v.lower() in ("yes", "true", "t", "y", "1"):
        return True
    if v.lower() in ("no", "false", "f", "n", "0"):
        return False
    raise argparse.ArgumentTypeError("Boolean value expected.")


parser = argparse.ArgumentParser(description=__doc__)
parser.add_argument("--batch_size", type=int, default=128,
                    help="Batch size for training.")
parser.add_argument("--learning_rate", type=float, default=1e-3,
                    help="Learning rate for training.")
parser.add_argument("--num_passes", type=int, default=50, help="No. of passes.")
parser.add_argument("--iterations", type=int, default=0,
                    help="Cap on train iterations per pass (0 = full pass).")
parser.add_argument("--device", type=str, default="TPU",
                    choices=["CPU", "GPU", "TPU"], help="The device type.")
parser.add_argument("--device_id", type=int, default=0, help="The device id.")
parser.add_argument("--data_format", type=str, default="NCHW",
                    choices=["NCHW"], help="The data order.")
parser.add_argument("--data_set", type=str, default="cifar10",
                    choices=["cifar10", "flowers"],
                    help="Optional dataset for benchmark.")
parser.add_argument("--parallel", type=str2bool, default=True,
                    help="Run the model under ParallelDo (mesh DP).")
parser.add_argument("--local", type=str2bool, default=True,
                    help="Whether to run as local mode.")


def vgg16_bn_drop(input):
    def conv_block(inp, num_filter, groups, dropouts):
        return fluid.nets.img_conv_group(
            input=inp,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * groups,
            conv_filter_size=3,
            conv_act="relu",
            conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts,
            pool_type="max",
        )

    conv1 = conv_block(input, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0])

    drop = fluid.layers.dropout(x=conv5, dropout_prob=0.5)
    fc1 = fluid.layers.fc(input=drop, size=512, act=None)
    bn = fluid.layers.batch_norm(input=fc1, act="relu")
    drop2 = fluid.layers.dropout(x=bn, dropout_prob=0.5)
    fc2 = fluid.layers.fc(input=drop2, size=512, act=None)
    return fc2


def main(args=None):
    args = parser.parse_args(args)
    if args.data_set == "cifar10":
        classdim = 10
        data_shape = [3, 32, 32]
    else:
        classdim = 102
        data_shape = [3, 224, 224]

    # Input data
    images = fluid.layers.data(name="pixel", shape=data_shape, dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")

    # Train program
    def model_head(images_, label_):
        net = vgg16_bn_drop(images_)
        predict_ = fluid.layers.fc(input=net, size=classdim, act="softmax")
        cost = fluid.layers.cross_entropy(input=predict_, label=label_)
        avg_cost_ = fluid.layers.mean(x=cost)
        return predict_, avg_cost_

    if args.parallel:
        places = fluid.layers.get_places()
        pd = fluid.layers.ParallelDo(places)
        with pd.do():
            images_ = pd.read_input(images)
            label_ = pd.read_input(label)
            predict, avg_cost = model_head(images_, label_)
            pd.write_output(avg_cost)
            pd.write_output(predict)
        avg_cost, predict = pd()
        avg_cost = fluid.layers.mean(x=avg_cost)
    else:
        predict, avg_cost = model_head(images, label)

    # Evaluator
    accuracy = fluid.evaluator.Accuracy(input=predict, label=label)

    # inference program
    inference_program = fluid.default_main_program().clone()
    with fluid.program_guard(inference_program):
        test_target = accuracy.metrics + accuracy.states
        inference_program = fluid.io.get_inference_program(test_target)

    # Optimization
    optimizer = fluid.optimizer.Adam(learning_rate=args.learning_rate)
    optimize_ops, params_grads = optimizer.minimize(avg_cost)

    place = (
        fluid.CPUPlace() if args.device == "CPU"
        else fluid.TPUPlace(args.device_id)
    )
    if args.parallel:
        # mesh data parallelism: every local chip joins the 'data' axis
        # (--parallel false = single-device baseline, reference semantics)
        from paddle_tpu import parallel

        import jax

        if parallel.get_default_mesh() is None and jax.local_device_count() > 1:
            parallel.set_default_mesh(
                parallel.make_mesh({"data": jax.local_device_count()})
            )
    exe = fluid.Executor(place)

    def reshape_batch(data):
        img_data = np.array(
            [x[0].reshape(data_shape) for x in data]
        ).astype("float32")
        y_data = np.array([x[1] for x in data]).astype("int64").reshape([-1, 1])
        return img_data, y_data

    def test(exe):
        accuracy.reset(exe)
        for batch_id, data in enumerate(test_reader()):
            img_data, y_data = reshape_batch(data)
            exe.run(inference_program,
                    feed={"pixel": img_data, "label": y_data})
        return accuracy.eval(exe)

    def train_loop(exe, trainer_prog):
        iters = 0
        for pass_id in range(args.num_passes):
            start_time = time.time()
            num_samples = 0
            accuracy.reset(exe)
            for batch_id, data in enumerate(train_reader()):
                if args.iterations and batch_id >= args.iterations:
                    break
                ts = time.time()
                img_data, y_data = reshape_batch(data)
                loss, acc = exe.run(
                    trainer_prog,
                    feed={"pixel": img_data, "label": y_data},
                    fetch_list=[avg_cost] + accuracy.metrics,
                )
                iters += 1
                num_samples += len(data)
                print(
                    "Pass = %d, Iters = %d, Loss = %f, Accuracy = %f, "
                    "spent %f"
                    % (pass_id, iters, float(np.ravel(loss)[0]),
                       float(np.ravel(acc)[0]), time.time() - ts)
                )
            pass_elapsed = time.time() - start_time
            pass_train_acc = accuracy.eval(exe)
            pass_test_acc = test(exe)
            print(
                "Pass = %d, Training performance = %f imgs/s, "
                "Train accuracy = %f, Test accuracy = %f\n"
                % (pass_id, num_samples / pass_elapsed,
                   float(np.ravel(pass_train_acc)[0]),
                   float(np.ravel(pass_test_acc)[0]))
            )

    train_reader = paddle.batch(
        paddle.reader.shuffle(
            paddle.dataset.cifar.train10() if args.data_set == "cifar10"
            else paddle.dataset.flowers.train(),
            buf_size=5120,
        ),
        batch_size=args.batch_size,
    )
    test_reader = paddle.batch(
        paddle.dataset.cifar.test10()
        if args.data_set == "cifar10" else paddle.dataset.flowers.test(),
        batch_size=args.batch_size,
    )

    if args.local:
        exe.run(fluid.default_startup_program())
        train_loop(exe, fluid.default_main_program())
    else:
        # multi-host: the transpiler shim validates the call; dense DP is
        # XLA-SPMD psum over the (process-spanning) mesh, so the PSERVER
        # role has nothing to serve
        training_role = os.getenv("TRAINING_ROLE", "TRAINER")
        if training_role == "PSERVER":
            print("PSERVER role is unnecessary under SPMD data "
                  "parallelism; dense gradients allreduce over the mesh.")
            return
        pserver_ips = os.getenv("PADDLE_INIT_PSERVERS", "")
        eplist = [":".join([ip, "6174"]) for ip in pserver_ips.split(",") if ip]
        trainers = int(os.getenv("TRAINERS", "1"))
        t = fluid.DistributeTranspiler()
        t.transpile(
            optimize_ops, params_grads,
            pservers=",".join(eplist), trainers=trainers,
        )
        exe.run(fluid.default_startup_program())
        train_loop(exe, t.get_trainer_program())


if __name__ == "__main__":
    main()
