"""Synthetic image provider for the timing benchmarks (counterpart of
reference benchmark/paddle/image/provider.py — which also feeds random
data; --job=time measures compute, not IO)."""

import numpy as np

from paddle_tpu.trainer.PyDataProvider2 import (
    CacheType,
    dense_vector,
    integer_value,
    provider,
)


def init_hook(settings, height, width, color, num_class, **kwargs):
    settings.height = height
    settings.width = width
    settings.data_size = height * width * (3 if color else 1)
    settings.num_class = num_class
    settings.is_infer = kwargs.get("is_infer", False)
    settings.num_samples = kwargs.get("num_samples", 2560)
    if settings.is_infer:
        settings.slots = [dense_vector(settings.data_size)]
    else:
        settings.slots = [dense_vector(settings.data_size), integer_value(num_class)]


@provider(init_hook=init_hook, min_pool_size=-1, cache=CacheType.CACHE_PASS_IN_MEM)
def process(settings, file_list):
    rng = np.random.RandomState(0)
    for _ in range(settings.num_samples):
        img = rng.rand(settings.data_size).astype("float32")
        if settings.is_infer:
            yield (img,)
        else:
            yield img, int(rng.randint(0, settings.num_class))
