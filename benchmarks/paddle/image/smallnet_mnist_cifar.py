"""Small CNN timing config (counterpart of reference
benchmark/paddle/image/smallnet_mnist_cifar.py)."""

height = 32
width = 32
num_class = 10
batch_size = get_config_arg("batch_size", int, 128)
num_samples = get_config_arg("num_samples", int, 2560)

define_py_data_sources2(
    "train.list", None, module="provider", obj="process",
    args={
        "height": height, "width": width, "color": True,
        "num_class": num_class, "num_samples": num_samples,
    },
)

settings(
    batch_size=batch_size,
    learning_rate=0.01 / batch_size,
    learning_method=MomentumOptimizer(0.9),
    regularization=L2Regularization(0.0005 * batch_size),
)

net = data_layer("data", size=height * width * 3)
net = img_conv_layer(input=net, filter_size=5, num_channels=3,
                     num_filters=32, stride=1, padding=2)
net = img_pool_layer(input=net, pool_size=3, stride=2, padding=1)
net = img_conv_layer(input=net, filter_size=5, num_filters=32, stride=1,
                     padding=2)
net = img_pool_layer(input=net, pool_size=3, stride=2, padding=1,
                     pool_type=AvgPooling())
net = img_conv_layer(input=net, filter_size=3, num_filters=64, stride=1,
                     padding=1)
net = img_pool_layer(input=net, pool_size=3, stride=2, padding=1,
                     pool_type=AvgPooling())
net = fc_layer(input=net, size=64, act=ReluActivation())
net = fc_layer(input=net, size=10, act=SoftmaxActivation())

lab = data_layer("label", num_class)
outputs(classification_cost(input=net, label=lab))
