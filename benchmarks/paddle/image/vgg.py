"""VGG timing config (counterpart of reference
benchmark/paddle/image/vgg.py; layer_num 16/19)."""

height = 224
width = 224
num_class = 1000
batch_size = get_config_arg("batch_size", int, 64)
layer_num = get_config_arg("layer_num", int, 19)
is_infer = get_config_arg("is_infer", bool, False)
num_samples = get_config_arg("num_samples", int, 2560)

define_py_data_sources2(
    "train.list" if not is_infer else None,
    "test.list" if is_infer else None,
    module="provider",
    obj="process",
    args={
        "height": height,
        "width": width,
        "color": True,
        "num_class": num_class,
        "is_infer": is_infer,
        "num_samples": num_samples,
    },
)

settings(
    batch_size=batch_size,
    learning_rate=0.001 / batch_size,
    learning_method=MomentumOptimizer(0.9),
    regularization=L2Regularization(0.0005 * batch_size),
)

img = data_layer(name="image", size=height * width * 3)

vgg_num = {16: 2, 19: 3}[layer_num]

net = img_conv_group(
    input=img, num_channels=3, conv_num_filter=[64, 64], conv_filter_size=3,
    conv_padding=1, conv_act=ReluActivation(), pool_size=2, pool_stride=2,
    pool_type=MaxPooling(),
)
net = img_conv_group(
    input=net, conv_num_filter=[128, 128], conv_filter_size=3,
    conv_padding=1, conv_act=ReluActivation(), pool_size=2, pool_stride=2,
    pool_type=MaxPooling(),
)
# VGG16: groups of 3 convs (vgg_num=2 -> +1); VGG19: groups of 4
for channels in (256, 512, 512):
    net = img_conv_group(
        input=net, conv_num_filter=[channels] * (vgg_num + 1),
        conv_filter_size=3, conv_padding=1, conv_act=ReluActivation(),
        pool_size=2, pool_stride=2, pool_type=MaxPooling(),
    )

net = fc_layer(input=net, size=4096, act=ReluActivation())
net = dropout_layer(input=net, dropout_rate=0.5)
net = fc_layer(input=net, size=4096, act=ReluActivation())
net = dropout_layer(input=net, dropout_rate=0.5)
net = fc_layer(input=net, size=num_class, act=SoftmaxActivation())

if is_infer:
    outputs(net)
else:
    lab = data_layer("label", num_class)
    outputs(classification_cost(input=net, label=lab))
