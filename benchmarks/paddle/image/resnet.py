"""ResNet timing config (counterpart of reference
benchmark/paddle/image/resnet.py — the north-star workload definition,
SURVEY §6). Same topology, driven through paddle_tpu.trainer."""

height = 224
width = 224
num_class = 1000
batch_size = get_config_arg("batch_size", int, 64)
layer_num = get_config_arg("layer_num", int, 50)
is_infer = get_config_arg("is_infer", bool, False)
num_samples = get_config_arg("num_samples", int, 2560)

define_py_data_sources2(
    "train.list" if not is_infer else None,
    "test.list" if is_infer else None,
    module="provider",
    obj="process",
    args={
        "height": height,
        "width": width,
        "color": True,
        "num_class": num_class,
        "is_infer": is_infer,
        "num_samples": num_samples,
    },
)

settings(
    batch_size=batch_size,
    learning_rate=0.01 / batch_size,
    learning_method=MomentumOptimizer(0.9),
    regularization=L2Regularization(0.0005 * batch_size),
)


def conv_bn(name, input, filter_size, num_filters, stride, padding,
            channels=None, active_type=ReluActivation()):
    conv = img_conv_layer(
        name=name + "_conv",
        input=input,
        filter_size=filter_size,
        num_channels=channels,
        num_filters=num_filters,
        stride=stride,
        padding=padding,
        act=LinearActivation(),
        bias_attr=False,
    )
    return batch_norm_layer(name=name + "_bn", input=conv, act=active_type)


def bottleneck(name, input, num_filters1, num_filters2, stride=1):
    last_name = name + "_branch2c"
    mid = conv_bn(name + "_branch2a", input, 1, num_filters1, stride, 0)
    mid = conv_bn(name + "_branch2b", mid, 3, num_filters1, 1, 1)
    mid = conv_bn(last_name, mid, 1, num_filters2, 1, 0,
                  active_type=LinearActivation())
    if stride != 1 or input.im_shape[0] != num_filters2:
        shortcut = conv_bn(name + "_branch1", input, 1, num_filters2, stride,
                           0, active_type=LinearActivation())
    else:
        shortcut = input
    return addto_layer(name=name + "_addto", input=[mid, shortcut],
                       act=ReluActivation())


def res_group(name, input, blocks, num_filters1, num_filters2, stride):
    out = bottleneck(name + "a", input, num_filters1, num_filters2, stride)
    for i in range(1, blocks):
        out = bottleneck("%s%c" % (name, ord('a') + i), out, num_filters1,
                         num_filters2, 1)
    return out


cfgs = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}
n2, n3, n4, n5 = cfgs[layer_num]

img = data_layer(name="image", size=height * width * 3)
net = conv_bn("conv1", img, 7, 64, 2, 3, channels=3)
net = img_pool_layer(input=net, pool_size=3, stride=2, padding=1,
                     pool_type=MaxPooling())
net = res_group("res2", net, n2, 64, 256, 1)
net = res_group("res3", net, n3, 128, 512, 2)
net = res_group("res4", net, n4, 256, 1024, 2)
net = res_group("res5", net, n5, 512, 2048, 2)
net = img_pool_layer(input=net, pool_size=7, stride=1, pool_type=AvgPooling())
net = fc_layer(input=net, size=num_class, act=SoftmaxActivation())

if is_infer:
    outputs(net)
else:
    lbl = data_layer(name="label", size=num_class)
    outputs(cross_entropy(name="loss", input=net, label=lbl))
