"""GoogLeNet-v1 timing config (counterpart of reference
benchmark/paddle/image/googlenet.py; BASELINE 1149 ms/batch bs=128 K40m)."""

height = 224
width = 224
num_class = 1000
batch_size = get_config_arg("batch_size", int, 128)
is_infer = get_config_arg("is_infer", bool, False)
num_samples = get_config_arg("num_samples", int, 2560)

define_py_data_sources2(
    "train.list" if not is_infer else None,
    "test.list" if is_infer else None,
    module="provider",
    obj="process",
    args={
        "height": height,
        "width": width,
        "color": True,
        "num_class": num_class,
        "is_infer": is_infer,
        "num_samples": num_samples,
    },
)

settings(
    batch_size=batch_size,
    learning_rate=0.01 / batch_size,
    learning_method=MomentumOptimizer(0.9),
    regularization=L2Regularization(0.0005 * batch_size),
)


def inception(name, input, nf1, nf3r, nf3, nf5r, nf5, proj):
    t1 = img_conv_layer(
        name=name + "_1x1", input=input, filter_size=1, num_filters=nf1,
        stride=1, padding=0, act=ReluActivation(),
    )
    t3 = img_conv_layer(
        name=name + "_3x3r", input=input, filter_size=1, num_filters=nf3r,
        stride=1, padding=0, act=ReluActivation(),
    )
    t3 = img_conv_layer(
        name=name + "_3x3", input=t3, filter_size=3, num_filters=nf3,
        stride=1, padding=1, act=ReluActivation(),
    )
    t5 = img_conv_layer(
        name=name + "_5x5r", input=input, filter_size=1, num_filters=nf5r,
        stride=1, padding=0, act=ReluActivation(),
    )
    t5 = img_conv_layer(
        name=name + "_5x5", input=t5, filter_size=5, num_filters=nf5,
        stride=1, padding=2, act=ReluActivation(),
    )
    tp = img_pool_layer(
        name=name + "_pool", input=input, pool_size=3, stride=1, padding=1,
        pool_type=MaxPooling(),
    )
    tp = img_conv_layer(
        name=name + "_proj", input=tp, filter_size=1, num_filters=proj,
        stride=1, padding=0, act=ReluActivation(),
    )
    return concat_layer(name=name, input=[t1, t3, t5, tp])


img = data_layer(name="image", size=height * width * 3)

net = img_conv_layer(input=img, filter_size=7, num_channels=3,
                     num_filters=64, stride=2, padding=3,
                     act=ReluActivation())
net = img_pool_layer(input=net, pool_size=3, stride=2, padding=1)
net = img_cmrnorm_layer(input=net, size=5)
net = img_conv_layer(input=net, filter_size=1, num_filters=64, stride=1,
                     padding=0, act=ReluActivation())
net = img_conv_layer(input=net, filter_size=3, num_filters=192, stride=1,
                     padding=1, act=ReluActivation())
net = img_cmrnorm_layer(input=net, size=5)
net = img_pool_layer(input=net, pool_size=3, stride=2, padding=1)

net = inception("ince3a", net, 64, 96, 128, 16, 32, 32)
net = inception("ince3b", net, 128, 128, 192, 32, 96, 64)
net = img_pool_layer(input=net, pool_size=3, stride=2, padding=1)

net = inception("ince4a", net, 192, 96, 208, 16, 48, 64)
net = inception("ince4b", net, 160, 112, 224, 24, 64, 64)
net = inception("ince4c", net, 128, 128, 256, 24, 64, 64)
net = inception("ince4d", net, 112, 144, 288, 32, 64, 64)
net = inception("ince4e", net, 256, 160, 320, 32, 128, 128)
net = img_pool_layer(input=net, pool_size=3, stride=2, padding=1)

net = inception("ince5a", net, 256, 160, 320, 32, 128, 128)
net = inception("ince5b", net, 384, 192, 384, 48, 128, 128)
net = img_pool_layer(input=net, pool_size=7, stride=1, pool_type=AvgPooling())

net = dropout_layer(input=net, dropout_rate=0.4)
net = fc_layer(input=net, size=num_class, act=SoftmaxActivation())

if is_infer:
    outputs(net)
else:
    lab = data_layer(name="label", size=num_class)
    outputs(cross_entropy(input=net, label=lab))
