"""AlexNet timing config (counterpart of reference
benchmark/paddle/image/alexnet.py)."""

height = 227
width = 227
num_class = 1000
batch_size = get_config_arg("batch_size", int, 128)
gp = get_config_arg("layer_num", int, 1)
is_infer = get_config_arg("is_infer", bool, False)
num_samples = get_config_arg("num_samples", int, 2560)

define_py_data_sources2(
    "train.list" if not is_infer else None,
    "test.list" if is_infer else None,
    module="provider",
    obj="process",
    args={
        "height": height,
        "width": width,
        "color": True,
        "num_class": num_class,
        "is_infer": is_infer,
        "num_samples": num_samples,
    },
)

settings(
    batch_size=batch_size,
    learning_rate=0.01 / batch_size,
    learning_method=MomentumOptimizer(0.9),
    regularization=L2Regularization(0.0005 * batch_size),
)

net = data_layer("data", size=height * width * 3)

net = img_conv_layer(input=net, filter_size=11, num_channels=3,
                     num_filters=96, stride=4, padding=1)
net = img_cmrnorm_layer(input=net, size=5, scale=0.0001, power=0.75)
net = img_pool_layer(input=net, pool_size=3, stride=2)

net = img_conv_layer(input=net, filter_size=5, num_filters=256, stride=1,
                     padding=2, groups=gp)
net = img_cmrnorm_layer(input=net, size=5, scale=0.0001, power=0.75)
net = img_pool_layer(input=net, pool_size=3, stride=2)

net = img_conv_layer(input=net, filter_size=3, num_filters=384, stride=1,
                     padding=1)
net = img_conv_layer(input=net, filter_size=3, num_filters=384, stride=1,
                     padding=1, groups=gp)
net = img_conv_layer(input=net, filter_size=3, num_filters=256, stride=1,
                     padding=1, groups=gp)
net = img_pool_layer(input=net, pool_size=3, stride=2)

net = fc_layer(input=net, size=4096, act=ReluActivation())
net = dropout_layer(input=net, dropout_rate=0.5)
net = fc_layer(input=net, size=4096, act=ReluActivation())
net = dropout_layer(input=net, dropout_rate=0.5)
net = fc_layer(input=net, size=1000, act=SoftmaxActivation())

if is_infer:
    outputs(net)
else:
    lab = data_layer("label", num_class)
    outputs(cross_entropy(input=net, label=lab))
