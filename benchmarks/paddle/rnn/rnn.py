"""LSTM text-classification timing config (counterpart of reference
benchmark/paddle/rnn/rnn.py: embedding -> stacked simple_lstm -> last_seq
-> softmax; BASELINE 184 ms/batch @ bs=64 h=512 on K40m)."""

num_class = 2
vocab_size = 30000
fixedlen = 100
batch_size = get_config_arg("batch_size", int, 128)
lstm_num = get_config_arg("lstm_num", int, 1)
hidden_size = get_config_arg("hidden_size", int, 128)
pad_seq = get_config_arg("pad_seq", bool, True)
num_samples = get_config_arg("num_samples", int, 2560)

define_py_data_sources2(
    "train.list", None, module="provider", obj="process",
    args={
        "vocab_size": vocab_size,
        "pad_seq": pad_seq,
        "maxlen": fixedlen,
        "num_samples": num_samples,
    },
)

settings(
    batch_size=batch_size,
    learning_rate=2e-3,
    learning_method=AdamOptimizer(),
    regularization=L2Regularization(8e-4),
    gradient_clipping_threshold=25,
)

net = data_layer("data", size=vocab_size)
net = embedding_layer(input=net, size=128)
for _ in range(lstm_num):
    net = simple_lstm(input=net, size=hidden_size)
net = last_seq(input=net)
net = fc_layer(input=net, size=2, act=SoftmaxActivation())

lab = data_layer("label", num_class)
outputs(classification_cost(input=net, label=lab))
