"""Synthetic ragged-text provider for the RNN timing benchmark
(counterpart of reference benchmark/paddle/rnn/provider.py)."""

import numpy as np

from paddle_tpu.trainer.PyDataProvider2 import (
    integer_value,
    integer_value_sequence,
    provider,
)


def init_hook(settings, vocab_size, pad_seq, maxlen, **kwargs):
    settings.vocab_size = vocab_size
    settings.pad_seq = pad_seq
    settings.maxlen = maxlen
    settings.num_samples = kwargs.get("num_samples", 2560)
    settings.slots = [integer_value_sequence(vocab_size), integer_value(2)]


@provider(init_hook=init_hook, min_pool_size=-1)
def process(settings, file_list):
    rng = np.random.RandomState(0)
    for _ in range(settings.num_samples):
        if settings.pad_seq:
            l = settings.maxlen
        else:
            l = int(rng.randint(10, settings.maxlen + 1))
        words = rng.randint(0, settings.vocab_size, l).tolist()
        yield words, int(rng.randint(0, 2))
