"""On-chip acceptance drive: train three book models (SURVEY §4.3) on
the REAL device through the user-facing fluid surface and check they
learn, then round-trip an inference model through save/load.

The pytest suite runs the full acceptance set on the virtual CPU mesh
(tests/conftest.py pins JAX_PLATFORMS=cpu); this script is the silicon
companion — run it with no JAX_PLATFORMS override so the default
(tunnel TPU) backend is used:

    python benchmarks/onchip_acceptance.py

Prints one JSON line per model and a final summary line. Reference
anchors: fit_a_line / recognize_digits / understand_sentiment book
chapters (python/paddle/v2/fluid/tests/book/ in the reference tree).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _honor_platform_env():
    """The ambient sitecustomize latches the tunnel platform at
    interpreter boot; honor an explicit JAX_PLATFORMS request (e.g.
    JAX_PLATFORMS=cpu for a smoke run of this script off-chip)."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass


_honor_platform_env()


def _losses_fall(losses, factor=0.7):
    head = float(np.mean(losses[:3]))
    tail = float(np.mean(losses[-3:]))
    return tail < head * factor, head, tail


def drive_fit_a_line(steps=60):
    """Linear regression on a synthetic housing-style feature set."""
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1, act=None)
        cost = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)

    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    w_true = rng.randn(13, 1).astype(np.float32)
    losses = []
    for _ in range(steps):
        xb = rng.randn(32, 13).astype(np.float32)
        yb = xb @ w_true + 0.01 * rng.randn(32, 1).astype(np.float32)
        (loss,) = exe.run(main, feed={"x": xb, "y": yb},
                          fetch_list=[cost])
        losses.append(float(np.ravel(loss)[0]))
    ok, head, tail = _losses_fall(losses)
    return {"model": "fit_a_line", "ok": ok,
            "loss_head": round(head, 4), "loss_tail": round(tail, 4)}


def drive_recognize_digits(steps=40):
    """Conv net on synthetic MNIST-shaped data + save/load round trip."""
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[1, 28, 28],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        conv = fluid.nets.simple_img_conv_pool(
            input=img, filter_size=5, num_filters=8, pool_size=2,
            pool_stride=2, act="relu")
        pred = fluid.layers.fc(input=conv, size=10, act="softmax")
        cost = fluid.layers.mean(
            x=fluid.layers.cross_entropy(input=pred, label=label))
        acc = fluid.layers.accuracy(input=pred, label=label)
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(cost)

    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    # ten fixed class templates + noise: learnable quickly, non-trivial
    templates = rng.rand(10, 1, 28, 28).astype(np.float32)
    losses, accs = [], []
    for _ in range(steps):
        lb = rng.randint(0, 10, (64, 1)).astype(np.int64)
        xb = templates[lb[:, 0]] + 0.1 * rng.randn(64, 1, 28, 28).astype(
            np.float32)
        loss, a = exe.run(main, feed={"img": xb, "label": lb},
                          fetch_list=[cost, acc])
        losses.append(float(np.ravel(loss)[0]))
        accs.append(float(np.ravel(a)[0]))
    ok, head, tail = _losses_fall(losses)
    # inference save/load round trip through the on-disk format
    with tempfile.TemporaryDirectory() as d:
        fluid.io.save_inference_model(d, ["img"], [pred], exe,
                                      main_program=main)
        prog2, feeds, fetches = fluid.io.load_inference_model(d, exe)
        lb = rng.randint(0, 10, (8, 1)).astype(np.int64)
        xb = templates[lb[:, 0]].astype(np.float32)
        (out,) = exe.run(prog2, feed={feeds[0]: xb}, fetch_list=fetches)
        reload_ok = (np.asarray(out).shape == (8, 10)
                     and float(np.max(out)) <= 1.0)
    return {"model": "recognize_digits", "ok": bool(ok and reload_ok),
            "loss_head": round(head, 4), "loss_tail": round(tail, 4),
            "final_acc": round(accs[-1], 3), "reload_ok": bool(reload_ok)}


def drive_understand_sentiment(steps=40):
    """Embedding + LSTM + pool classifier on synthetic token streams."""
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                                 lod_level=1)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(input=data, size=[500, 32])
        fc = fluid.layers.fc(input=emb, size=128)
        lstm, _ = fluid.layers.dynamic_lstm(input=fc, size=128)
        pooled = fluid.layers.sequence_pool(input=lstm, pool_type="max")
        pred = fluid.layers.fc(input=pooled, size=2, act="softmax")
        cost = fluid.layers.mean(
            x=fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(cost)

    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(steps):
        lb = rng.randint(0, 2, (32, 1)).astype(np.int64)
        # ragged batch: variable-length sequences with class-dependent
        # token ranges (low ids class 0, high ids class 1) — learnable
        # by the embedding alone, and exercises the LoD path on-chip
        lens = rng.randint(20, 64, 32)
        toks = [
            (0 if lb[i, 0] == 0 else 250)
            + rng.randint(0, 250, lens[i])
            for i in range(32)
        ]
        lod = np.cumsum([0] + list(lens)).astype(np.int32)
        flat = np.concatenate(toks).astype(np.int64)
        (loss,) = exe.run(main,
                          feed={"words": (flat, [lod]), "label": lb},
                          fetch_list=[cost])
        losses.append(float(np.ravel(loss)[0]))
    ok, head, tail = _losses_fall(losses)
    return {"model": "understand_sentiment", "ok": ok,
            "loss_head": round(head, 4), "loss_tail": round(tail, 4)}


def main():
    import jax

    backend = jax.default_backend()
    t0 = time.time()
    results = []
    for fn in (drive_fit_a_line, drive_recognize_digits,
               drive_understand_sentiment):
        t = time.time()
        try:
            rec = fn()
        except Exception as e:  # one failure must not hide the others
            rec = {"model": fn.__name__, "ok": False,
                   "error": "%s: %s" % (type(e).__name__, e)}
        rec["seconds"] = round(time.time() - t, 1)
        results.append(rec)
        print(json.dumps(rec), flush=True)
    print(json.dumps({
        "metric": "onchip_acceptance",
        "backend": backend,
        "all_ok": all(r["ok"] for r in results),
        "total_s": round(time.time() - t0, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
