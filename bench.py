"""Benchmark suite: training throughput + MFU on the local chip.

Workloads (BASELINE.md units, reference benchmark/ configs as workload
definitions):

  resnet50  — headline: chip training throughput, img/s vs the 1500
              img/s/chip north star (BASELINE.json); a companion
              `resnet50_input_pipeline` record times the SAME model fed
              end-to-end from the native recordio prefetch queue (uint8
              images, normalised on device). On this harness the
              pipeline number is bounded by the remote-TPU tunnel's
              ~40 MB/s sustained h2d bandwidth (reported as h2d_MBps),
              which a real TPU host does not have.
  vgg16     — benchmark/paddle/image/vgg.py, img/s
  alexnet   — benchmark/paddle/image/alexnet.py, img/s vs 334 ms/batch
              bs=128 (benchmark/README.md:37 -> 383 img/s)
  googlenet — benchmark/paddle/image/googlenet.py, img/s vs 1149 ms/batch
              bs=128 (benchmark/README.md:50 -> 111.4 img/s)
  lstm      — benchmark/paddle/rnn/rnn.py (2x LSTM h=512, bs=64, seq 100),
              ms/batch vs 184 ms/batch (benchmark/README.md:119)
  resnet50_infer — serving-side: clone(for_test=True) forward, img/s
              vs the reference's only published inference number
              (217.69 img/s CPU MKL-DNN bs=16,
              IntelOptimizedPaddle.md:87)
  transformer_lm — long-context flagship: decoder-only LM (8x512, T=1024,
              flash attention, bf16), tokens/s + MFU; beyond-reference,
              no 2018 baseline
  transformer_lm_large — 12x1024 (heads=16, T=2048, flash, bf16):
              MXU-shaped matmuls; beyond-reference, no 2018 baseline
  transformer_lm_xl — 16x2048 (heads=16, T=2048, B=2): the
              utilization headline — dim-2048 matmuls run the MXU
              near peak (72.2% MFU measured r5); beyond-reference
  serving_decode — continuous-batching serving engine
              (paddle_tpu/serving): aggregate tok/s + mean slot
              occupancy + compile counts under a fixed-seed Poisson
              arrival trace; beyond-reference, no 2018 baseline
  serving_shared_prefix — prefix-cache acceptance (ISSUE 4): the same
              fixed-seed Poisson trace over K prompt families sharing
              a common header, run with the prefix KV pool off vs on;
              reports prefill-tokens-computed both ways, hit rate, and
              TTFT; greedy outputs must match between runs
  serving_paged — paged-KV + speculative-decoding acceptance (ISSUE
              7): the same fixed-seed Poisson trace at ONE fixed KV
              HBM budget through the [S, max_len]-slab-equivalent
              engine, the paged block pool, and paged + self-drafting
              speculative decoding; reports peak resident slots (paged
              must beat slab at equal budget), speculative
              accept-rate, and tok/s per mode; outputs must be
              token-identical across all three runs
  serving_paged_kernel — fused paged-attention kernel acceptance
              (ISSUE 13): the same fixed-seed shared-header trace with
              paged_kernel="gather" vs "fused" (Pallas table-walk, no
              materialised view) across aliasing/COW/chunking/spec;
              hard-raises on any output divergence or any _paged_view
              gather in the fused run; tokens/s contrast on-chip-only
  serving_fleet — fault-tolerant fleet acceptance (ISSUE 6): the same
              fixed-seed shared-header Poisson trace through a
              single replica, an N=3 fleet with prefix-affinity
              routing + a mid-trace kill drill, and an N=3 fleet with
              affinity off; reports requests lost (must be 0),
              duplicate completions (must be 0), failovers, the
              fleet-wide prefix reuse contrast, and tok/s vs the N×1
              ideal; outputs must be token-identical across all runs
  serving_slo — gray-failure / request-SLO acceptance (ISSUE 8): the
              same fixed-seed Poisson trace of deadline-carrying
              interactive requests through a healthy N-replica fleet
              and through the same fleet with one replica gray-slowed
              (slow@ fault: heartbeating, but every step stalls)
              mid-trace; reports expired requests (must be 0 — the
              gray replica is demoted and its work hedged to survivors
              with token-level resume), resumed requests and tokens
              reused (journal-verified: no emitted token is ever
              re-decoded), demote/probe/restore counts, and p99 TTFT
              healthy vs gray (gray must stay under the slow window —
              the demotion bounded the tail); outputs must be
              token-identical across both runs
  serving_elastic — disaggregated elastic fleet acceptance (ISSUE 11):
              the same fixed-seed Poisson BURST trace of
              deadline-carrying requests through a STATIC tiered fleet
              (prefill/decode disaggregation only) and through the
              ELASTIC fleet (autoscaler on, one mid-trace
              roll_weights to a CRC-verified checkpoint of the same
              weights); pins zero expired requests, zero lost or
              duplicated rids, >=1 scale-up spawn and >=1 scale-down
              retirement, >=1 prefill->decode migration, exactly one
              completed rollout, a corrupted-candidate rollout
              aborting with every replica still serving the old
              version, the journal DFA green including the J009
              version fence (no mixed-version output), and outputs
              token-identical between the static and elastic runs
  serving_multitenant — multi-tenant serving acceptance (ISSUE 12):
              a fixed-seed 3-tenant Poisson mix (two well-behaved
              deadline-class tenants with their own LoRA adapters +
              one adapter tenant driving pool eviction) through one
              fleet, with a fourth tenant BURSTING past its
              token-bucket quota mid-trace and a zoo tenant running
              batched Executor inference through the same scheduler;
              pins zero deadline misses for the well-behaved tenants,
              the burst shed via TenantQuotaExceeded and NEVER
              FleetSaturated (and never journaled), >=1 adapter-pool
              eviction (adapters page like KV), batch results equal
              to the direct Executor run, the journal DFA green with
              the typed tenant side-band, and every tenant's outputs
              token-identical to a per-tenant SEQUENTIAL run — N
              adapters batched over one base model change nothing
  serving_integrity — silent-corruption tolerance acceptance
              (ISSUE 15): the same fixed-seed Poisson shared-header
              trace through (a) a clean fleet with canaries +
              fingerprints armed, (b) the same fleet with one replica
              GARBLED mid-trace (garble@ fault: wrong-but-finite
              tokens — only a known-answer canary mismatch can see
              it), and (c) with one resident KV block FLIPPED
              mid-trace (flip@ fault: caught by the block-fingerprint
              spot-check at aliased re-open); pins zero trips/
              mismatches in the clean run, the corrupt replica
              tripping + quarantining EXACTLY once per drill (fresh
              incarnation via the supervisor backoff), zero lost or
              duplicated rids, outputs token-identical to the clean
              run (zero tainted tokens survive — the taint window
              re-decoded on a healthy survivor), and the journal DFA
              green --expect-closed including the J010 taint fence
              (only tainted tokens ever re-decode)
  training_sentinel — silent-failure tolerance acceptance (ISSUE 10):
              a fixed-seed training job over shards containing one
              poisoned chunk; pins >=1 sentinel trip, rollback landing
              on the last KNOWN-GOOD step, the poison chunk journaled
              to quarantine exactly once, a finite committed loss curve
              bit-identical to a clean run that never saw the chunk,
              and (sub-drill) resume succeeding past a corrupted latest
              checkpoint with zero manual intervention. Pure host work
  input_pipeline — host-side loader overlap (paddle_tpu/data):
              RecordShard shards -> ShardedDataset -> DataLoader on a
              fixed-seed synthetic trace, prefetch OFF (synchronous
              baseline) vs ON (decode threads + bounded queue);
              reports batches/s and the loader-wait fraction. Pure
              host work — fully offline-measurable (ISSUE 3)

Timing: per-step cost is measured by differencing two multi-step
`run_repeated` calls ((T(hi)-T(lo))/(hi-lo)), which cancels the
per-dispatch round-trip latency of the remote-TPU tunnel (~3 s/call —
an artifact of this harness, not of the framework or chip).

MFU = img_per_sec x 3 x fwd_flops_per_sample / 197e12 (v5e bf16 peak;
backward ~= 2x forward for conv/matmul nets, so train step ~= 3x fwd).

Prints one JSON line per workload; the FINAL line is the headline
ResNet-50 record (driver contract) and carries `mfu` and the full
`workloads` map.

Record field glossary (r4 measurement protocol):
  timing.raw_chunk_s   every raw multi-step chunk wall time, per step
                       count — the full audit trail
  timing.per_step_s_min/median  per-step estimates differencing the
                       per-count minima (noise-robust: a tunnel hiccup
                       only ADDs time) and medians
  timing.spread        (max-min)/min of the raw chunks per step count
  timing.spread_trimmed  same after dropping at most ONE worst chunk
                       per count (only when >=4 chunks were taken, the
                       raw spread failed, AND the max chunk is a gross
                       outlier vs the median — a tunnel stall, not
                       smooth drift; the drop is recorded in
                       outliers_dropped and the raw data stays)
  timing.stable / stable  true iff every trimmed spread <=
                       BENCH_SPREAD_LIMIT (default 10%) — a record
                       with stable=false cannot demonstrate progress
                       or regression
  timing.chunk_scale   >1 when step counts were scaled up so the low
                       chunk reaches BENCH_MIN_CHUNK_S (two-point
                       probe of the warmed counts solves out the
                       additive per-call tunnel overhead)
  mfu                  model-FLOPs utilisation (published fwd FLOPs x3)
  xla_flops_util       XLA cost-model FLOPs / peak (counts backward
                       dilated convs, ~1.8x model FLOPs on ResNet)
  roofline             arithmetic intensity vs the v5e ridge
                       (~240 flops/byte), the bound verdict (hbm|mxu),
                       the cost-model-implied ceiling img/s, and the
                       achieved fraction of that ceiling
"""

from __future__ import annotations

import glob
import json
import os
import struct
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

BASELINE_IMG_PER_SEC = 1500.0  # ResNet-50 north star (BASELINE.json)
PEAK_FLOPS = 197e12  # TPU v5e bf16
HBM_BW = 819e9  # TPU v5e HBM bytes/s

# forward FLOPs per sample (2 FLOPs per MAC), standard published counts
FWD_FLOPS = {
    "resnet50": 4.09e9,   # 224x224, bottleneck v1
    "vgg16": 15.47e9,     # 224x224
    "vgg19": 19.63e9,     # 224x224
    "alexnet": 1.43e9,    # 224x224 (0.71 GMAC)
    "googlenet": 3.0e9,   # 224x224 inception v1 (1.5 GMAC)
    "mobilenet": 1.14e9,  # 224x224 v1 1.0x (0.57 GMAC)
}

AMP = os.environ.get("BENCH_AMP", "1") == "1"
IMG_DTYPE = "bfloat16" if AMP else "float32"


def _build_image_workload(fluid, model_fn, batch, class_dim=1000, uint8_input=False):
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        if uint8_input:
            # realistic input pipeline: uint8 images cross the host->device
            # link; normalisation happens on device in the compiled step
            raw = fluid.layers.data(name="image", shape=[3, 224, 224], dtype="uint8")
            image = fluid.layers.scale(
                x=fluid.layers.cast(raw, IMG_DTYPE), scale=1.0 / 255.0
            )
        else:
            image = fluid.layers.data(name="image", shape=[3, 224, 224], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        predict = model_fn(image, class_dim)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg_cost = fluid.layers.mean(x=cost)
        opt = fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9)
        opt.minimize(avg_cost)
    main_prog.amp = AMP
    return main_prog, startup, avg_cost


_DEADLINE = None  # monotonic deadline set by main(); guards extra compiles


SPREAD_LIMIT = float(os.environ.get("BENCH_SPREAD_LIMIT", "0.10"))
TIMING_CHUNKS = int(os.environ.get("BENCH_TIMING_CHUNKS", "3"))
# floor on the LOW-count chunk's steady-state wall time: the tunnel's
# per-call jitter is additive and of order tens of ms, so a chunk much
# shorter than this cannot pass the spread gate no matter how steady the
# chip is (r5: alexnet/mobilenet/lstm/sparse all captured stable=false
# purely because their 8-12-step chunks ran 0.06-0.25 s)
MIN_CHUNK_S = float(os.environ.get("BENCH_MIN_CHUNK_S", "1.0"))
# bounds the iterative rescale (runtime/compile guard; the r5 sparse row
# needed >16 to bring its 8-step chunks to the floor)
MAX_CHUNK_SCALE = int(os.environ.get("BENCH_MAX_CHUNK_SCALE", "32"))


def _diff_time(run_at, s_lo, s_hi, return_info=False, scale_steps=True):
    """Steady-state per-step seconds by differencing two multi-step calls
    (cancels the per-call dispatch/sync overhead of the tunnel).
    `run_at(steps)` must execute `steps` iterations and block until the
    result is real; with scale_steps=True (default) it must accept ANY
    positive step count, because the counts are scaled up until the low
    chunk runs at least MIN_CHUNK_S (callers whose step count has
    semantic meaning — e.g. KV-cache decode length — pass
    scale_steps=False).

    Measurement protocol (falsifiability requirements from the r3
    verdict): warm both step counts (compile), then time >=3 chunks per
    count; if either count's spread ((max-min)/min) exceeds
    SPREAD_LIMIT, take one more round of chunks. The estimate differs
    the per-count MINIMA (min is the noise-robust statistic against a
    tunnel that can only ADD time); the median-based estimate, every
    raw chunk timing, the spreads, and a `stable` verdict are all
    reported so the record can be audited and two runs compared."""
    warm_s = {}

    def _warm(s):
        if s not in warm_s:
            t0 = time.time()
            run_at(s)  # compile + warm
            warm_s[s] = time.time() - t0

    _warm(s_lo)
    _warm(s_hi)

    def _probe(s):
        t0 = time.time()
        run_at(s)  # steady-state (already compiled)
        return time.time() - t0

    base_lo, base_hi = s_lo, s_hi
    scale = 1
    seeds = {}  # steady chunks measured while scaling; reused as data
    if scale_steps:
        # two-point solve for the scale: probe BOTH already-warmed
        # counts (zero extra compiles), fit t(n) = overhead + n*per_step
        # — the additive per-call tunnel overhead that makes a naive
        # scale = ceil(floor/probe) undershoot is solved for exactly.
        t1 = _probe(base_lo)
        # every run_at blocks on a value readback, so a healthy probe is
        # a full execution (>= tunnel RTT + real steps). A probe under
        # 10 ms is the signature of the r3 memoized/ack-only failure
        # mode — scaling off it would saturate at MAX_CHUNK_SCALE and
        # waste the side budget on every workload, so don't scale then;
        # and the suspect probe is NOT a steady-state chunk, so it must
        # not seed raw[] either (it would deflate dt_min and inflate
        # that count's spread — the stable=false flag still fires from
        # the real chunks if the mode persists).
        if t1 >= 0.01:
            seeds.setdefault(base_lo, []).append(t1)
        if 0.01 <= t1 < MIN_CHUNK_S:
            t2 = _probe(base_hi)
            if t2 >= 0.01:
                seeds.setdefault(base_hi, []).append(t2)
            per_step = (t2 - t1) / (base_hi - base_lo)
            if per_step > 0:
                ovh = max(t1 - base_lo * per_step, 0.0)
                need = (MIN_CHUNK_S - ovh) / (base_lo * per_step)
            else:  # probe noise inverted the pair; fall back to ratio
                need = MIN_CHUNK_S / t1
            scale = int(np.clip(np.ceil(need), 1, MAX_CHUNK_SCALE))
    s_lo, s_hi = base_lo * scale, base_hi * scale
    if scale > 1:
        # the probes above ran at the PRE-scale counts. When the solved
        # scale lands a final count on base_hi (e.g. steps (24,144) at
        # scale 6 -> s_lo == 144), merging them would count a pre-scale
        # probe — possibly carrying exactly the stall the corrective-
        # rescale path below exists to absorb — as a steady chunk at
        # the final count and consume the single-outlier trim
        # allowance. Only probes taken at the FINAL counts are reused.
        seeds = {}
    _warm(s_lo)
    _warm(s_hi)
    if scale > 1:
        # verify the solve landed: a stall in the s_hi probe inflates
        # per_step and undershoots the floor. One corrective rescale
        # off the verified chunk (bounded: exactly one).
        tv = _probe(s_lo)
        if tv < MIN_CHUNK_S * 0.9 and scale < MAX_CHUNK_SCALE:
            scale = int(np.clip(
                np.ceil(scale * MIN_CHUNK_S / max(tv, 1e-3)),
                scale + 1, MAX_CHUNK_SCALE))
            s_lo, s_hi = base_lo * scale, base_hi * scale
            _warm(s_lo)
            _warm(s_hi)
        else:
            seeds.setdefault(s_lo, []).append(tv)
    raw = {s_lo: [], s_hi: []}
    # only probes taken at the FINAL counts survive in `seeds`; they are
    # valid steady-state chunks — count them instead of discarding
    # (saves an execution per workload)
    for s, ts in seeds.items():
        if s in raw:
            raw[s].extend(ts)
    rounds = 0
    while True:
        rounds += 1
        for s in (s_lo, s_hi):
            for _ in range(TIMING_CHUNKS):
                t0 = time.time()
                run_at(s)
                raw[s].append(time.time() - t0)
        spread = {
            s: (max(raw[s]) - min(raw[s])) / min(raw[s]) for s in raw
        }
        if max(spread.values()) <= SPREAD_LIMIT or rounds >= 2:
            break
    # stability verdict: a single gross tunnel stall (r5 observed one
    # 144-step chunk at 42 s among five at 6.47 s) should not flip the
    # flag when the remaining chunks agree — drop at most ONE worst
    # chunk per count, visibly: the full raw data stays in the record
    # and trimmed counts are reported. Guarded so smooth run-to-run
    # drift just past the gate is NOT relabeled stable: the drop needs
    # >=4 chunks AND the max to be a genuine outlier (3x the limit
    # above the median — the observed stall was 6.5x the median; 12%
    # steady drift is not). The per-step ESTIMATE never used the
    # outlier anyway (min/median differencing).
    spread_trimmed, outliers_dropped = {}, {}
    for s in raw:
        if (
            spread[s] > SPREAD_LIMIT
            and len(raw[s]) >= 4
            and max(raw[s])
            > float(np.median(raw[s])) * (1 + 3 * SPREAD_LIMIT)
        ):
            kept = sorted(raw[s])[:-1]
            spread_trimmed[s] = (max(kept) - min(kept)) / min(kept)
            outliers_dropped[s] = 1
        else:
            spread_trimmed[s] = spread[s]
    dt_min = (min(raw[s_hi]) - min(raw[s_lo])) / (s_hi - s_lo)
    dt_med = float(
        (np.median(raw[s_hi]) - np.median(raw[s_lo])) / (s_hi - s_lo)
    )
    # a hiccup in every lo-count chunk can still invert min-differencing;
    # the median estimate is the fallback before declaring the data bad
    dt = dt_min if dt_min > 0 else dt_med
    assert dt > 0, "timing inversion: %r" % raw
    info = {
        "steps": [s_lo, s_hi],
        # trace+compile+first-execution per signature (each step count
        # jits its own scan): the compile-time budget column (r4 verdict
        # #9 — the reference tracked per-step op-creation overhead,
        # executor.cc:119; ours moved to compile time)
        "warm_s": {str(s): round(warm_s[s], 2) for s in warm_s},
        "raw_chunk_s": {
            str(s): [round(t, 4) for t in raw[s]] for s in raw
        },
        "per_step_s_min": round(dt_min, 6),
        "per_step_s_median": round(dt_med, 6),
        "spread": {str(s): round(spread[s], 4) for s in raw},
        "spread_trimmed": {
            str(s): round(spread_trimmed[s], 4) for s in raw
        },
        "stable": bool(max(spread_trimmed.values()) <= SPREAD_LIMIT),
        # >1 when the requested counts were scaled to reach MIN_CHUNK_S;
        # warm_s then also carries the requested (pre-scale) counts'
        # warms, whose steady probes fed the solve
        "chunk_scale": scale,
    }
    if outliers_dropped:
        info["outliers_dropped"] = {
            str(s): n for s, n in outliers_dropped.items()
        }
    return (dt, info) if return_info else dt


def _last_banked_headline():
    """Best stable driver-format headline in the committed evidence
    file (records are not timestamped and restoration can append old
    captures, so file order is not capture order) — referenced
    (clearly labeled as NOT this run's measurement) when an outage
    blocks a fresh one, so the error line points the reader at
    auditable data instead of nothing."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_r05_builder.jsonl")
    best = None
    # strictly best-effort enrichment: the caller is the watchdog's
    # must-exit path, so NO exception may escape (a hand-appended or
    # corrupted evidence line must not cancel the bench_error contract
    # line and the exit)
    try:
        with open(path, errors="replace") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                val = rec.get("value")
                if (rec.get("metric")
                        == "resnet50_train_images_per_sec_per_chip"
                        and rec.get("stable")
                        and isinstance(val, (int, float))
                        and (best is None or val > best["value"])):
                    best = {
                        "value": val,
                        "unit": rec.get("unit"),
                        "vs_baseline": rec.get("vs_baseline"),
                        "mfu": rec.get("mfu"),
                        "source": "BENCH_r05_builder.jsonl",
                        "note": "banked during an earlier on-chip "
                                "window of this round — NOT this "
                                "run's measurement",
                    }
    except Exception:
        return best
    return best


def _jit_per_count(build, consume):
    """run_at factory for the scale_steps contract: jit `build(n)` on
    demand per step count (any count — chunk scaling picks new ones)
    and pass the result to `consume` (which must block on a readback)."""
    fs = {}

    def run_at(n):
        if n not in fs:
            fs[n] = build(n)
        consume(fs[n])

    return run_at


def _per_step_seconds(exe, prog, feed, fetch, s_lo, s_hi):
    def run_at(s):
        out = exe.run_repeated(prog, feed=feed, fetch_list=[fetch], steps=s)
        v = np.ravel(out[0])[-1]
        assert np.isfinite(float(v)), "non-finite loss"

    return _diff_time(run_at, s_lo, s_hi, return_info=True)


def _xla_step_cost(prog, cost, feed):
    """XLA's own cost model for the compiled train step: flops + bytes
    accessed. The model-FLOPs MFU we report is conservative — XLA counts
    ~1.8x more flops for ResNet-50 (backward convs via dilated convs are
    tallied over the dilated windows) — so the record carries both.
    Costs one extra XLA compile (lower().cost_analysis() without compile
    returns None on this backend), so callers deadline-guard it."""
    import jax

    from paddle_tpu.fluid.core.lowering import build_step_fn
    from paddle_tpu.fluid.executor import global_scope

    scope = global_scope()
    persist_names = sorted(
        v.name for v in prog.list_vars() if v.persistable)
    persist_in = {n: scope.get(n) for n in persist_names if n in scope}
    fn, _ = build_step_fn(
        prog, feed_names=list(feed), fetch_names=[cost.name],
        persist_names=persist_names, persist_in=list(persist_in))
    ca = (
        jax.jit(fn)
        .lower(persist_in, feed, jax.random.PRNGKey(0))
        .compile()
        .cost_analysis()
    )
    if isinstance(ca, list):
        ca = ca[0]
    return float(ca.get("flops", 0.0)), float(ca.get("bytes accessed", 0.0))


def bench_image(name, model_fn, batch, steps=(12, 72), baseline_ips=None,
                xla_cost=False, remat=False):
    import jax

    import paddle_tpu.fluid as fluid

    prog, startup, cost = _build_image_workload(fluid, model_fn, batch)
    if remat:
        fluid.memory_optimize(prog)  # forward-region rematerialization
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {
        "image": jax.device_put(rng.rand(batch, 3, 224, 224).astype(np.float32)),
        "label": jax.device_put(rng.randint(0, 1000, (batch, 1)).astype(np.int32)),
    }
    dt, timing = _per_step_seconds(exe, prog, feed, cost, *steps)
    img_per_sec = batch / dt
    rec = {
        "img_per_sec": round(img_per_sec, 2),
        "ms_per_batch": round(dt * 1e3, 2),
        "batch": batch,
        "mfu": round(img_per_sec * 3 * FWD_FLOPS[name] / PEAK_FLOPS, 4),
        "timing": timing,
    }
    if (
        xla_cost
        and os.environ.get("BENCH_XLA_COST", "1") == "1"
        # the extra compile must not push a near-budget run into the
        # watchdog: skip when under 5 minutes remain
        and (_DEADLINE is None or _DEADLINE - time.monotonic() > 300)
    ):
        try:
            flops, hbm_bytes = _xla_step_cost(prog, cost, feed)
            rec["xla_flops_util"] = round(flops / dt / PEAK_FLOPS, 4)
            rec["hbm_GBps"] = round(hbm_bytes / dt / 1e9, 1)
            # roofline verdict (r3 ask): where does this step sit
            # relative to the v5e machine balance, and how much of the
            # model-implied ceiling is achieved? The ridge point is
            # PEAK_FLOPS/HBM_BW ~ 240 flops/byte; a step below it is
            # bandwidth-bound and its ceiling is bytes/BW.
            if flops > 0 and hbm_bytes > 0:
                ai = flops / hbm_bytes
                t_roof = max(flops / PEAK_FLOPS, hbm_bytes / HBM_BW)
                rec["roofline"] = {
                    "ai_flops_per_byte": round(ai, 1),
                    "ridge_flops_per_byte": round(PEAK_FLOPS / HBM_BW, 1),
                    "bound": "hbm" if ai < PEAK_FLOPS / HBM_BW else "mxu",
                    "roofline_ms": round(t_roof * 1e3, 3),
                    "roofline_img_per_sec": round(batch / t_roof, 1),
                    "achieved_frac_of_roofline": round(t_roof / dt, 4),
                }
        except Exception as e:  # cost model is informational only
            rec["xla_cost_error"] = "%s: %s" % (type(e).__name__, e)
    exe.close()
    if baseline_ips:
        rec["vs_baseline"] = round(img_per_sec / baseline_ips, 4)
    return rec


# ---------------------------------------------------------------------------
# recordio-fed ResNet-50 (headline)
# ---------------------------------------------------------------------------


def _ensure_recordio(path, n_samples, rng):
    """A record per sample: [label u16][raw uint8 3*224*224] — the data
    plane the reference's Go master dispatches (RecordIO chunks)."""
    from paddle_tpu import native

    if os.path.exists(path):
        return
    w = native.RecordWriter(path + ".tmp")
    img_bytes = 3 * 224 * 224
    for _ in range(n_samples):
        label = int(rng.randint(0, 1000))
        img = rng.randint(0, 256, img_bytes, dtype=np.uint8)
        w.write(struct.pack("<H", label) + img.tobytes())
    w.close()
    os.replace(path + ".tmp", path)


def _build_image_infer_program(fluid, model_fn, class_dim=1000):
    """The serving-side program: f32 vars (declaring bf16 vars would
    create bf16 parameters — a different model than the f32 one
    save_inference_model exports; the amp lowering only engages on the
    autodiff path, so this forward runs f32 — conservative, and
    precision-matched to the f32 MKL-DNN baselines), clone(for_test)
    so batch-norm uses moving statistics. Shared with bench_offline so
    the AOT fingerprint always matches the program benched on-chip."""
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        image = fluid.layers.data(
            name="image", shape=[3, 224, 224], dtype="float32")
        pred = model_fn(image, class_dim)
    return main_prog.clone(for_test=True), startup, pred


def bench_image_infer(name, model_fn, baseline_ips, batch=None,
                      steps=None):
    """Image-model inference throughput (img/s): the serving-side rows,
    run through clone(for_test=True) so batch-norm uses the moving
    statistics (the same program save_inference_model would export).
    Reference baselines: the MKL-DNN bs=16 inference table on a 2S Xeon
    Gold 6148 (/root/reference/benchmark/IntelOptimizedPaddle.md:77-107)
    — the only published inference numbers in the reference tree."""
    import jax

    import paddle_tpu.fluid as fluid

    # bs=16 matches the reference baselines; overridable for CPU smokes
    batch = batch or int(os.environ.get("BENCH_INFER_BATCH", "16"))
    steps = steps or tuple(
        int(s)
        for s in os.environ.get("BENCH_INFER_STEPS", "24,144").split(","))
    test_prog, startup, pred = _build_image_infer_program(fluid, model_fn)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {
        "image": jax.device_put(
            rng.rand(batch, 3, 224, 224).astype(np.float32)),
    }
    dt, timing = _per_step_seconds(exe, test_prog, feed, pred, *steps)
    exe.close()
    img_per_sec = batch / dt
    return {
        "img_per_sec": round(img_per_sec, 2),
        "ms_per_batch": round(dt * 1e3, 2),
        "batch": batch,
        "mfu": round(img_per_sec * FWD_FLOPS[name] / PEAK_FLOPS, 4),
        "vs_baseline": round(img_per_sec / baseline_ips, 4),
        "timing": timing,
    }


def bench_resnet50_recordio(batch, chunk_steps, n_chunks):
    """Timed loop fed from the native recordio prefetch queue: each chunk
    of `chunk_steps` batches is decoded on the host while the previous
    chunk trains on device (async dispatch overlaps transfer+compute)."""
    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu import native
    from paddle_tpu.models.resnet import resnet_imagenet

    prog, startup, cost = _build_image_workload(
        fluid,
        lambda img, cd: resnet_imagenet(img, class_dim=cd, depth=50),
        batch,
        uint8_input=True,
    )
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)

    path = os.environ.get("BENCH_RECORDIO", "/tmp/bench_imagenet.rio")
    samples_per_chunk = batch * chunk_steps
    rng = np.random.RandomState(7)
    _ensure_recordio(path, samples_per_chunk * 4, rng)  # cycled reader

    img_bytes = 3 * 224 * 224

    def chunks():
        """Endless chunk stream off the native prefetch queue. Fresh
        buffers per chunk: the consumer may still be uploading the
        previous one (AsyncDeviceFeeder double-buffering below)."""
        imgs = np.empty((chunk_steps, batch, 3, 224, 224), np.uint8)
        lbls = np.empty((chunk_steps, batch, 1), np.int64)
        i = 0
        while True:
            reader = native.PrefetchReader([path], capacity=256)
            for rec in reader:
                s, b = divmod(i, batch)
                lbls[s, b, 0] = struct.unpack("<H", rec[:2])[0]
                imgs[s, b] = np.frombuffer(
                    rec[2 : 2 + img_bytes], np.uint8
                ).reshape(3, 224, 224)
                i += 1
                if i == samples_per_chunk:
                    yield imgs, lbls
                    imgs = np.empty_like(imgs)
                    lbls = np.empty_like(lbls)
                    i = 0

    stream = chunks()
    # compile + warm with the first chunk
    imgs, lbls = next(stream)
    out = exe.run_repeated(
        prog, feed={"image": imgs, "label": lbls}, fetch_list=[cost],
        steps=chunk_steps, scan_feeds=True,
    )
    assert np.isfinite(np.ravel(out[0])[-1])

    # sustained host->device bandwidth of this harness (the axon tunnel):
    # the input pipeline is bounded by it, the chip is not
    jax.device_put(np.zeros(1024, np.uint8)).block_until_ready()  # warm link
    t0 = time.time()
    probe = jax.device_put(imgs)
    probe.block_until_ready()
    h2d_mbps = imgs.nbytes / 1e6 / (time.time() - t0)
    del probe

    # double-buffered: a background thread decodes + uploads chunk k+1
    # while the device trains on chunk k (fluid.AsyncDeviceFeeder —
    # reference DataProvider.h:249 DoubleBuffer)
    from paddle_tpu.fluid.data_feeder import AsyncDeviceFeeder

    def feed_iter():
        for _ in range(n_chunks):
            imgs_c, lbls_c = next(stream)
            yield {"image": imgs_c, "label": lbls_c}

    t0 = time.time()
    outs = None
    feeder = AsyncDeviceFeeder(feed_iter(), capacity=2)
    try:
        for feed in feeder:
            outs = exe.run_repeated(
                prog, feed=feed, fetch_list=[cost],
                steps=chunk_steps, scan_feeds=True, return_numpy=False,
            )
    finally:
        # a raise mid-loop must not leave the producer pinning device
        # buffers for the rest of the bench process
        feeder.close()
    final_loss = float(np.ravel(np.asarray(outs[0]))[-1])  # full sync
    dt = time.time() - t0
    exe.close()
    assert np.isfinite(final_loss)

    img_per_sec = batch * chunk_steps * n_chunks / dt
    return {
        "img_per_sec": round(img_per_sec, 2),
        "ms_per_batch": round(dt / (chunk_steps * n_chunks) * 1e3, 2),
        "batch": batch,
        "mfu": round(img_per_sec * 3 * FWD_FLOPS["resnet50"] / PEAK_FLOPS, 4),
        "input": "recordio-uint8",
        "h2d_MBps": round(h2d_mbps, 1),
        "note": "end-to-end including host->device transfer; bounded by "
                "the harness tunnel bandwidth above, not the chip",
    }


# ---------------------------------------------------------------------------
# LSTM (benchmark/paddle/rnn/rnn.py: 2x LSTM h=512, bs=64, seq 100)
# ---------------------------------------------------------------------------


def bench_profiler_reconciliation(batch=32):
    """r4 verdict #4: on-chip, reconcile the compiled profiler's
    traffic-modeled per-op attribution against MEASURED jax.profiler
    instruction times (reference measured per-op with CUDA events,
    platform/profiler.cc:198). Records both columns for the top ops
    and the top-5 disagreement — <=0.20 is the verdict's pass bar."""
    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import profiler
    from paddle_tpu.models.resnet import resnet_imagenet

    prog, startup, cost = _build_image_workload(
        fluid, lambda i, c: resnet_imagenet(i, class_dim=c, depth=50),
        batch,
    )
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {
        "image": jax.device_put(
            rng.rand(batch, 3, 224, 224).astype(np.float32)),
        "label": jax.device_put(
            rng.randint(0, 1000, (batch, 1)).astype(np.int32)),
    }
    table, meta = profiler.trace_profile(exe, prog, feed, [cost], runs=3)
    exe.close()
    return {
        "backend": meta["backend"],
        "measured_total_ms": meta["measured_total_ms"],
        "unmatched_ms": meta["unmatched_ms"],
        "top5_max_disagreement": meta["top5_max_disagreement"],
        "reconciled": meta["top5_max_disagreement"] <= 0.20,
        "top_rows": table[:8],
    }


def bench_lstm(batch=64, hidden=512, emb=128, seqlen=100, vocab=30000,
               layers_n=2, steps=(8, 48)):
    import jax

    import paddle_tpu.fluid as fluid

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        words = fluid.layers.data(name="words", shape=[1], dtype="int64",
                                  lod_level=1)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        x = fluid.layers.embedding(input=words, size=[vocab, emb])
        for _ in range(layers_n):
            proj = fluid.layers.fc(input=x, size=hidden * 4)
            x, _ = fluid.layers.dynamic_lstm(input=proj, size=hidden * 4)
        last = fluid.layers.sequence_last_step(input=x)
        predict = fluid.layers.fc(input=last, size=2, act="softmax")
        cost = fluid.layers.mean(
            x=fluid.layers.cross_entropy(input=predict, label=label)
        )
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(cost)
    main_prog.amp = AMP

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, vocab, (batch * seqlen, 1)).astype(np.int64)
    offsets = np.arange(0, batch * seqlen + 1, seqlen, dtype=np.int32)
    feed = {
        "words": (tokens, [offsets]),
        "label": rng.randint(0, 2, (batch, 1)).astype(np.int64),
    }
    dt, timing = _per_step_seconds(exe, main_prog, feed, cost, *steps)
    exe.close()

    # fwd FLOPs/batch: per LSTM layer, input proj (E or H -> 4H) + the
    # recurrent GEMM (H -> 4H) over T*B tokens, 2 FLOPs/MAC
    toks = batch * seqlen
    f = 0.0
    in_dim = emb
    for _ in range(layers_n):
        f += 2.0 * toks * (in_dim * 4 * hidden + hidden * 4 * hidden)
        in_dim = hidden
    ms = dt * 1e3
    return {
        "ms_per_batch": round(ms, 2),
        "batch": batch,
        "hidden": hidden,
        "seq_len": seqlen,
        "mfu": round((f * 3 / dt) / PEAK_FLOPS, 4),
        "vs_baseline": round(184.0 / ms, 4),  # >1 = faster than reference
        "timing": timing,
    }


def bench_sparse_embedding(vocab=1_000_000, dim=64, batch=4096, fields=8,
                           steps=(8, 40)):
    """CTR-style sparse-embedding training step (SelectedRows path, r4):
    `fields` id lookups per example into a [1M, dim] table, sum-pooled
    into a logistic head, SGD. The sparse step's gradient work scales
    with touched rows (batch*fields), not vocab; the dense run of the
    SAME model is timed for the on-chip comparison. Reference workload
    family: sparse remote updaters + SelectedRows CTR path
    (RemoteParameterUpdater.h:265, operators/sgd_op.cc sparse branch)."""
    import jax

    import paddle_tpu.fluid as fluid

    def build(is_sparse):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data(name="ids", shape=[fields],
                                    dtype="int64")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            emb = fluid.layers.embedding(
                input=ids, size=[vocab, dim], is_sparse=is_sparse,
            )
            pooled = fluid.layers.reduce_sum(emb, dim=1)
            pred = fluid.layers.fc(input=pooled, size=1, act=None)
            cost = fluid.layers.mean(
                x=fluid.layers.sigmoid_cross_entropy_with_logits(
                    x=pred, label=y
                )
            )
            fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        return main, startup, cost

    rng = np.random.RandomState(0)
    feed = {
        "ids": rng.randint(0, vocab, (batch, fields)).astype(np.int64),
        "y": (rng.rand(batch, 1) > 0.5).astype(np.float32),
    }

    out = {}
    for is_sparse in (True, False):
        main, startup, cost = build(is_sparse)
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        dt, timing = _per_step_seconds(exe, main, feed, cost, *steps)
        exe.close()
        key = "sparse" if is_sparse else "dense"
        out["ms_per_step_" + key] = round(dt * 1e3, 3)
        if is_sparse:
            out["timing"] = timing
            out["examples_per_sec"] = round(batch / dt, 1)
            out["touched_rows_per_sec"] = round(batch * fields / dt, 1)
    out.update(vocab=vocab, dim=dim, batch=batch, fields=fields)
    out["sparse_speedup"] = round(
        out["ms_per_step_dense"] / out["ms_per_step_sparse"], 3
    )
    return out


def bench_transformer_lm(B=8, T=1024, dim=512, heads=8, layers_n=8,
                         vocab=32000, steps=(4, 24)):
    """Decoder-only transformer LM training throughput (tokens/s + MFU):
    the long-context flagship (models/transformer.py) with the pallas
    flash-attention kernel, bf16 params, steps inside one lax.scan.
    Beyond-reference capability — no 2018 baseline exists, reported for
    the record."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from paddle_tpu.models import transformer as tlm

    impl = "flash" if jax.default_backend() != "cpu" else "xla"
    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=T,
                                dtype=jnp.bfloat16)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    step = tlm.make_train_step(cfg, lr=1e-3, attn_impl=impl)

    def multi(p, toks, n):
        def body(c, _):
            c, l = step(c, toks)
            return c, l

        return lax.scan(body, p, None, length=n)

    rng = np.random.RandomState(0)
    toks = jax.device_put(
        rng.randint(0, vocab, (B, T + 1)).astype(np.int32))

    def _check(f):
        _, losses = f(params, toks)
        assert np.isfinite(float(np.ravel(np.asarray(losses))[-1]))

    run_at = _jit_per_count(
        lambda n: jax.jit(lambda p, t: multi(p, t, n)), _check)

    dt, timing = _diff_time(run_at, *steps, return_info=True)

    # FLOPs: matmul params (tied head counted once at the logits matmul)
    p_mat = vocab * dim + layers_n * 12 * dim * dim
    fwd = 2.0 * B * T * p_mat + layers_n * B * 2.0 * T * T * dim  # causal
    tok_per_sec = B * T / dt
    return {
        "tokens_per_sec": round(tok_per_sec, 1),
        "ms_per_step": round(dt * 1e3, 2),
        "batch": B,
        "seq_len": T,
        "attn_impl": impl,
        "mfu": round(3.0 * fwd / dt / PEAK_FLOPS, 4),
        "timing": timing,
    }


def bench_lm_decode(B=8, T0=512, new_tokens=(64, 192), dim=512, heads=8,
                    layers_n=8, vocab=32000):
    """Cached autoregressive decode throughput (tokens/s/chip): prefill
    once, then KV-cache decode steps inside one lax.scan
    (models/transformer.py generate). The serving-side companion to the
    training record; beyond-reference capability, no 2018 baseline."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer as tlm

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=T0 + max(new_tokens),
                                dtype=jnp.bfloat16)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    prompt = jax.device_put(
        rng.randint(0, vocab, (B, T0)).astype(np.int32))

    gens = {
        n: jax.jit(lambda p, pr, n=n: tlm.generate(p, pr, cfg, n))
        for n in new_tokens
    }

    def run_at(n):
        out = gens[n](params, prompt)
        assert int(np.asarray(out[0, -1])) >= 0

    # seconds per generated token; the step count IS the decode length
    # (bounded by cfg.max_len), so chunk scaling must not touch it
    dt, timing = _diff_time(
        run_at, *new_tokens, return_info=True, scale_steps=False)
    return {
        "decode_tokens_per_sec": round(B / dt, 1),
        "ms_per_token": round(dt * 1e3 / B, 3),
        "batch": B,
        "prompt_len": T0,
        "timing": timing,
    }


def bench_serving_decode(max_slots=None, n_requests=None):
    """Continuous-batching serving engine (paddle_tpu/serving) under a
    synthetic Poisson arrival trace: aggregate decode tokens/s + mean
    slot occupancy + compile counts. The trace is FIXED-SEED and
    measured in engine steps (arrivals are injected by step index, not
    wall clock), so the workload — prompts, budgets, admission order,
    greedy outputs — is fully deterministic and tunnel-capturable: the
    occupancy/compile-count columns are meaningful offline (CPU), the
    tokens/s column only on-chip. Serving counterpart of lm_decode,
    which measures ONE request's decode; this measures many concurrent
    requests sharing one compiled step (ISSUE 2)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer as tlm
    from paddle_tpu.serving import ServingEngine

    cpu = jax.default_backend() == "cpu"
    if cpu:  # smoke shape: exercises the full engine, seconds not minutes
        dim, heads, layers_n, vocab, max_len = 128, 4, 2, 512, 128
        max_slots = max_slots or 4
        n_requests = n_requests or 12
        p_lo, p_hi, n_lo, n_hi, rate = 4, 48, 4, 16, 2.0
        dtype = jnp.float32
    else:
        dim, heads, layers_n, vocab, max_len = 512, 8, 8, 32000, 1024
        max_slots = max_slots or 16
        n_requests = n_requests or 64
        p_lo, p_hi, n_lo, n_hi, rate = 64, 512, 32, 128, 1.0
        dtype = jnp.bfloat16

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=max_len,
                                dtype=dtype)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    arrive_at = np.floor(
        np.cumsum(rng.exponential(1.0 / rate, n_requests))
    ).astype(int)
    reqs = [
        (
            rng.randint(0, vocab,
                        rng.randint(p_lo, p_hi + 1)).astype(np.int32),
            int(rng.randint(n_lo, n_hi + 1)),
        )
        for _ in range(n_requests)
    ]

    eng = ServingEngine(params, cfg, max_slots=max_slots)
    t0 = time.time()
    i = step = 0
    while i < n_requests or eng.live_slots or eng.queue_depth \
            or eng.prefilling_slots:
        while i < n_requests and arrive_at[i] <= step:
            p, n = reqs[i]
            eng.submit(p, n)
            i += 1
        if not eng.step() and i < n_requests:
            step = max(step + 1, int(arrive_at[i]))  # idle gap: jump
            continue
        step += 1
    wall = time.time() - t0
    rep = eng.metrics.report()
    compile_total = int(sum(eng.metrics.trace_counts.values()))
    return {
        # wall includes the O(#buckets)+1 compiles; tokens/s is the
        # steady aggregate the tunnel window should capture on-chip
        "tokens_per_sec": round(rep["tokens_out"] / wall, 1),
        "tokens_out": rep["tokens_out"],
        "decode_steps": rep["decode_steps"],
        "mean_occupancy": rep["mean_occupancy"],
        "mean_queue_wait_s": rep["mean_queue_wait_s"],
        "mean_ttft_s": rep["mean_ttft_s"],
        "prefill_traces": rep["prefill_traces"],
        "decode_traces": rep["decode_traces"],
        "compile_total": compile_total,
        "max_slots": max_slots,
        "n_requests": n_requests,
        "arrival": "poisson(rate=%g/step, seed=0)" % rate,
        "model": {"dim": dim, "heads": heads, "layers": layers_n,
                  "vocab": vocab, "max_len": max_len},
    }


def bench_serving_megabatch(max_slots=None, n_requests=None,
                            windows=(1, 4, 8)):
    """Megabatch decode window (ISSUE 19): ONE fixed-seed Poisson trace
    replayed across (decode_window=K, async_dispatch) variants — K in
    {1, 4, 8} each sync and async. Headline column is the
    host-overhead fraction (wall minus device-step time, over wall):
    folding K decode iterations into the one compiled step amortizes
    the per-token host round-trip K ways, and async dispatch hides
    the remaining scheduler work under device compute. Also reported:
    steps/token (the amortization itself) and band-upload counts (the
    steady window loop must re-upload nothing, like K=1). Two hard
    raises keep the row honest: (a) any output divergence across
    variants (greedy AND sampled requests ride the same trace — the
    window must be token-identical to the sequential path), (b)
    host-overhead(K=8, async) >= host-overhead(K=1, sync) — the whole
    point of the window, measured, on every backend. Compiles are
    paid by an unmeasured warm-up request per variant, so the
    overhead columns compare steady-state loops, not trace time;
    decode must trace exactly ONCE per variant regardless of K."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer as tlm
    from paddle_tpu.serving import ServingEngine

    cpu = jax.default_backend() == "cpu"
    if cpu:
        # smoke shape: deliberately TINY model so the per-step host
        # scheduler cost is a visible fraction of wall (a fat model
        # would bury the contrast under CPU matmul time; on-chip the
        # real shape below has the same property for free)
        dim, heads, layers_n, vocab, max_len = 64, 4, 2, 128, 128
        max_slots = max_slots or 4
        n_requests = n_requests or 12
        p_lo, p_hi, budget, rate = 4, 16, 32, 4.0
        dtype = jnp.float32
    else:
        dim, heads, layers_n, vocab, max_len = 512, 8, 8, 32000, 1024
        max_slots = max_slots or 16
        n_requests = n_requests or 64
        p_lo, p_hi, budget, rate = 64, 512, 128, 1.0
        dtype = jnp.bfloat16

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=max_len,
                                dtype=dtype)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    arrive_at = np.floor(
        np.cumsum(rng.exponential(1.0 / rate, n_requests))
    ).astype(int)
    # mixed trace: even requests greedy, odd requests sampled (the
    # fold_in(count) schedule must make sampling window-invariant
    # too). Budgets are FIXED at a multiple of every K so no variant
    # pays window-quantization waste (a request retiring mid-window
    # parks the remainder — real, but a different effect than the
    # host-overhead amortization this row isolates; the identity
    # tests cover mid-window retirement).
    reqs = [
        (
            rng.randint(0, vocab,
                        rng.randint(p_lo, p_hi + 1)).astype(np.int32),
            budget,
            0.0 if j % 2 == 0 else 0.8,
        )
        for j in range(n_requests)
    ]

    def drive(K, async_on):
        eng = ServingEngine(params, cfg, max_slots=max_slots,
                            decode_window=K, async_dispatch=async_on)
        # warm-up request: pays the decode trace + one prefill bucket
        # outside the measured trace (counters are deltas below)
        eng.submit(np.arange(1, 9, dtype=np.int32), 4)
        eng.run()
        busy0 = eng.metrics.device_busy_s
        up0 = eng.metrics.band_uploads
        st0 = eng.metrics.decode_steps
        tk0 = eng.metrics.tokens_out
        handles = []
        t0 = time.time()
        i = step = 0
        while i < n_requests or eng.live_slots or eng.queue_depth \
                or eng.prefilling_slots:
            while i < n_requests and arrive_at[i] <= step:
                p, n, temp = reqs[i]
                handles.append(
                    eng.submit(p, n, temperature=temp, seed=1000 + i))
                i += 1
            if not eng.step() and i < n_requests:
                step = max(step + 1, int(arrive_at[i]))  # idle gap: jump
                continue
            step += 1
        wall = time.time() - t0
        if eng.metrics.decode_trace_count() != 1:
            raise RuntimeError(
                "serving_megabatch: decode traced %d times at K=%d "
                "async=%s (must be exactly once per engine lifetime)"
                % (eng.metrics.decode_trace_count(), K, async_on))
        busy = eng.metrics.device_busy_s - busy0
        toks = eng.metrics.tokens_out - tk0
        steps = eng.metrics.decode_steps - st0
        outs = tuple(tuple(h.tokens) for h in handles)
        return outs, {
            "host_overhead_frac": round(
                max(0.0, wall - busy) / wall, 4) if wall else None,
            "steps_per_token": round(steps / max(1, toks), 4),
            "band_uploads": eng.metrics.band_uploads - up0,
            "decode_steps": steps,
            "tokens_out": toks,
            "wall_s": round(wall, 4),
        }

    variants = {}
    base = None
    for K in windows:
        for async_on in (False, True):
            outs, row = drive(K, async_on)
            if base is None:
                base = outs
            elif outs != base:
                raise RuntimeError(
                    "serving_megabatch: output divergence at K=%d "
                    "async=%s vs the K=%d sync baseline — the decode "
                    "window is not token-identical"
                    % (K, async_on, windows[0]))
            variants["K%d_%s" % (K, "async" if async_on else "sync")] \
                = row
    lo = variants["K%d_async" % windows[-1]]["host_overhead_frac"]
    hi = variants["K%d_sync" % windows[0]]["host_overhead_frac"]
    if lo >= hi:
        raise RuntimeError(
            "serving_megabatch: host-overhead(K=%d, async)=%.4f is not "
            "below host-overhead(K=%d, sync)=%.4f — the window buys "
            "nothing" % (windows[-1], lo, windows[0], hi))
    return {
        "variants": variants,
        "host_overhead_K%d_async" % windows[-1]: lo,
        "host_overhead_K%d_sync" % windows[0]: hi,
        "outputs_identical": True,
        "n_requests": n_requests,
        "max_slots": max_slots,
        "arrival": "poisson(rate=%g/step, seed=0)" % rate,
        "model": {"dim": dim, "heads": heads, "layers": layers_n,
                  "vocab": vocab, "max_len": max_len},
    }


def bench_serving_shared_prefix(n_requests=None, families=None,
                                header_len=None, family_len=None,
                                max_slots=None, dim=None, heads=None,
                                layers_n=None, vocab=None, max_len=None,
                                chunk_tokens=None, block_tokens=None,
                                cache_tokens=None):
    """Prefix-cache acceptance trace (ISSUE 4): fixed-seed Poisson
    arrivals over K prompt families sharing a common header (system-
    prompt/few-shot shape — the workload RadixAttention exists for).
    The SAME deterministic trace runs twice through the serving engine —
    prefix cache OFF vs ON — and the row reports the offline-meaningful
    columns: prefill-tokens-computed (the work the cache deletes),
    prefix-hit rate, evictions, and mean TTFT both ways. Greedy outputs
    must be token-identical between the two runs (asserted in-bench:
    reuse must never change what a request decodes to); tokens/s is
    only meaningful on-chip, like the serving_decode row."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer as tlm
    from paddle_tpu.serving import ServingEngine

    cpu = jax.default_backend() == "cpu"
    if cpu:  # smoke shape: exercises both engine paths, seconds not minutes
        dim, heads, layers_n = dim or 128, heads or 4, layers_n or 2
        vocab, max_len = vocab or 512, max_len or 256
        n_requests, families = n_requests or 12, families or 3
        header_len, family_len = header_len or 32, family_len or 16
        max_slots = max_slots or 4
        t_lo, t_hi, n_lo, n_hi, rate = 4, 12, 4, 10, 2.0
        dtype = jnp.float32
    else:
        dim, heads, layers_n = dim or 512, heads or 8, layers_n or 8
        vocab, max_len = vocab or 32000, max_len or 1024
        n_requests, families = n_requests or 64, families or 4
        header_len, family_len = header_len or 256, family_len or 64
        max_slots = max_slots or 16
        t_lo, t_hi, n_lo, n_hi, rate = 16, 64, 32, 128, 1.0
        dtype = jnp.bfloat16
    chunk_tokens = chunk_tokens or max(16, header_len // 2)
    block_tokens = block_tokens or 16
    cache_tokens = cache_tokens or 8 * (header_len + family_len)

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=max_len,
                                dtype=dtype)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    header = rng.randint(0, vocab, header_len).astype(np.int32)
    fam = [rng.randint(0, vocab, family_len).astype(np.int32)
           for _ in range(families)]
    arrive_at = np.floor(
        np.cumsum(rng.exponential(1.0 / rate, n_requests))
    ).astype(int)
    reqs = []
    for _ in range(n_requests):
        f = int(rng.randint(families))
        tail = rng.randint(0, vocab,
                           int(rng.randint(t_lo, t_hi + 1))).astype(np.int32)
        prompt = np.concatenate([header, fam[f], tail])
        reqs.append((prompt, int(rng.randint(n_lo, n_hi + 1)),
                     header_len + family_len))

    def run_once(pool_tokens):
        eng = ServingEngine(
            params, cfg, max_slots=max_slots,
            prefill_chunk_tokens=chunk_tokens,
            prefix_cache_tokens=pool_tokens,
            prefix_block_tokens=block_tokens)
        hs = []
        i = step = 0
        while i < n_requests or eng.live_slots or eng.queue_depth \
                or eng.prefilling_slots:
            while i < n_requests and arrive_at[i] <= step:
                p, n, pub = reqs[i]
                # publish-boundary tag: only the shared header+family
                # prefix enters the pool, never the unique tails
                hs.append(eng.submit(p, n, publish_len=pub))
                i += 1
            if not eng.step() and i < n_requests:
                step = max(step + 1, int(arrive_at[i]))  # idle gap: jump
                continue
            step += 1
        return eng, [list(h.tokens) for h in hs]

    eng_off, out_off = run_once(None)
    eng_on, out_on = run_once(cache_tokens)
    # reuse must never change what any request decodes to — a hard
    # raise, not a bare assert: the acceptance gate must survive -O
    if out_on != out_off:
        raise RuntimeError("prefix cache changed greedy outputs")
    rep_off, rep_on = eng_off.metrics.report(), eng_on.metrics.report()
    pc = eng_on.prefix_cache.stats()
    return {
        "prefill_tokens_computed_off": rep_off["prefill_tokens_computed"],
        "prefill_tokens_computed_on": rep_on["prefill_tokens_computed"],
        "prefill_tokens_saved_frac": round(
            1.0 - rep_on["prefill_tokens_computed"]
            / max(rep_off["prefill_tokens_computed"], 1), 4),
        "prefix_hit_rate": pc["hit_rate"],
        "prefix_tokens_saved": pc["tokens_saved"],
        "prefix_evictions": pc["evictions"],
        "mean_ttft_s_off": rep_off["mean_ttft_s"],
        "mean_ttft_s_on": rep_on["mean_ttft_s"],
        "decode_steps_off": rep_off["decode_steps"],
        "decode_steps_on": rep_on["decode_steps"],
        "prefill_traces_on": rep_on["prefill_traces"],
        "decode_traces_on": rep_on["decode_traces"],
        "tokens_out": rep_on["tokens_out"],
        "n_requests": n_requests,
        "families": families,
        "arrival": "poisson(rate=%g/step, seed=0)" % rate,
        "knobs": {"prefill_chunk_tokens": chunk_tokens,
                  "prefix_block_tokens": block_tokens,
                  "prefix_cache_tokens": cache_tokens,
                  "publish_len": header_len + family_len,
                  "max_slots": max_slots},
        "model": {"dim": dim, "heads": heads, "layers": layers_n,
                  "vocab": vocab, "max_len": max_len},
    }


def bench_serving_paged(n_requests=None, max_slots=None, dim=None,
                        heads=None, layers_n=None, vocab=None,
                        max_len=None, block_tokens=None,
                        budget_tokens=None, spec_draft_len=None):
    """Paged-KV acceptance trace (ISSUE 7): the SAME fixed-seed Poisson
    trace of short requests runs three times at ONE fixed KV HBM budget
    (`budget_tokens` cached tokens per layer):

      slab  — the pre-paging concurrency wall: a [S, max_len] slab at
              this budget holds floor(budget/max_len) slots, each
              paying max_len whether the request needs it or not
              (emulated exactly: max_slots = that floor, pool =
              worst-case blocks per slot);
      paged — the block pool shares budget/block_tokens fixed-size
              blocks across many slots; admission reserves each
              request's OWN worst case (ceil((T0+max_new)/Bt)), so
              resident slots scale with actual tokens;
      spec  — paged + self-drafting speculative decoding
              (`spec_draft_len`-token verify windows, one compiled
              verify step).

    The row reports peak resident slots both ways (the acceptance
    inequality: paged > slab at the same budget — pinned by
    tests/test_bench_protocol.py), speculative accept-rate, and
    tokens/s for each mode. Greedy outputs must be token-identical
    across all three runs (hard raise in-bench: paging and speculation
    must never change WHAT a request decodes to, only when/where).
    Peak-resident, accept-rate, and compile counts are deterministic
    offline; the tokens/s contrast is only meaningful on-chip."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer as tlm
    from paddle_tpu.serving import ServingEngine

    cpu = jax.default_backend() == "cpu"
    if cpu:  # smoke shape: exercises all three engine modes in seconds
        dim, heads, layers_n = dim or 64, heads or 4, layers_n or 2
        vocab, max_len = vocab or 256, max_len or 96
        n_requests = n_requests or 10
        max_slots = max_slots or 8
        block_tokens = block_tokens or 8
        budget_tokens = budget_tokens or 2 * (max_len or 96)
        spec_draft_len = spec_draft_len or 4
        t_lo, t_hi, n_lo, n_hi, rate = 4, 12, 6, 14, 3.0
        dtype = jnp.float32
    else:
        dim, heads, layers_n = dim or 512, heads or 8, layers_n or 8
        vocab, max_len = vocab or 32000, max_len or 1024
        n_requests = n_requests or 64
        max_slots = max_slots or 32
        block_tokens = block_tokens or 16
        budget_tokens = budget_tokens or 8 * (max_len or 1024)
        spec_draft_len = spec_draft_len or 4
        t_lo, t_hi, n_lo, n_hi, rate = 32, 128, 32, 96, 2.0
        dtype = jnp.bfloat16

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=max_len,
                                dtype=dtype)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    arrive_at = np.floor(
        np.cumsum(rng.exponential(1.0 / rate, n_requests))
    ).astype(int)
    reqs = [
        (
            rng.randint(0, vocab,
                        int(rng.randint(t_lo, t_hi + 1))).astype(np.int32),
            int(rng.randint(n_lo, n_hi + 1)),
        )
        for _ in range(n_requests)
    ]
    # the slab wall at this budget: floor(budget/max_len) slots, each
    # paying max_len (the [MAX_SLOTS, max_len] allocation PR 7 removed)
    slab_slots = max(1, int(budget_tokens) // int(max_len))
    pool_blocks = int(budget_tokens) // int(block_tokens)

    def run_once(slots, blocks, spec):
        eng = ServingEngine(
            params, cfg, max_slots=slots, kv_block_tokens=block_tokens,
            kv_pool_blocks=blocks, spec_draft_len=spec)
        hs, peak, peak_blocks = [], 0, 0
        t0 = time.time()
        i = step = 0
        while i < n_requests or eng.live_slots or eng.queue_depth \
                or eng.prefilling_slots:
            while i < n_requests and arrive_at[i] <= step:
                p, n = reqs[i]
                hs.append(eng.submit(p, n))
                i += 1
            if not eng.step() and i < n_requests:
                step = max(step + 1, int(arrive_at[i]))  # idle gap: jump
                continue
            peak = max(peak, eng.live_slots + eng.prefilling_slots)
            peak_blocks = max(peak_blocks, eng.kv_blocks_in_use)
            step += 1
        wall = time.time() - t0
        return eng, wall, peak, peak_blocks, [list(h.tokens) for h in hs]

    eng_slab, wall_slab, peak_slab, _, out_slab = run_once(
        slab_slots, None, None)
    eng_paged, wall_paged, peak_paged, pk_blocks, out_paged = run_once(
        max_slots, pool_blocks, None)
    eng_spec, wall_spec, peak_spec, _, out_spec = run_once(
        max_slots, pool_blocks, spec_draft_len)
    # paging/speculation must never change what any request decodes to
    # — a hard raise, not a bare assert: the gate must survive -O
    if out_paged != out_slab or out_spec != out_slab:
        raise RuntimeError("paged/speculative run changed greedy outputs")
    rep_paged = eng_paged.metrics.report()
    rep_spec = eng_spec.metrics.report()
    toks = rep_paged["tokens_out"]
    return {
        # the acceptance inequality: resident slots at ONE KV budget
        "slots_resident_slab": peak_slab,
        "slots_resident_paged": peak_paged,
        "slots_resident_spec": peak_spec,
        "kv_budget_tokens": int(budget_tokens),
        "kv_pool_blocks": pool_blocks,
        "kv_block_tokens": int(block_tokens),
        "peak_kv_blocks_in_use": pk_blocks,
        "kv_frag_tokens_last": rep_paged["kv_frag_tokens"],
        "kv_tail_blocks_freed": rep_paged["kv_tail_blocks_freed"],
        "cow_blocks": rep_paged["cow_blocks"],
        "spec_draft_len": int(spec_draft_len),
        "spec_accept_rate": rep_spec["spec_accept_rate"],
        "spec_windows": rep_spec["spec_windows"],
        "tokens_out": toks,
        "tokens_per_sec_slab": round(toks / wall_slab, 1),
        "tokens_per_sec_paged": round(toks / wall_paged, 1),
        "tokens_per_sec_spec": round(toks / wall_spec, 1),
        "decode_steps_paged": rep_paged["decode_steps"],
        "decode_steps_spec": rep_spec["decode_steps"],
        "decode_traces_paged": rep_paged["decode_traces"],
        "spec_verify_traces":
            eng_spec.metrics.trace_counts.get("spec_verify", 0),
        "n_requests": n_requests,
        "arrival": "poisson(rate=%g/step, seed=0)" % rate,
        "model": {"dim": dim, "heads": heads, "layers": layers_n,
                  "vocab": vocab, "max_len": max_len},
    }


def bench_serving_paged_kernel(n_requests=None, max_slots=None, dim=None,
                               heads=None, layers_n=None, vocab=None,
                               max_len=None, block_tokens=None,
                               chunk_tokens=None, cache_tokens=None,
                               spec_draft_len=None):
    """Fused paged-attention kernel acceptance trace (ISSUE 13): the
    SAME fixed-seed Poisson shared-header trace runs twice — once with
    `paged_kernel="gather"` (the XLA `_paged_view` form: a transient
    gathered view [S, MAXB*Bt, H, Dh] per layer per step) and once
    with `paged_kernel="fused"` (parallel/paged_attention.py: Pallas
    kernels that walk the block table inside the kernel) — through the
    full reuse surface: prefix aliasing + publish boundaries, chunked
    prefill, copy-on-write, and self-drafting speculative decoding.

    Hard raises (the acceptance gates, armed in-bench so they survive
    -O): any greedy output divergence between the runs; any
    `_paged_view` call observed DURING the fused run (counted via a
    wrapper — the fused steps must attend through the table, zero
    gathers); decode and spec-verify not traced exactly once per
    engine.

    CPU columns (deterministic offline): step/trace counts, prefill
    tokens, accept rate, the zero-gather count. tokens/s both ways is
    reported but ON-CHIP-PENDING: on CPU the fused kernel runs
    INTERPRETED (resolve_interpret), so the wall-clock contrast is
    meaningless until the kernel compiles to Mosaic on a v5e — the
    measurement slot is reserved in PERF.md's PR 13 section."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer as tlm
    from paddle_tpu.serving import ServingEngine

    cpu = jax.default_backend() == "cpu"
    if cpu:  # smoke shape: both engines compile + drain in seconds
        dim, heads, layers_n = dim or 64, heads or 4, layers_n or 2
        vocab, max_len = vocab or 256, max_len or 96
        n_requests = n_requests or 8
        max_slots = max_slots or 4
        block_tokens = block_tokens or 8
        chunk_tokens = chunk_tokens or 16
        cache_tokens = cache_tokens or 256
        spec_draft_len = spec_draft_len or 4
        header_len, t_lo, t_hi, n_lo, n_hi, rate = 12, 2, 10, 5, 12, 2.0
        dtype = jnp.float32
    else:
        dim, heads, layers_n = dim or 512, heads or 8, layers_n or 8
        vocab, max_len = vocab or 32000, max_len or 1024
        n_requests = n_requests or 64
        max_slots = max_slots or 32
        block_tokens = block_tokens or 16
        chunk_tokens = chunk_tokens or 128
        cache_tokens = cache_tokens or 8192
        spec_draft_len = spec_draft_len or 4
        header_len, t_lo, t_hi, n_lo, n_hi, rate = 128, 32, 128, 32, 96, 2.0
        dtype = jnp.bfloat16

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=max_len,
                                dtype=dtype)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    header = rng.randint(0, vocab, header_len).astype(np.int32)
    arrive_at = np.floor(
        np.cumsum(rng.exponential(1.0 / rate, n_requests))
    ).astype(int)
    reqs = [
        (
            np.concatenate([header, rng.randint(
                0, vocab, int(rng.randint(t_lo, t_hi + 1))
            ).astype(np.int32)]),
            int(rng.randint(n_lo, n_hi + 1)),
        )
        for _ in range(n_requests)
    ]

    def run_once(pk, spec):
        eng = ServingEngine(
            params, cfg, max_slots=max_slots,
            kv_block_tokens=block_tokens,
            prefill_chunk_tokens=chunk_tokens,
            prefix_cache_tokens=cache_tokens,
            spec_draft_len=spec, paged_kernel=pk)
        hs = []
        t0 = time.time()
        i = step = 0
        while i < n_requests or eng.live_slots or eng.queue_depth \
                or eng.prefilling_slots:
            while i < n_requests and arrive_at[i] <= step:
                p, n = reqs[i]
                hs.append(eng.submit(p, n, publish_len=header_len))
                i += 1
            if not eng.step() and i < n_requests:
                step = max(step + 1, int(arrive_at[i]))  # idle gap: jump
                continue
            step += 1
        wall = time.time() - t0
        return eng, wall, [list(h.tokens) for h in hs]

    # two pairs: plain decode (the decode kernel) and speculative
    # (the verify kernel) — spec replaces the plain decode step
    # entirely, so one engine can never trace both
    eng_g, wall_g, out_g = run_once("gather", None)
    eng_gs, _, out_gs = run_once("gather", spec_draft_len)

    # count every _paged_view gather the fused runs perform — the
    # fused steps must attend THROUGH the table, so this must be 0
    views = {"n": 0}
    orig_view = tlm._paged_view

    def _counting_view(*a, **kw):
        views["n"] += 1
        return orig_view(*a, **kw)

    tlm._paged_view = _counting_view
    try:
        eng_f, wall_f, out_f = run_once("fused", None)
        eng_fs, wall_fs, out_fs = run_once("fused", spec_draft_len)
    finally:
        tlm._paged_view = orig_view

    # the acceptance gates — hard raises, not asserts (must survive -O)
    if out_f != out_g or out_fs != out_g or out_gs != out_g:
        raise RuntimeError(
            "fused paged kernel changed greedy outputs vs gather")
    if views["n"]:
        raise RuntimeError(
            "fused run materialised %d _paged_view gathers (must be 0)"
            % views["n"])
    rep_g, rep_f = eng_g.metrics.report(), eng_f.metrics.report()
    rep_fs = eng_fs.metrics.report()
    for eng, pk in ((eng_g, "gather"), (eng_f, "fused")):
        if eng.metrics.report()["decode_traces"] != 1:
            raise RuntimeError(
                "%s run broke the one-compiled-step discipline: %r"
                % (pk, eng.metrics.trace_counts))
    for eng, pk in ((eng_gs, "gather+spec"), (eng_fs, "fused+spec")):
        if eng.metrics.trace_counts.get("spec_verify", 0) != 1:
            raise RuntimeError(
                "%s run broke the one-compiled-step discipline: %r"
                % (pk, eng.metrics.trace_counts))
    toks = rep_f["tokens_out"]
    return {
        "paged_view_calls_fused": views["n"],  # the gather-tax gate: 0
        "decode_steps_gather": rep_g["decode_steps"],
        "decode_steps_fused": rep_f["decode_steps"],
        "decode_traces_fused": rep_f["decode_traces"],
        "spec_verify_traces_fused":
            eng_fs.metrics.trace_counts.get("spec_verify", 0),
        "decode_steps_fused_spec": rep_fs["decode_steps"],
        "prefill_traces_fused": rep_f["prefill_traces"],
        "prefill_tokens_computed": rep_f["prefill_tokens_computed"],
        "spec_accept_rate_fused": rep_fs["spec_accept_rate"],
        "cow_blocks_fused": rep_f["cow_blocks"],
        "tokens_out": toks,
        # on-chip-pending on CPU: the fused kernel runs interpreted
        # here — only the compiled Mosaic contrast means anything
        # (PERF.md PR 13 reserves the v5e slot)
        "tokens_per_sec_gather": round(toks / wall_g, 1),
        "tokens_per_sec_fused": round(toks / wall_f, 1),
        "tokens_per_sec_fused_spec": round(toks / wall_fs, 1),
        "tokens_per_sec_note": "on-chip-pending (fused is interpreted "
                               "on CPU)" if cpu else "compiled",
        "paged_kernel_gather": rep_g["paged_kernel"],
        "paged_kernel_fused": rep_f["paged_kernel"],
        "n_requests": n_requests,
        "arrival": "poisson(rate=%g/step, seed=0)" % rate,
        "knobs": {"kv_block_tokens": block_tokens,
                  "prefill_chunk_tokens": chunk_tokens,
                  "prefix_cache_tokens": cache_tokens,
                  "spec_draft_len": spec_draft_len,
                  "max_slots": max_slots},
        "model": {"dim": dim, "heads": heads, "layers": layers_n,
                  "vocab": vocab, "max_len": max_len},
    }


def _kv_block_bytes(layers_n, heads, dh, block_tokens, kv_quant,
                    act_itemsize):
    """One physical KV block's HBM bytes at a storage dtype — the
    bench's fixed BYTE budget must price blocks exactly as the engine
    does, so this delegates to THE one formula
    (models/transformer.kv_block_bytes, also behind
    engine.kv_block_bytes and bench_offline's roofline)."""
    from paddle_tpu.models.transformer import kv_block_bytes

    return kv_block_bytes(layers_n, heads, dh, block_tokens, kv_quant,
                          act_itemsize=act_itemsize)


def _greedy_agreement(outs, ref):
    """Mean per-request prefix agreement of greedy outputs vs the
    reference run: longest common prefix over the longer length. 1.0
    = token-identical; a first-token flip on every request ~0. The
    serving_quant quality gate's metric — prefix-based because greedy
    decode is autoregressive (one flipped token reshapes everything
    after it, so position-wise matching would punish the tail twice)."""
    num = den = 0
    for a, b in zip(outs, ref):
        m = 0
        while m < min(len(a), len(b)) and a[m] == b[m]:
            m += 1
        num += m
        den += max(len(a), len(b))
    return num / den if den else 1.0


# the serving_quant quality gates: minimum mean greedy-prefix
# agreement vs the f32 run on the fixed-seed smoke trace, per variant
# — a hard raise below the floor (speed must never silently buy
# wrongness; tests/test_bench_protocol.py pins the gates stay armed).
# Floors sit under the measured smoke values by a margin that absorbs
# low-bit format drift but catches wiring bugs (a wrong scale or a
# sign error craters agreement toward ~0.1): int8 KV carries 8-bit
# codes (measured 0.93 on the 2-layer toy — near-lossless on real
# logit margins, the LLM.int8/KVQuant result); fp8 e4m3 has 3
# mantissa bits (~6% relative error, measured 0.75 — the toy model's
# tiny logit margins flip early and prefix agreement compounds);
# weight-int8 perturbs EVERY matmul, not just the cache (measured
# 0.78). 'none' IS the reference: anything under exact 1.0 means the
# baseline run stopped being the baseline.
QUANT_AGREEMENT_GATES = {
    "none": 1.0,
    "int8": 0.85,
    "fp8": 0.60,
    "weight_int8": 0.70,
}


def bench_serving_quant(n_requests=None, max_slots=None, dim=None,
                        heads=None, layers_n=None, vocab=None,
                        max_len=None, block_tokens=None,
                        chunk_tokens=None, cache_tokens=None,
                        budget_bytes=None, agreement_gate=None):
    """Quantized-serving acceptance trace (ISSUE 14): the SAME
    fixed-seed Poisson shared-header trace runs at ONE fixed KV HBM
    BYTE budget with kv_quant = none / int8 / fp8 (each variant gets
    budget_bytes // block_bytes(variant) pool blocks — int8/fp8 blocks
    cost ~1/4 the bytes, so they hold ~4x the blocks), plus a
    weight-quantized run (weight_quant='int8' at the f32 KV pool), all
    through the full reuse surface: prefix aliasing + publish
    boundaries, chunked prefill, and copy-on-write.

    Hard raises (the acceptance gates, armed in-bench so they survive
    -O): int8 KV must hold STRICTLY more resident slots than f32 at
    the byte budget; every variant's mean greedy-prefix agreement vs
    the f32 run must meet its QUANT_AGREEMENT_GATES floor (override
    every floor at once with `agreement_gate`) — the quality gate
    that keeps the byte saving from silently buying wrongness; and
    the one-compiled-step discipline must survive quantization
    (decode traced exactly once per engine).

    CPU columns (deterministic offline): slots-resident,
    bytes-per-resident-token, pool blocks at the budget, agreement,
    trace counts. tokens/s per variant is reported but
    ON-CHIP-PENDING: the HBM-bandwidth win quantization exists for is
    only measurable on a real chip (PERF.md PR 14 reserves the v5e
    slot next to PR 13's)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer as tlm
    from paddle_tpu.serving import ServingEngine

    cpu = jax.default_backend() == "cpu"
    if cpu:  # smoke shape: four engines compile + drain in seconds
        dim, heads, layers_n = dim or 64, heads or 4, layers_n or 2
        vocab, max_len = vocab or 256, max_len or 96
        n_requests = n_requests or 10
        max_slots = max_slots or 8
        block_tokens = block_tokens or 8
        chunk_tokens = chunk_tokens or 16
        cache_tokens = cache_tokens or 256
        header_len, t_lo, t_hi, n_lo, n_hi, rate = 12, 2, 10, 5, 12, 2.0
        dtype = jnp.float32
    else:
        dim, heads, layers_n = dim or 512, heads or 8, layers_n or 8
        vocab, max_len = vocab or 32000, max_len or 1024
        n_requests = n_requests or 64
        max_slots = max_slots or 32
        # int8/fp8 pools want 32-row blocks on the fused Mosaic path
        # (int8 sublane tile) — harmless for the others
        block_tokens = block_tokens or 32
        chunk_tokens = chunk_tokens or 128
        cache_tokens = cache_tokens or 8192
        header_len, t_lo, t_hi, n_lo, n_hi, rate = 128, 32, 128, 32, 96, 2.0
        dtype = jnp.bfloat16

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=max_len,
                                dtype=dtype)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    dh = dim // heads
    act_item = jnp.dtype(dtype).itemsize
    # ONE byte budget for every variant (default: ONE f32 slab slot's
    # worth of blocks — tight enough that the f32 run queues on the
    # pool while int8's ~4x blocks keep admitting)
    f32_block_bytes = _kv_block_bytes(layers_n, heads, dh, block_tokens,
                                      "none", act_item)
    if budget_bytes is None:
        budget_bytes = (max_len // block_tokens) * f32_block_bytes
    budget_bytes = int(budget_bytes)
    rng = np.random.RandomState(0)
    header = rng.randint(0, vocab, header_len).astype(np.int32)
    arrive_at = np.floor(
        np.cumsum(rng.exponential(1.0 / rate, n_requests))
    ).astype(int)
    reqs = [
        (
            np.concatenate([header, rng.randint(
                0, vocab, int(rng.randint(t_lo, t_hi + 1))
            ).astype(np.int32)]),
            int(rng.randint(n_lo, n_hi + 1)),
        )
        for _ in range(n_requests)
    ]

    variants = ["none", "int8"]
    if hasattr(jnp, "float8_e4m3fn"):
        variants.append("fp8")

    def run_once(kvq, wq=None):
        bb = _kv_block_bytes(layers_n, heads, dh, block_tokens, kvq,
                             act_item)
        blocks = max(1, budget_bytes // bb)
        eng = ServingEngine(
            params, cfg, max_slots=max_slots,
            kv_block_tokens=block_tokens, kv_pool_blocks=blocks,
            prefill_chunk_tokens=chunk_tokens,
            prefix_cache_tokens=cache_tokens,
            kv_quant=kvq, weight_quant=wq)
        hs, peak = [], 0
        t0 = time.time()
        i = step = 0
        while i < n_requests or eng.live_slots or eng.queue_depth \
                or eng.prefilling_slots:
            while i < n_requests and arrive_at[i] <= step:
                p, n = reqs[i]
                hs.append(eng.submit(p, n, publish_len=header_len))
                i += 1
            if not eng.step() and i < n_requests:
                step = max(step + 1, int(arrive_at[i]))  # idle gap: jump
                continue
            peak = max(peak, eng.live_slots + eng.prefilling_slots)
            step += 1
        wall = time.time() - t0
        return eng, wall, peak, blocks, bb, [list(h.tokens) for h in hs]

    ref_out = None
    rep = {}
    for name in variants + ["weight_int8"]:
        if name == "weight_int8":
            eng, wall, peak, blocks, bb, outs = run_once("none",
                                                         wq="int8")
        else:
            eng, wall, peak, blocks, bb, outs = run_once(name)
        if ref_out is None:  # the f32 baseline runs first
            ref_out = outs
        m = eng.metrics.report()
        ag = _greedy_agreement(outs, ref_out)
        # the quality gate — a hard raise, not an assert (must
        # survive -O): quantization may trade low bits, never the
        # trace's gross shape
        gate = QUANT_AGREEMENT_GATES[name] if agreement_gate is None \
            else float(agreement_gate)
        if ag < gate:
            raise RuntimeError(
                "serving_quant quality gate: %s agreement %.4f < %.2f "
                "vs the f32 run" % (name, ag, gate))
        if m["decode_traces"] != 1:
            raise RuntimeError(
                "%s run broke the one-compiled-step discipline: %r"
                % (name, eng.metrics.trace_counts))
        toks = m["tokens_out"]
        rep[name] = {
            "slots_resident": peak,
            "kv_pool_blocks": blocks,
            "kv_block_bytes": bb,
            "bytes_per_resident_token": round(bb / block_tokens, 2),
            "agreement_vs_f32": round(ag, 4),
            "agreement_gate": gate,
            "tokens_out": toks,
            "tokens_per_sec": round(toks / wall, 1),
            "prefix_hits": eng.prefix_cache.stats()["hits"],
            "cow_blocks": m["cow_blocks"],
            "kv_quant": m["kv_quant"],
            "weight_quant": m["weight_quant"],
        }
    # the residency inequality int8 > f32 at ONE byte budget — the
    # whole point of the PR; strictly more resident slots or the row
    # is lying about the multiplier
    if rep["int8"]["slots_resident"] <= rep["none"]["slots_resident"]:
        raise RuntimeError(
            "int8 KV did not hold more resident slots than f32 at the "
            "fixed byte budget: %d <= %d"
            % (rep["int8"]["slots_resident"],
               rep["none"]["slots_resident"]))
    # the default path must stay the default path: kv_quant='none'
    # reports no quantization (its token identity vs the pre-quant
    # tree is pinned by the tier-1 engine/kernel suites)
    if rep["none"]["kv_quant"] != "none":
        raise RuntimeError("f32 baseline ran quantized: %r" % rep["none"])
    return {
        "variants": rep,
        "agreement_gates": dict(QUANT_AGREEMENT_GATES),
        "kv_budget_bytes": budget_bytes,
        "kv_block_tokens": int(block_tokens),
        "pool_multiplier_int8": round(
            rep["int8"]["kv_pool_blocks"] / rep["none"]["kv_pool_blocks"],
            2),
        "tokens_per_sec_note": "on-chip-pending (the HBM-bandwidth win "
                               "needs a chip; PERF.md PR 14 reserves "
                               "the v5e slot)" if cpu else "compiled",
        "n_requests": n_requests,
        "arrival": "poisson(rate=%g/step, seed=0)" % rate,
        "model": {"dim": dim, "heads": heads, "layers": layers_n,
                  "vocab": vocab, "max_len": max_len,
                  "dtype": str(jnp.dtype(dtype))},
    }


def bench_serving_fleet(n_replicas=None, n_requests=None, families=None,
                        header_len=None, family_len=None, max_slots=None,
                        dim=None, heads=None, layers_n=None, vocab=None,
                        max_len=None, chunk_tokens=None, block_tokens=None,
                        cache_tokens=None, kill_replica=0):
    """Serving-fleet acceptance trace (ISSUE 6): the SAME fixed-seed
    Poisson shared-header trace runs through (a) a single-replica
    fleet (the N=1 baseline row), (b) an N-replica fleet with prefix
    AFFINITY routing and a kill drill — replica `kill_replica` is
    killed mid-trace once a third of the paced requests completed —
    and (c) an N-replica fleet with affinity OFF (undisturbed). The
    deterministic offline columns: requests lost (MUST be 0 — the
    drill's whole point), duplicate completions (must be 0), and
    failovers (must be 1 in the drill). The fleet-wide prefix reuse
    contrast (tokens saved / prefill tokens computed, affinity on vs
    off) is REPORTED but timing-dependent: least-loaded routing under
    concurrent load depends on replica-thread scheduling, and the
    kill erases one replica's pool mid-trace — the strict on>off
    inequality is pinned by the no-kill drill in
    tests/test_serving_fleet.py instead.
    Outputs must be token-identical across all three runs (hard raise
    in-bench: neither replication, routing, nor failover may change
    what a request decodes to). tokens/s and the speedup-vs-N×1 ratio
    are only meaningful on-chip — on CPU the replica threads share the
    GIL and one chip's compute, like every serving row here. A warm
    wave (one request per family, concurrent) precedes the paced trace
    so compiles and pool publication happen before measurement starts,
    matching the steady state the fleet serves in."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer as tlm
    from paddle_tpu.serving import ServingFleet

    cpu = jax.default_backend() == "cpu"
    if cpu:  # smoke shape: 3 fleets' worth of tiny engines, seconds each
        dim, heads, layers_n = dim or 64, heads or 4, layers_n or 2
        vocab, max_len = vocab or 256, max_len or 128
        n_replicas = n_replicas or 3
        n_requests, families = n_requests or 12, families or 3
        header_len, family_len = header_len or 16, family_len or 8
        max_slots = max_slots or 2
        t_lo, t_hi, n_lo, n_hi, rate = 3, 8, 4, 10, 0.5
        dtype = jnp.float32
    else:
        dim, heads, layers_n = dim or 512, heads or 8, layers_n or 8
        vocab, max_len = vocab or 32000, max_len or 1024
        n_replicas = n_replicas or 3
        n_requests, families = n_requests or 48, families or 3
        header_len, family_len = header_len or 256, family_len or 64
        max_slots = max_slots or 8
        t_lo, t_hi, n_lo, n_hi, rate = 16, 64, 32, 128, 0.5
        dtype = jnp.bfloat16
    chunk_tokens = chunk_tokens or max(16, header_len // 2)
    block_tokens = block_tokens or max(4, header_len // 4)
    cache_tokens = cache_tokens or 4 * (header_len + family_len)
    pub = header_len + family_len

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=max_len,
                                dtype=dtype)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    header = rng.randint(0, vocab, header_len).astype(np.int32)
    fam = [rng.randint(0, vocab, family_len).astype(np.int32)
           for _ in range(families)]
    arrive_at = np.floor(
        np.cumsum(rng.exponential(1.0 / rate, n_requests))
    ).astype(int)
    # warm wave: one request per family (published headers + compiled
    # buckets), then the paced Poisson trace
    warm = []
    for f in range(families):
        tail = rng.randint(0, vocab,
                           int(rng.randint(t_lo, t_hi + 1))).astype(np.int32)
        warm.append((np.concatenate([header, fam[f], tail]),
                     int(rng.randint(n_lo, n_hi + 1))))
    reqs = []
    for _ in range(n_requests):
        f = int(rng.randint(families))
        tail = rng.randint(0, vocab,
                           int(rng.randint(t_lo, t_hi + 1))).astype(np.int32)
        reqs.append((np.concatenate([header, fam[f], tail]),
                     int(rng.randint(n_lo, n_hi + 1))))

    def run_once(n_reps, affinity, kill_at=None):
        fleet = ServingFleet(
            params, cfg, n_replicas=n_reps, affinity=affinity,
            heartbeat_timeout_s=120.0,
            max_pending=2 * (n_requests + families),
            engine_kw={"max_slots": max_slots,
                       "prefill_chunk_tokens": chunk_tokens,
                       "prefix_cache_tokens": cache_tokens,
                       "prefix_block_tokens": block_tokens})
        try:
            ws = [fleet.submit(p, n, publish_len=pub) for p, n in warm]
            for h in ws:
                h.result(timeout=600)
            t0 = time.time()
            hs, i, step, killed = [], 0, 0, False
            while True:
                while i < n_requests and arrive_at[i] <= step:
                    p, n = reqs[i]
                    hs.append(fleet.submit(p, n, publish_len=pub))
                    i += 1
                if kill_at is not None and not killed \
                        and sum(h.done for h in hs) >= kill_at:
                    fleet.kill_replica(kill_replica)
                    killed = True
                if i >= n_requests and all(h.done for h in hs):
                    break
                time.sleep(0.004)
                step += 1
            for h in hs:
                h.result(timeout=600)  # raises if anything was lost
            wall = time.time() - t0
            time.sleep(0.2)  # final replica-stats sync
            st = fleet.stats()
            toks = sum(len(h.tokens) for h in hs)
            return st, [list(h.tokens) for h in ws + hs], toks / wall
        finally:
            fleet.close()

    st_1, out_1, tps_1 = run_once(1, affinity=True)
    kill_at = max(1, n_requests // 3)
    st_on, out_on, tps_on = run_once(n_replicas, affinity=True,
                                     kill_at=kill_at)
    st_off, out_off, tps_off = run_once(n_replicas, affinity=False)
    if not (out_1 == out_on == out_off):
        raise RuntimeError(
            "fleet outputs diverge across replication/affinity/kill runs")
    if st_on["lost"] or st_off["lost"] or st_1["lost"]:
        raise RuntimeError("fleet lost requests: %r" % (
            (st_1["lost"], st_on["lost"], st_off["lost"]),))
    return {
        # the drill columns (deterministic offline): nothing lost,
        # nothing double-answered, exactly one failover
        "requests_lost": st_on["lost"],
        "duplicate_completions": st_on["duplicate_refused"],
        "failovers": st_on["failovers"],
        "resubmitted": st_on["resubmitted"],
        "completed": st_on["completed"],
        # fleet-wide prefix reuse: affinity keeps families hot
        "prefix_tokens_saved_affinity_on": st_on["prefix_tokens_saved"],
        "prefix_tokens_saved_affinity_off": st_off["prefix_tokens_saved"],
        "prefill_tokens_computed_on": st_on["prefill_tokens_computed"],
        "prefill_tokens_computed_off": st_off["prefill_tokens_computed"],
        "prefix_hit_rate_on": st_on["prefix_hit_rate"],
        "prefix_hit_rate_off": st_off["prefix_hit_rate"],
        # throughput (on-chip meaningful; CPU shares one chip + GIL)
        "tokens_per_sec_single": round(tps_1, 1),
        "tokens_per_sec_fleet": round(tps_on, 1),
        "tokens_per_sec_fleet_no_kill": round(tps_off, 1),
        "speedup_vs_single": round(tps_on / tps_1, 3) if tps_1 else None,
        "ideal_speedup": n_replicas,
        "n_replicas": n_replicas,
        "n_requests": n_requests,
        "kill_drill": {"replica": kill_replica, "after_completed": kill_at},
        "arrival": "poisson(rate=%g/step, seed=0)" % rate,
        "knobs": {"max_slots": max_slots,
                  "prefill_chunk_tokens": chunk_tokens,
                  "prefix_block_tokens": block_tokens,
                  "prefix_cache_tokens": cache_tokens,
                  "publish_len": pub},
        "model": {"dim": dim, "heads": heads, "layers": layers_n,
                  "vocab": vocab, "max_len": max_len},
    }


def bench_serving_slo(n_replicas=None, n_requests=None, max_slots=None,
                      dim=None, heads=None, layers_n=None, vocab=None,
                      max_len=None, deadline_s=None, slow_window_s=None,
                      slow_step_s=None, slow_factor=None,
                      slow_min_duration_s=None):
    """Request-SLO / gray-failure acceptance trace (ISSUE 8): the SAME
    fixed-seed Poisson trace of INTERACTIVE requests — every one
    carrying a `deadline_s` budget — runs twice through an N-replica
    fleet with gray-failure detection on: (a) healthy, and (b) with
    replica 0 gray-slowed mid-trace (`slow@` fault: it heartbeats on
    every step, each step just stalls `slow_step_s` for
    `slow_window_s` of wall time — invisible to fail-stop detection).
    The deterministic offline columns, hard-raised in-bench:

      * expired requests MUST be 0 in both runs — the gray replica is
        demoted (step-latency EWMA past `slow_factor` x the live
        median, sustained) and its open requests hedged to survivors
        with token-level resume, so no deadline dies on a wedged
        replica;
      * no false demotion in the healthy run (demotions == 0 there;
        the drill run must demote >= 1 and, after the window, PROBE
        and RESTORE the replica under the SAME incarnation — warm
        pool, no fresh spawn);
      * resumed requests re-decode ZERO already-emitted tokens,
        verified from the journal itself: per rid, the concatenation
        of accepted progress deltas equals the done record's tokens —
        a re-decoded token would appear twice;
      * outputs token-identical between the healthy and gray runs
        (neither demotion, hedging, nor resume may change what a
        request decodes to).

    p99 TTFT under the gray replica is pinned within a bounded excess
    of the healthy run's: gray p99 must beat healthy p99 + the slow
    WINDOW — without demotion, work pinned on the gray replica stalls
    the whole window and then restarts from token zero, so its tail
    exceeds healthy by at least the window; with demotion + resume the
    excess is the demotion response time. tokens/s is on-chip-pending
    like every serving row (CPU replicas share one chip + the GIL);
    the drill columns above are deterministic offline."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed.fault_injection import FaultInjector
    from paddle_tpu.models import transformer as tlm
    from paddle_tpu.serving import RequestJournal, ServingFleet

    cpu = jax.default_backend() == "cpu"
    if cpu:  # smoke shape: 2 fleets' worth of tiny engines
        dim, heads, layers_n = dim or 32, heads or 4, layers_n or 2
        vocab, max_len = vocab or 64, max_len or 64
        n_replicas = n_replicas or 2
        n_requests = n_requests or 10
        # slots sized so healthy TTFT is admission-bound, not
        # queue-bound: the p99 tail must measure the GRAY response,
        # not a deliberately undersized batch
        max_slots = max_slots or 6
        t_lo, t_hi, n_lo, n_hi, rate = 4, 10, 12, 20, 0.5
        dtype = jnp.float32
    else:
        dim, heads, layers_n = dim or 512, heads or 8, layers_n or 8
        vocab, max_len = vocab or 32000, max_len or 1024
        n_replicas = n_replicas or 3
        n_requests = n_requests or 32
        max_slots = max_slots or 8
        t_lo, t_hi, n_lo, n_hi, rate = 16, 64, 32, 96, 0.5
        dtype = jnp.bfloat16
    deadline_s = deadline_s or 60.0
    slow_window_s = slow_window_s or 2.5
    slow_step_s = slow_step_s or 0.25
    slow_factor = slow_factor or 4.0
    slow_min_duration_s = slow_min_duration_s or 0.3

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=max_len,
                                dtype=dtype)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    arrive_at = np.floor(
        np.cumsum(rng.exponential(1.0 / rate, n_requests))
    ).astype(int)
    reqs = []
    for _ in range(n_requests):
        t = int(rng.randint(t_lo, t_hi + 1))
        reqs.append((rng.randint(0, vocab, t).astype(np.int32),
                     int(rng.randint(n_lo, n_hi + 1))))
    # warm waves: EVERY compiled shape the trace can hit, on EVERY
    # replica, before any health judgement (the README sizing rule:
    # never judge a replica mid-first-compile — a compile is one long
    # silent step, indistinguishable from gray slowness from outside).
    # One wave per pow-2 prefill bucket; each wave is n_replicas
    # concurrent requests, which least-loaded routing spreads one per
    # replica, so after the waves the paced trace compiles NOTHING.
    from paddle_tpu.fluid.core.kernels_sequence import bucket_pow2
    warm_buckets = sorted({max(8, bucket_pow2(t))
                           for t in range(t_lo, t_hi + 1)})
    warm_waves = []
    for L in warm_buckets:
        warm_waves.append([
            (rng.randint(0, vocab, L).astype(np.int32), 4)
            for _ in range(n_replicas)])

    def run_once(gray: bool):
        inj = FaultInjector("")  # inert until armed post-warm
        # PADDLE_TPU_KEEP_JOURNAL_DIR: land the journal there and keep
        # it, so tools/lint.sh's protocol gate can replay the bench
        # smoke's journal through `python -m paddle_tpu.analysis
        # journal` after the run
        keep_dir = os.environ.get("PADDLE_TPU_KEEP_JOURNAL_DIR") or None
        if keep_dir is not None:
            os.makedirs(keep_dir, exist_ok=True)
        jpath = tempfile.mktemp(suffix=".jsonl", prefix="slo_journal_",
                                dir=keep_dir)
        fleet = ServingFleet(
            params, cfg, n_replicas=n_replicas, journal_path=jpath,
            heartbeat_timeout_s=120.0, monitor_interval_s=0.05,
            max_pending=2 * (n_requests
                             + sum(len(w) for w in warm_waves)),
            slow_replica_factor=slow_factor,
            slow_min_duration_s=slow_min_duration_s,
            probe_interval_s=0.15,
            engine_kw={"max_slots": max_slots},
            engine_kw_for=lambda i: (
                {"fault_injector": inj} if i == 0 else {}))
        try:
            for wave in warm_waves:
                ws = [fleet.submit(p, n) for p, n in wave]
                for h in ws:
                    h.result(timeout=600)
            time.sleep(0.3)  # EWMAs settle post-compile
            if gray:
                # the gray window opens 2 engine steps into the paced
                # trace: replica 0 keeps heartbeating but every step
                # stalls — the failure heartbeat monitors cannot see
                inj.arm("slow@2:%g/%g" % (slow_window_s, slow_step_s))
            t0 = time.time()
            hs, i, step = [], 0, 0
            while True:
                while i < n_requests and arrive_at[i] <= step:
                    p, n = reqs[i]
                    hs.append(fleet.submit(
                        p, n, slo="interactive", deadline_s=deadline_s))
                    i += 1
                if i >= n_requests and all(h.done for h in hs):
                    break
                time.sleep(0.004)
                step += 1
            for h in hs:
                h.result(timeout=600)  # raises on lost/expired
            wall = time.time() - t0
            restored = True
            if gray:  # after the window: probe -> restore, same incarnation
                deadline = time.monotonic() + slow_window_s + 30.0
                while fleet.stats()["replicas"][0]["state"] != "live":
                    if time.monotonic() >= deadline:
                        restored = False
                        break
                    time.sleep(0.05)
            st = fleet.stats()
            incarnation0 = st["replicas"][0]["incarnation"]
            toks = sum(len(h.tokens) for h in hs)
            ttfts = sorted(h.ttft_s for h in hs if h.ttft_s is not None)
            p99 = (float(np.percentile(ttfts, 99)) if ttfts else None)
        finally:
            fleet.close()
        # journal audit: every progress token appears EXACTLY once in
        # its rid's done record — a resumed request that re-decoded an
        # already-emitted token would journal it twice and fail here
        done_toks, prog_toks, sources = {}, {}, {}
        for rec in RequestJournal._read(jpath):
            if rec["kind"] == "done":
                done_toks[rec["rid"]] = rec["tokens"]
            elif rec["kind"] == "progress":
                prog_toks.setdefault(rec["rid"], []).extend(rec["tokens"])
                sources.setdefault(rec["rid"], set()).add(
                    (rec["replica"], rec["incarnation"], rec["gen"]))
        if keep_dir is None:
            os.unlink(jpath)
        for rid, toks_done in done_toks.items():
            if prog_toks.get(rid, []) != toks_done:
                raise RuntimeError(
                    "rid %d: journaled progress %r != done tokens %r "
                    "(a resumed request re-decoded emitted tokens?)"
                    % (rid, prog_toks.get(rid), toks_done))
        resumed_rids = sum(1 for s in sources.values() if len(s) > 1)
        return {
            "stats": st, "outputs": [list(h.tokens) for h in hs],
            "p99_ttft_s": p99, "tokens_per_sec": toks / wall,
            "restored": restored, "incarnation0": incarnation0,
            "resumed_rids_journal": resumed_rids,
        }

    healthy = run_once(gray=False)
    gray = run_once(gray=True)
    if healthy["outputs"] != gray["outputs"]:
        raise RuntimeError(
            "outputs diverge between healthy and gray-slow runs: "
            "demotion/hedging/resume changed what a request decodes to")
    hs_st, gr_st = healthy["stats"], gray["stats"]
    for name, st in (("healthy", hs_st), ("gray", gr_st)):
        if st["expired"] or st["expired_on_arrival"]:
            raise RuntimeError(
                "%s run expired %d request(s): the SLO layer failed "
                "its zero-expired bar" % (name, st["expired"]))
        if st["lost"]:
            raise RuntimeError("%s run lost requests: %r" % (name, st))
    if hs_st["demotions"]:
        raise RuntimeError(
            "healthy run demoted a replica (false positive): %r"
            % hs_st["demotions"])
    if not gr_st["demotions"]:
        raise RuntimeError(
            "gray run never demoted the slowed replica: detection "
            "missed a %gs window of %gs steps"
            % (slow_window_s, slow_step_s))
    if not gray["restored"] or gray["incarnation0"] != 1:
        raise RuntimeError(
            "gray replica not restored warm (restored=%r, "
            "incarnation=%r): the demote-probe-restore cycle broke"
            % (gray["restored"], gray["incarnation0"]))
    if not gr_st["resumed_requests"]:
        raise RuntimeError(
            "gray run hedged nothing with token-level resume — the "
            "drill did not exercise the resume path")
    if gray["p99_ttft_s"] is not None and healthy["p99_ttft_s"] is not None \
            and gray["p99_ttft_s"] >= healthy["p99_ttft_s"] + slow_window_s:
        # without demotion, work pinned on the gray replica stalls for
        # the WHOLE window and then re-decodes from scratch — the gray
        # tail would exceed healthy by at least the window. Demotion
        # must keep the excess under it (the demotion response time)
        raise RuntimeError(
            "gray p99 TTFT %.3fs exceeds healthy %.3fs by more than "
            "the %.1fs slow window: demotion failed to bound the tail"
            % (gray["p99_ttft_s"], healthy["p99_ttft_s"], slow_window_s))
    return {
        # the SLO columns (deterministic offline)
        "expired_healthy": hs_st["expired"],
        "expired_gray": gr_st["expired"],
        "requests_lost": gr_st["lost"],
        "demotions_gray": gr_st["demotions"],
        "restores_gray": gr_st["restores"],
        "probes_sent_gray": gr_st["probes_sent"],
        "restored_same_incarnation": gray["incarnation0"] == 1,
        "resumed_requests": gr_st["resumed_requests"],
        "resumed_tokens_reused": gr_st["resumed_tokens"],
        "resumed_rids_journal": gray["resumed_rids_journal"],
        "redecoded_tokens": 0,  # journal-audited above (hard raise)
        # latency columns (wall-clock; tail bounded by demotion)
        "p99_ttft_healthy_s": round(healthy["p99_ttft_s"], 4)
        if healthy["p99_ttft_s"] is not None else None,
        "p99_ttft_gray_s": round(gray["p99_ttft_s"], 4)
        if gray["p99_ttft_s"] is not None else None,
        "p99_ttft_ratio": round(
            gray["p99_ttft_s"] / healthy["p99_ttft_s"], 2)
        if healthy["p99_ttft_s"] and gray["p99_ttft_s"] else None,
        "p99_ttft_excess_bound_s": slow_window_s,
        "tokens_per_sec_healthy": round(healthy["tokens_per_sec"], 1),
        "tokens_per_sec_gray": round(gray["tokens_per_sec"], 1),
        "n_replicas": n_replicas,
        "n_requests": n_requests,
        "arrival": "poisson(rate=%g/step, seed=0)" % rate,
        "drill": {"fault": "slow@2:%g/%g" % (slow_window_s, slow_step_s),
                  "replica": 0, "deadline_s": deadline_s},
        "knobs": {"max_slots": max_slots,
                  "slow_replica_factor": slow_factor,
                  "slow_min_duration_s": slow_min_duration_s,
                  "probe_interval_s": 0.15},
        "model": {"dim": dim, "heads": heads, "layers": layers_n,
                  "vocab": vocab, "max_len": max_len},
    }


def bench_serving_elastic(n_requests=None, max_slots=None, dim=None,
                          heads=None, layers_n=None, vocab=None,
                          max_len=None, deadline_s=None):
    """Disaggregated elastic fleet acceptance (ISSUE 11): the SAME
    fixed-seed Poisson BURST trace — every request carrying a generous
    deadline — runs twice: (a) STATIC, a fixed-size tiered fleet
    (prefill/decode disaggregation, no scaling, no rollout), and (b)
    ELASTIC, the same tiers with the autoscaler on (min 2, max 3
    replicas) plus ONE mid-trace `roll_weights` onto a CRC-verified
    checkpoint of the SAME weights (saved with `save_weights` — the
    pserver push/pull cycle recast as checkpoint promotion). The
    deterministic offline columns, hard-raised in-bench:

      * expired requests MUST be 0 in both runs (the burst rides
        scale-up instead of queue-starving deadlines), and no rid is
        lost or answered twice (`lost == 0`, one `done` per rid in
        the journal);
      * the elastic run must spawn >= 1 replica during the burst,
        retire >= 1 after it (full scale-up -> scale-down cycle),
        migrate >= 1 request from the prefill tier to a decode tier
        at first token, and complete exactly one rollout;
      * NO mixed-version output: the journal replays green through
        the protocol DFA (`--expect-closed`), including the J009
        version fence — every done record's `weights_version` equals
        its latest assignment's;
      * a CORRUPTED candidate checkpoint aborts a second
        `roll_weights` with every live replica still serving the
        rolled version, and the fleet still completing requests;
      * outputs token-identical between the static and elastic runs —
        neither tier migration, autoscaling, nor the weight rollout
        may change what a request decodes to.

    tokens/s is on-chip-pending like every serving row; the drill
    columns above are deterministic offline."""
    import glob
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis.protocol_lint import verify_journal
    from paddle_tpu.models import transformer as tlm
    from paddle_tpu.serving import (RequestJournal, RolloutAborted,
                                    ServingFleet, save_weights)

    cpu = jax.default_backend() == "cpu"
    if cpu:  # smoke shape
        dim, heads, layers_n = dim or 32, heads or 4, layers_n or 2
        vocab, max_len = vocab or 64, max_len or 64
        n_requests = n_requests or 12
        max_slots = max_slots or 3
        t_lo, t_hi, n_lo, n_hi, rate = 4, 10, 6, 12, 2.0
        dtype = jnp.float32
    else:
        dim, heads, layers_n = dim or 512, heads or 8, layers_n or 8
        vocab, max_len = vocab or 32000, max_len or 1024
        n_requests = n_requests or 32
        max_slots = max_slots or 8
        t_lo, t_hi, n_lo, n_hi, rate = 16, 64, 32, 96, 2.0
        dtype = jnp.bfloat16
    deadline_s = deadline_s or 300.0

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=max_len,
                                dtype=dtype)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    # a BURST: high-rate Poisson arrivals, so open requests outrun the
    # two starting replicas and the scaler has something to answer
    arrive_at = np.floor(
        np.cumsum(rng.exponential(1.0 / rate, n_requests))
    ).astype(int)
    reqs = []
    for _ in range(n_requests):
        t = int(rng.randint(t_lo, t_hi + 1))
        reqs.append((rng.randint(0, vocab, t).astype(np.int32),
                     int(rng.randint(n_lo, n_hi + 1))))

    work_dir = tempfile.mkdtemp(prefix="bench_elastic_")
    ckpt_dir = os.path.join(work_dir, "ckpt")
    # the promotion target: the SAME weights at step 1, written through
    # the training checkpoint machinery (CRC sidecars, atomic commit)
    # so the rollout's verify walk has something real to check — and
    # identical weights keep the output-identity bar meaningful
    save_weights(params, ckpt_dir, step=1)

    tiers = ["prefill", "decode", "decode"]

    def run_once(elastic: bool):
        keep_dir = os.environ.get("PADDLE_TPU_KEEP_JOURNAL_DIR") or None
        if keep_dir is not None:
            os.makedirs(keep_dir, exist_ok=True)
        jpath = tempfile.mktemp(suffix=".jsonl",
                                prefix="elastic_journal_", dir=keep_dir)
        kw = dict(
            n_replicas=2, journal_path=jpath,
            heartbeat_timeout_s=300.0, monitor_interval_s=0.02,
            max_pending=4 * n_requests,
            engine_kw={"max_slots": max_slots},
        )
        if elastic:
            kw.update(replica_tier=tiers, min_replicas=2,
                      max_replicas=3, scale_up_open_per_replica=2,
                      scale_down_idle_s=0.4, scale_cooldown_s=0.05,
                      ckpt_dir=ckpt_dir)
        else:
            kw.update(replica_tier=tiers[:2])
        fleet = ServingFleet(params, cfg, **kw)
        rolled = False
        try:
            t0 = time.time()
            hs, i, step = [], 0, 0
            while True:
                while i < n_requests and arrive_at[i] <= step:
                    p, n = reqs[i]
                    hs.append(fleet.submit(p, n, deadline_s=deadline_s))
                    i += 1
                if elastic and not rolled and i >= n_requests:
                    # the whole burst is in flight (requests run for
                    # many engine steps yet): first let the scaler
                    # answer the queue depth — scale-up is PAUSED
                    # during a rollout, so the cycle under test is
                    # burst -> scale-up -> rolling swap — then roll
                    # while traffic still decodes (drain -> swap ->
                    # refill; in-flight finishes on the old version)
                    gate = time.monotonic() + 60.0
                    while not fleet.stats()["replicas_spawned"]:
                        if time.monotonic() >= gate:
                            raise RuntimeError(
                                "burst never triggered a scale-up "
                                "before the mid-trace rollout")
                        time.sleep(0.01)
                    fleet.roll_weights(ckpt_step=1, timeout=300.0)
                    rolled = True
                if i >= n_requests and all(h.done for h in hs):
                    break
                time.sleep(0.004)
                step += 1
            for h in hs:
                h.result(timeout=600)  # raises on lost/expired
            wall = time.time() - t0
            if elastic:
                # after the burst: sustained low load must retire the
                # extra replica (full scale-up -> scale-down cycle)
                deadline = time.monotonic() + 60.0
                while fleet.stats()["replicas_live"] > 2:
                    if time.monotonic() >= deadline:
                        break
                    time.sleep(0.05)
                # corrupted-candidate drill: a torn weight file must
                # abort the rollout with the fleet untouched
                save_weights(params, ckpt_dir, step=2)
                bad = sorted(glob.glob(os.path.join(
                    ckpt_dir, "step_0000000002", "*.npy")))[0]
                with open(bad, "r+b") as fh:
                    fh.seek(12)
                    fh.write(b"\xde\xad\xbe\xef")
                aborted = False
                try:
                    fleet.roll_weights(ckpt_step=2, timeout=300.0)
                except RolloutAborted:
                    aborted = True
                if not aborted:
                    raise RuntimeError(
                        "corrupted candidate checkpoint did NOT abort "
                        "roll_weights")
                st_live = [r for r in fleet.stats()["replicas"]
                           if r["state"] == "live"]
                if any(r["weights_version"] != 1 for r in st_live):
                    raise RuntimeError(
                        "aborted rollout touched the fleet: live "
                        "versions %r != 1"
                        % [r["weights_version"] for r in st_live])
                # ...and the fleet still serves
                h = fleet.submit(reqs[0][0], reqs[0][1])
                post_abort = list(
                    h.result(timeout=600)[len(reqs[0][0]):])
                if post_abort != [int(t) for t in hs[0].tokens]:
                    raise RuntimeError(
                        "post-abort output diverged from the burst "
                        "run's for the same request")
            st = fleet.stats()
        finally:
            fleet.close()
        # journal audit: the protocol DFA replay IS the dedupe and
        # version-fence check — a second done for a rid is J002, a
        # done whose version differs from its latest assignment's is
        # J009, an unterminated rid is J007 (expect_closed)
        done_ver = {rec["rid"]: rec.get("weights_version")
                    for rec in RequestJournal._read(jpath)
                    if rec["kind"] == "done"}
        diags = verify_journal(jpath, expect_closed=True)
        if diags:
            raise RuntimeError(
                "journal audit failed: %s"
                % "; ".join("%s %s" % (d.code, d.message)
                            for d in diags))
        if keep_dir is None:
            os.unlink(jpath)
        if st["expired"] or st["expired_on_arrival"]:
            raise RuntimeError(
                "%s run expired %d request(s)"
                % ("elastic" if elastic else "static", st["expired"]))
        if st["lost"]:
            raise RuntimeError(
                "%s run lost requests: %r"
                % ("elastic" if elastic else "static", st))
        toks = sum(len(h.tokens) for h in hs)
        return {"stats": st, "outputs": [list(h.tokens) for h in hs],
                "versions": sorted(
                    {v for v in done_ver.values() if v is not None}),
                "tokens_per_sec": toks / wall}

    try:
        static = run_once(elastic=False)
        elastic = run_once(elastic=True)
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)
    if static["outputs"] != elastic["outputs"]:
        raise RuntimeError(
            "outputs diverge between the static and elastic runs: "
            "tier migration / scaling / rollout changed what a "
            "request decodes to")
    el = elastic["stats"]
    if not el["replicas_spawned"]:
        raise RuntimeError(
            "the burst never triggered a scale-up: autoscaler dead "
            "or thresholds wrong (%r)" % el["replicas_spawned"])
    if not el["replicas_retired"]:
        raise RuntimeError(
            "the post-burst lull never retired a replica: scale-down "
            "path dead")
    if not el["migrations"]:
        raise RuntimeError(
            "no prefill->decode migration happened on a tiered fleet")
    if el["rollouts_completed"] != 1:
        raise RuntimeError(
            "expected exactly 1 completed rollout, got %r"
            % el["rollouts_completed"])
    if el["rollout_aborts"] != 1:
        raise RuntimeError(
            "expected exactly 1 aborted rollout (the corrupted "
            "candidate drill), got %r" % el["rollout_aborts"])
    return {
        # the elasticity columns (deterministic offline)
        "expired": el["expired"],
        "requests_lost": el["lost"],
        "replicas_spawned": el["replicas_spawned"],
        "replicas_retired": el["replicas_retired"],
        "migrations": el["migrations"],
        "rollouts_completed": el["rollouts_completed"],
        "rollout_aborts": el["rollout_aborts"],
        "weights_version_final": el["weights_version"],
        "done_versions_seen": elastic["versions"],
        "resumed_requests": el["resumed_requests"],
        "resumed_tokens_reused": el["resumed_tokens"],
        "outputs_identical_to_static": True,  # hard-raised above
        "replicas_live_final": el["replicas_live"],
        # latency/throughput (wall-clock; on-chip-pending)
        "tokens_per_sec_static": round(static["tokens_per_sec"], 1),
        "tokens_per_sec_elastic": round(elastic["tokens_per_sec"], 1),
        "n_requests": n_requests,
        "arrival": "poisson(rate=%g/step, seed=0), burst" % rate,
        "knobs": {"max_slots": max_slots, "tiers": tiers,
                  "min_replicas": 2, "max_replicas": 3,
                  "scale_up_open_per_replica": 2,
                  "scale_down_idle_s": 0.4, "scale_cooldown_s": 0.05,
                  "rollout_policy": "finish", "deadline_s": deadline_s},
        "model": {"dim": dim, "heads": heads, "layers": layers_n,
                  "vocab": vocab, "max_len": max_len},
    }


def bench_serving_multitenant(n_requests=None, max_slots=None, dim=None,
                              heads=None, layers_n=None, vocab=None,
                              max_len=None, deadline_s=None):
    """Multi-tenant serving acceptance (ISSUE 12): one fleet, many
    consumers. The fixed-seed trace mixes

      * two WELL-BEHAVED deadline-class tenants (alpha, weight 2, and
        beta, weight 1), each with its own LoRA adapter batched over
        the one base model through the one compiled step;
      * gamma, a third adapter tenant whose requests force the
        2-payload-slot adapter pool to LRU-EVICT (adapters page like
        KV blocks — the paged-adapter column);
      * hog, which BURSTS 6 back-to-back submits against a burst=2
        token bucket mid-trace;
      * zoo, a batch-SLO tenant running image/CTR-style batched
        inference through the EXISTING fluid.Executor path
        (`tenancy.executor_batch_fn`), interleaved with decode by the
        same continuous-batching scheduler.

    Hard raises (the in-bench acceptance bar):

      * zero deadline misses for alpha/beta/gamma (expired == 0 and
        expired_on_arrival == 0) — the hog burst and the zoo lane
        cannot starve the deadline-class tenants;
      * the burst is shed via `TenantQuotaExceeded`, NOT
        `FleetSaturated` (fleet shed == 0), and shed submits are
        NEVER journaled (the journal's submit count is checked);
      * >= 1 adapter-pool eviction (3 adapters through 2 payload
        slots MUST page);
      * every zoo batch result equals the direct Executor run;
      * the journal replays green through the protocol DFA
        (--expect-closed) and every assign/done record carries the
        typed `tenant` side-band;
      * every tenant's outputs are TOKEN-IDENTICAL to a per-tenant
        sequential run (one single-slot engine per tenant, same
        adapter): neither batching N adapters into one step, WFQ
        routing, nor the batch lane changes what any request decodes
        to.

    tokens/s is on-chip-pending like every serving row; the columns
    above are deterministic offline."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis.protocol_lint import verify_journal
    from paddle_tpu.models import transformer as tlm
    from paddle_tpu.serving import (AdapterRegistry, RequestJournal,
                                    ServingEngine, ServingFleet,
                                    TenantQuotaExceeded, TenantRegistry,
                                    executor_batch_fn, make_adapter)

    cpu = jax.default_backend() == "cpu"
    if cpu:  # smoke shape
        dim, heads, layers_n = dim or 32, heads or 4, layers_n or 2
        vocab, max_len = vocab or 64, max_len or 64
        n_requests = n_requests or 10
        max_slots = max_slots or 3
        t_lo, t_hi, n_lo, n_hi, rate = 4, 10, 4, 8, 1.0
        dtype = jnp.float32
    else:
        dim, heads, layers_n = dim or 512, heads or 8, layers_n or 8
        vocab, max_len = vocab or 32000, max_len or 1024
        n_requests = n_requests or 24
        max_slots = max_slots or 8
        t_lo, t_hi, n_lo, n_hi, rate = 16, 64, 16, 48, 1.0
        dtype = jnp.bfloat16
    deadline_s = deadline_s or 300.0

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=max_len,
                                dtype=dtype)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    areg = AdapterRegistry()
    for name, seed in (("ad_alpha", 1), ("ad_beta", 2), ("ad_gamma", 3)):
        areg.register(name, make_adapter(cfg, rank=4, seed=seed))
    treg = TenantRegistry()
    treg.add("alpha", rate=100.0, burst=100.0, weight=2.0,
             adapter="ad_alpha")
    treg.add("beta", rate=100.0, burst=100.0, weight=1.0,
             adapter="ad_beta")
    treg.add("gamma", rate=100.0, burst=100.0, weight=1.0,
             adapter="ad_gamma")
    treg.add("hog", rate=0.001, burst=2.0, weight=1.0)
    treg.add("zoo", rate=100.0, burst=100.0, weight=1.0, slo="batch")

    # the zoo model: a tiny inference program through the EXISTING
    # fluid Executor path (the reference's save_inference_model
    # serving story) — one fc layer is enough to prove the lane; the
    # real zoo (resnet/vgg/ctr) serves through exactly this surface
    import paddle_tpu.fluid as fluid

    zoo_main, zoo_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(zoo_main, zoo_startup):
        zx = fluid.layers.data(name="zx", shape=[8], dtype="float32")
        zy = fluid.layers.fc(input=zx, size=4, act="softmax")
    zoo_exe = fluid.Executor(fluid.CPUPlace())
    zoo_exe.run(zoo_startup)
    zrng = np.random.RandomState(7)
    zoo_feeds = [{"zx": zrng.rand(4, 8).astype(np.float32)}
                 for _ in range(3)]
    zoo_direct = [zoo_exe.run(zoo_main, feed=f, fetch_list=[zy])[0]
                  for f in zoo_feeds]

    rng = np.random.RandomState(0)
    arrive_at = np.floor(
        np.cumsum(rng.exponential(1.0 / rate, n_requests))
    ).astype(int)
    tenant_of = ["alpha" if i % 2 == 0 else "beta"
                 for i in range(n_requests)]
    # gamma rides the tail: its adapter is the third through a
    # 2-payload-slot pool, so paging MUST evict
    reqs = []
    for _ in range(n_requests + 2):
        t = int(rng.randint(t_lo, t_hi + 1))
        reqs.append((rng.randint(0, vocab, t).astype(np.int32),
                     int(rng.randint(n_lo, n_hi + 1))))
    hog_burst_at = n_requests // 2

    keep_dir = os.environ.get("PADDLE_TPU_KEEP_JOURNAL_DIR") or None
    if keep_dir is not None:
        os.makedirs(keep_dir, exist_ok=True)
    jpath = tempfile.mktemp(suffix=".jsonl",
                            prefix="multitenant_journal_", dir=keep_dir)
    fleet = ServingFleet(
        params, cfg, n_replicas=2, journal_path=jpath,
        heartbeat_timeout_s=300.0, monitor_interval_s=0.02,
        max_pending=8 * (n_requests + 16), tenants=treg,
        engine_kw={"max_slots": max_slots, "adapter_registry": areg,
                   "adapter_slots": 3})
    t0 = time.time()
    by_tenant = {}
    hog_handles, quota_shed, zoo_handles = [], 0, []
    try:
        hs, i, step, burst_done = [], 0, 0, False
        while True:
            while i < n_requests + 2 and (
                    i >= n_requests or arrive_at[min(i, n_requests - 1)]
                    <= step):
                ten = tenant_of[i] if i < n_requests else "gamma"
                p, n = reqs[i]
                h = fleet.submit(p, n, tenant=ten,
                                 deadline_s=deadline_s)
                by_tenant.setdefault(ten, []).append((h, p, n))
                hs.append(h)
                i += 1
            if not burst_done and i >= hog_burst_at:
                # the quota drill: 6 back-to-back submits against a
                # burst=2 bucket — 2 admit, 4 shed as the TENANT's
                # verdict (TenantQuotaExceeded), and the fleet-wide
                # FleetSaturated shed must stay 0
                for _ in range(6):
                    p, n = reqs[0]
                    try:
                        h = fleet.submit(p, n, tenant="hog")
                    except TenantQuotaExceeded:
                        quota_shed += 1
                    else:
                        by_tenant.setdefault("hog", []).append(
                            (h, p, n))
                        hs.append(h)
                # ...and the zoo lane, through the same scheduler
                for f in zoo_feeds:
                    zoo_handles.append(fleet.submit_batch(
                        executor_batch_fn(zoo_exe, zoo_main, f, [zy]),
                        tenant="zoo", cost=8.0))
                burst_done = True
            if i >= n_requests + 2 and burst_done \
                    and all(h.done for h in hs) \
                    and all(h.done for h in zoo_handles):
                break
            time.sleep(0.004)
            step += 1
        for h in hs:
            h.result(timeout=600)  # raises on lost/expired
        for h in zoo_handles:
            h.result(timeout=600)
        wall = time.time() - t0
        st = fleet.stats()
    finally:
        fleet.close()

    if quota_shed != 4:
        raise RuntimeError(
            "hog burst: expected 4 TenantQuotaExceeded sheds "
            "(burst=2 of 6), got %d" % quota_shed)
    if st["shed"] != 0:
        raise RuntimeError(
            "the burst leaked into FleetSaturated (%d): quota must "
            "shed it as the tenant's verdict" % st["shed"])
    if st["expired"] or st["expired_on_arrival"]:
        raise RuntimeError(
            "%d deadline miss(es): the burst/zoo lanes starved a "
            "well-behaved tenant" % (st["expired"]
                                     + st["expired_on_arrival"]))
    if st["lost"]:
        raise RuntimeError("requests lost: %r" % st)
    if st["adapter_evictions"] < 1:
        raise RuntimeError(
            "no adapter-pool eviction: 3 adapters through 2 payload "
            "slots must page (got %r)" % st["adapter_evictions"])
    for got, want in zip([h.batch_result[0] for h in zoo_handles],
                         zoo_direct):
        if not np.allclose(got, want):
            raise RuntimeError(
                "zoo batch-lane result diverged from the direct "
                "Executor run")

    # journal audit: DFA green (exactly-once, typed side-bands,
    # everything terminal) + shed-never-journaled + tenant side-band
    # present on every assign/done
    recs = list(RequestJournal._read(jpath))
    n_submits = sum(1 for r in recs if r["kind"] == "submit")
    n_expected = len(hs) + len(zoo_handles)
    if n_submits != n_expected:
        raise RuntimeError(
            "journal holds %d submits, %d requests were accepted — a "
            "shed submit was journaled (or one was lost)"
            % (n_submits, n_expected))
    for r in recs:
        if r["kind"] == "assign" and "tenant" not in r:
            raise RuntimeError("assign record without tenant side-band")
        if r["kind"] == "done" and r.get("tenant") is None:
            raise RuntimeError("done record without tenant side-band")
    diags = verify_journal(jpath, expect_closed=True)
    if diags:
        raise RuntimeError(
            "journal audit failed: %s"
            % "; ".join("%s %s" % (d.code, d.message) for d in diags))
    if keep_dir is None:
        os.unlink(jpath)

    # per-tenant SEQUENTIAL oracle: one single-slot engine per tenant
    # (same base weights, same adapter) — batching N tenants' adapters
    # into one compiled step must not change any tenant's tokens
    for ten, items in sorted(by_tenant.items()):
        eng = ServingEngine(params, cfg, max_slots=1,
                            adapter_registry=areg, adapter_slots=3)
        seq = [eng.submit(p, n, adapter=treg.get(ten).adapter)
               for _h, p, n in items]
        eng.run()
        for (h, _p, _n), sh in zip(items, seq):
            if list(h.tokens) != list(sh.tokens):
                raise RuntimeError(
                    "tenant %r outputs diverge from its sequential "
                    "run: %r != %r" % (ten, h.tokens, sh.tokens))

    tok_total = sum(len(h.tokens) for h in hs)
    tenants = st["tenants"]
    return {
        # the multi-tenant columns (deterministic offline)
        "deadline_misses_well_behaved": st["expired"]
        + st["expired_on_arrival"],
        "requests_lost": st["lost"],
        "quota_shed": quota_shed,
        "fleet_saturated_shed": st["shed"],
        "hog_admitted": len(by_tenant.get("hog", [])),
        "batch_jobs_completed": st["batch_jobs_completed"],
        "adapter_hits": st["adapter_hits"],
        "adapter_misses": st["adapter_misses"],
        "adapter_evictions": st["adapter_evictions"],
        "adapter_uploads": st["adapter_uploads"],
        "outputs_identical_per_tenant": True,  # hard-raised above
        "zoo_results_match_executor": True,    # hard-raised above
        "per_tenant": {
            t: {"completed": v["completed"],
                "tokens_out": v["tokens_out"],
                "shed_quota": v["shed_quota"],
                "mean_queue_wait_s": v["mean_queue_wait_s"]}
            for t, v in sorted(tenants.items())},
        # latency/throughput (wall-clock; on-chip-pending)
        "tokens_per_sec": round(tok_total / wall, 1),
        "n_requests": n_requests,
        "arrival": "poisson(rate=%g/step, seed=0) + hog burst of 6"
        % rate,
        "knobs": {"max_slots": max_slots, "n_replicas": 2,
                  "adapter_slots": 3, "adapter_rank": 4,
                  "weights": {"alpha": 2.0, "beta": 1.0, "gamma": 1.0},
                  "hog_bucket": {"rate": 0.001, "burst": 2},
                  "deadline_s": deadline_s},
        "model": {"dim": dim, "heads": heads, "layers": layers_n,
                  "vocab": vocab, "max_len": max_len},
    }


def bench_serving_integrity(n_requests=None, max_slots=None, dim=None,
                            heads=None, layers_n=None, vocab=None,
                            max_len=None, canary_interval_s=None):
    """Silent-corruption tolerance acceptance (ISSUE 15): the SAME
    fixed-seed shared-header Poisson trace runs three times through a
    2-replica fleet with the full integrity stack armed (in-step
    numeric traps, KV block fingerprints, known-answer canaries,
    auto_refill quarantine):

      clean   no fault — pins the FALSE-POSITIVE bar: zero integrity
              trips, zero canary mismatches, zero fingerprint
              mismatches on a healthy fleet (canaries complete clean)
      garble  replica 1 emits wrong-but-FINITE tokens from mid-trace
              on (garble@, sticky — the SDC shape numeric traps cannot
              see); the next known-answer canary mismatches, the
              replica quarantines with its journaled progress since
              the last clean canary TAINTED, and the taint windows
              re-decode on the healthy survivor
      flip    one resident KV block on replica 1 is corrupted in place
              (flip@, finite garbage); the fingerprint spot-check at
              the next aliased re-open (the shared header keeps
              hitting replica 1 under prefix affinity) catches it

    Hard raises, all deterministic offline: every drill's outputs
    TOKEN-IDENTICAL to the clean run (zero tainted tokens survive into
    final outputs — the falsifiability bar: a single laundered corrupt
    token diverges), the corrupt replica tripped + quarantined EXACTLY
    once per drill with the expected trip kind (canary vs fingerprint)
    and a fresh incarnation (supervisor-backoff refill), zero rids
    lost or duplicated, and every journal green through the protocol
    DFA `--expect-closed` INCLUDING the J010 taint fence — re-decoded
    tokens lie entirely inside journaled taint windows, and nothing
    lands from a quarantined incarnation after its integrity event."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis.diagnostics import format_diag
    from paddle_tpu.analysis.protocol_lint import verify_journal
    from paddle_tpu.distributed.fault_injection import FaultInjector
    from paddle_tpu.models import transformer as tlm
    from paddle_tpu.serving import ServingFleet

    cpu = jax.default_backend() == "cpu"
    if cpu:  # smoke shape: 3 fleets' worth of tiny engines
        dim, heads, layers_n = dim or 32, heads or 4, layers_n or 2
        vocab, max_len = vocab or 64, max_len or 64
        n_requests = n_requests or 8
        max_slots = max_slots or 4
        t_hdr, t_lo, t_hi, n_lo, n_hi, rate = 8, 2, 5, 8, 14, 0.5
        dtype = jnp.float32
    else:
        dim, heads, layers_n = dim or 512, heads or 8, layers_n or 8
        vocab, max_len = vocab or 32000, max_len or 1024
        n_requests = n_requests or 24
        max_slots = max_slots or 8
        t_hdr, t_lo, t_hi, n_lo, n_hi, rate = 32, 8, 24, 32, 64, 0.5
        dtype = jnp.bfloat16
    canary_interval_s = canary_interval_s or 0.05
    bt = 4  # small blocks: the shared header publishes whole blocks

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=max_len,
                                dtype=dtype)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    header = rng.randint(0, vocab, t_hdr).astype(np.int32)
    arrive_at = np.floor(
        np.cumsum(rng.exponential(1.0 / rate, n_requests))
    ).astype(int)
    reqs = []
    for _ in range(n_requests):
        tail = rng.randint(0, vocab,
                           rng.randint(t_lo, t_hi + 1)).astype(np.int32)
        reqs.append((np.concatenate([header, tail]),
                     int(rng.randint(n_lo, n_hi + 1))))

    def run_once(fault):
        # inert until armed post-warm; handed to replica 1 ONCE — the
        # quarantine's fresh incarnation composes its engine kwargs
        # again and must come up CLEAN (a sticky garble re-armed on
        # the replacement would just trip it again, forever)
        inj = FaultInjector("")
        armed = {"used": False}

        def kw_for(i):
            if i == 1 and not armed["used"]:
                armed["used"] = True
                return {"fault_injector": inj}
            return {}

        keep_dir = os.environ.get("PADDLE_TPU_KEEP_JOURNAL_DIR") or None
        if keep_dir is not None:
            os.makedirs(keep_dir, exist_ok=True)
        jpath = tempfile.mktemp(suffix=".jsonl",
                                prefix="integrity_journal_",
                                dir=keep_dir)
        fleet = ServingFleet(
            params, cfg, n_replicas=2, journal_path=jpath,
            heartbeat_timeout_s=120.0, monitor_interval_s=0.02,
            max_pending=4 * n_requests, affinity=True,
            auto_refill=True, canary_interval_s=canary_interval_s,
            engine_kw={"max_slots": max_slots, "kv_block_tokens": bt,
                       "prefix_cache_tokens": 32 * bt,
                       "kv_fingerprints": True},
            engine_kw_for=kw_for)
        try:
            # warm both replicas (compiles + seed the shared-header
            # prefix on each pool) and let one clean canary land per
            # replica before any fault: the canary mark is the taint
            # window's left edge, and the drills' windows must open at
            # a VERIFIED index, not at token zero
            w0 = fleet.submit(*reqs[0])
            w1 = fleet.submit(*reqs[1])
            w0.result(timeout=600)
            w1.result(timeout=600)
            deadline = time.monotonic() + 60.0
            while fleet.stats()["canaries_ok"] < 2:
                if time.monotonic() >= deadline:
                    raise RuntimeError(
                        "no clean canary within 60s of a warm fleet: "
                        "the canary machinery is broken")
                time.sleep(0.01)
            if fault is not None:
                inj.arm(fault)  # fires on replica 1's next steps
            t0 = time.time()
            hs, i, step = [], 0, 0
            while True:
                while i < n_requests and arrive_at[i] <= step:
                    hs.append(fleet.submit(*reqs[i]))
                    i += 1
                if i >= n_requests and all(h.done for h in hs):
                    break
                time.sleep(0.004)
                step += 1
            outs = [list(h.result(timeout=600)) for h in hs]
            wall = time.time() - t0
            if fault is not None:
                # the quarantine must complete: fresh incarnation on
                # the corrupt replica (supervisor-backoff auto-refill)
                deadline = time.monotonic() + 60.0
                while fleet.stats()["replicas"][1]["incarnation"] < 2:
                    if time.monotonic() >= deadline:
                        raise RuntimeError(
                            "tripped replica never refilled under a "
                            "fresh incarnation")
                    time.sleep(0.02)
            st = fleet.stats()
            toks = sum(len(h.tokens) for h in hs)
        finally:
            fleet.close()
        diags = verify_journal(jpath, expect_closed=True)
        if diags:
            raise RuntimeError(
                "journal DFA violations (%s run):\n  %s"
                % (fault or "clean",
                   "\n  ".join(format_diag(d) for d in diags)))
        if keep_dir is None:
            os.unlink(jpath)
        return {"outputs": outs, "stats": st,
                "tokens_per_sec": toks / wall if wall else None}

    clean = run_once(None)
    st = clean["stats"]
    if st["integrity_trips"] or st["canary_mismatches"] \
            or st["fp_mismatches"]:
        raise RuntimeError(
            "clean run tripped the integrity sentinel (false "
            "positive): %r" % {k: st[k] for k in (
                "integrity_trips", "canary_mismatches",
                "fp_mismatches")})
    if not st["canaries_ok"]:
        raise RuntimeError("clean run completed no canaries: the "
                           "known-answer machinery never ran")

    drills = {}
    for name, fault, want_kind in (
            ("garble", "garble@2", "canary"),
            ("flip", "flip@2", "fingerprint")):
        rec = run_once(fault)
        dst = rec["stats"]
        if rec["outputs"] != clean["outputs"]:
            raise RuntimeError(
                "%s drill outputs diverge from the clean run: a "
                "corrupt token survived quarantine + taint-aware "
                "resume" % name)
        if dst["integrity_trips"] != 1:
            raise RuntimeError(
                "%s drill: expected exactly one integrity trip, got "
                "%r (%r)" % (name, dst["integrity_trips"],
                             dst["integrity_trip_kinds"]))
        if dst["integrity_trip_kinds"].get(want_kind) != 1:
            raise RuntimeError(
                "%s drill tripped via %r, expected kind %r"
                % (name, dst["integrity_trip_kinds"], want_kind))
        if dst["lost"] or dst["duplicate_refused"]:
            raise RuntimeError("%s drill lost/duplicated requests: %r"
                               % (name, dst))
        if dst["replicas"][1]["incarnation"] != 2:
            raise RuntimeError(
                "%s drill: corrupt replica quarantined %d times, "
                "expected exactly once (fresh incarnation == 2)"
                % (name, dst["replicas"][1]["incarnation"] - 1))
        drills[name] = dst

    return {
        # the integrity columns (deterministic offline)
        "trips_clean": st["integrity_trips"],
        "canaries_ok_clean": st["canaries_ok"],
        "trips_garble": drills["garble"]["integrity_trips"],
        "trip_kind_garble": dict(
            drills["garble"]["integrity_trip_kinds"]),
        "tainted_tokens_garble": drills["garble"]["tainted_tokens"],
        "trips_flip": drills["flip"]["integrity_trips"],
        "trip_kind_flip": dict(drills["flip"]["integrity_trip_kinds"]),
        "fp_mismatches_flip": drills["flip"]["fp_mismatches"],
        "requests_lost": max(d["lost"] for d in drills.values()),
        "outputs_identical": True,  # hard-raised above
        "journal_dfa": "green --expect-closed incl. J010 (hard-raised)",
        # honest overhead row (PERF.md): trap+fingerprint+canary cost
        # on the same trace, clean run vs drills — wall-clock, so
        # on-chip-pending like every serving tokens/s column
        "tokens_per_sec_clean": (
            round(clean["tokens_per_sec"], 1)
            if clean["tokens_per_sec"] else None),
        "n_requests": n_requests,
        "arrival": "poisson(rate=%g/step, seed=0), %d-token shared "
                   "header" % (rate, t_hdr),
        "drill": {"garble": "garble@2 (replica 1, sticky)",
                  "flip": "flip@2 (replica 1, one resident block)"},
        "knobs": {"max_slots": max_slots, "kv_block_tokens": bt,
                  "canary_interval_s": canary_interval_s,
                  "kv_fingerprints": True, "auto_refill": True},
        "model": {"dim": dim, "heads": heads, "layers": layers_n,
                  "vocab": vocab, "max_len": max_len},
    }


def bench_serving_kv_handoff(n_requests=None, max_slots=None, dim=None,
                             heads=None, layers_n=None, vocab=None,
                             max_len=None):
    """Durable-KV fleet acceptance (ISSUE 16): the SAME fixed-seed
    shared-header Poisson trace runs four times against ONE tiered
    block store directory (host-RAM/disk spill of closed, quantized,
    fingerprinted KV blocks):

      cold     1 replica, empty store — pins the baseline outputs,
               the cold first-request TTFT/prefill cost, and seeds
               the store (publish-at-retire spill MUST leave >= 1
               durable record behind)
      handoff  2 replicas, prefill/decode tiers, same store — every
               first-token migration ships the finished prefix as a
               checksummed block package; the CLEAN-PATH bar, hard-
               raised: `tokens_recomputed_at_migration == 0` with
               >= 1 migration and >= 1 verified import (re-prefill
               demoted to a counted fallback, not the path)
      kill     3 replicas (prefill + 2 decode), same store — one
               decode replica killed mid-trace; failover may fall
               back to re-prefill (graceful degradation, COUNTED in
               `handoff_fallbacks`) but never changes a token
      warm     a fresh 1-replica fleet on the same store directory —
               the restart warms its prefix trie from the store
               (`store_warm_blocks` >= 1) and serves the first
               shared-header request WITHOUT re-decoding the header
               (strictly fewer prefill tokens than the cold phase's
               first request); warm-vs-cold TTFT is the honest
               latency contrast column

    Hard raises, all deterministic offline: outputs token-identical
    across all four phases, zero rids lost or double-answered, and
    every phase's journal green through the protocol DFA
    `--expect-closed` INCLUDING the J011 handoff fence — every done
    record accounts for the block package its assignment shipped.
    tokens/s and the TTFT contrast are wall-clock (on-chip-pending
    like every serving row)."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis.diagnostics import format_diag
    from paddle_tpu.analysis.protocol_lint import verify_journal
    from paddle_tpu.models import transformer as tlm
    from paddle_tpu.serving import ServingFleet

    cpu = jax.default_backend() == "cpu"
    if cpu:  # smoke shape: 4 fleets' worth of tiny engines
        dim, heads, layers_n = dim or 32, heads or 4, layers_n or 2
        vocab, max_len = vocab or 64, max_len or 64
        n_requests = n_requests or 8
        max_slots = max_slots or 4
        t_hdr, t_lo, t_hi, n_lo, n_hi, rate = 8, 2, 5, 8, 14, 0.5
        dtype = jnp.float32
    else:
        dim, heads, layers_n = dim or 512, heads or 8, layers_n or 8
        vocab, max_len = vocab or 32000, max_len or 1024
        n_requests = n_requests or 24
        max_slots = max_slots or 8
        t_hdr, t_lo, t_hi, n_lo, n_hi, rate = 32, 8, 24, 32, 64, 0.5
        dtype = jnp.bfloat16
    bt = 4  # small blocks: the shared header spans >= 2 whole blocks

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=max_len,
                                dtype=dtype)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    header = rng.randint(0, vocab, t_hdr).astype(np.int32)
    arrive_at = np.floor(
        np.cumsum(rng.exponential(1.0 / rate, n_requests))
    ).astype(int)
    reqs = []
    for _ in range(n_requests):
        tail = rng.randint(0, vocab,
                           rng.randint(t_lo, t_hi + 1)).astype(np.int32)
        reqs.append((np.concatenate([header, tail]),
                     int(rng.randint(n_lo, n_hi + 1))))

    work_dir = tempfile.mkdtemp(prefix="bench_kvhandoff_")
    store_dir = os.path.join(work_dir, "kv_store")

    def run_phase(name, tiers, kill_at=None):
        keep_dir = os.environ.get("PADDLE_TPU_KEEP_JOURNAL_DIR") or None
        if keep_dir is not None:
            os.makedirs(keep_dir, exist_ok=True)
        jpath = tempfile.mktemp(suffix=".jsonl",
                                prefix="kvhandoff_%s_journal_" % name,
                                dir=keep_dir)
        fleet = ServingFleet(
            params, cfg, n_replicas=len(tiers), journal_path=jpath,
            heartbeat_timeout_s=120.0, monitor_interval_s=0.02,
            max_pending=4 * n_requests, affinity=True,
            replica_tier=(tiers if len(tiers) > 1 else None),
            kv_store_dir=store_dir, kv_store_bytes=1 << 20,
            handoff=True,
            engine_kw={"max_slots": max_slots, "kv_block_tokens": bt,
                       "prefix_cache_tokens": 32 * bt,
                       "kv_fingerprints": True})
        try:
            # request 0 runs ALONE first in every phase: its isolated
            # TTFT + prefill-token cost is the cold-vs-warm contrast
            # (same request, same fleet shape, only the store differs)
            h0 = fleet.submit(*reqs[0])
            h0.result(timeout=600)
            pst = fleet.stats()
            probe = {"prefill_tokens": pst["prefill_tokens_computed"],
                     "warm_blocks": pst["store_warm_blocks"],
                     "ttft_s": h0.ttft_s}
            t0 = time.time()
            hs, i, step, killed = [h0], 1, 0, False
            while True:
                while i < n_requests and arrive_at[i] <= step:
                    hs.append(fleet.submit(*reqs[i]))
                    i += 1
                if kill_at is not None and not killed \
                        and sum(h.done for h in hs) >= kill_at:
                    fleet.kill_replica(len(tiers) - 1)
                    killed = True
                if i >= n_requests and all(h.done for h in hs):
                    break
                time.sleep(0.004)
                step += 1
            outs = [list(h.result(timeout=600)) for h in hs]
            wall = time.time() - t0
            st = fleet.stats()
            toks = sum(len(h.tokens) for h in hs)
        finally:
            fleet.close()
        diags = verify_journal(jpath, expect_closed=True)
        if diags:
            raise RuntimeError(
                "journal DFA violations (%s phase):\n  %s"
                % (name, "\n  ".join(format_diag(d) for d in diags)))
        if keep_dir is None:
            os.unlink(jpath)
        if st["lost"] or st["duplicate_refused"]:
            raise RuntimeError("%s phase lost/duplicated requests: %r"
                               % (name, {k: st[k] for k in
                                         ("lost", "duplicate_refused")}))
        return {"outputs": outs, "stats": st, "probe": probe,
                "tokens_per_sec": toks / wall if wall else None}

    try:
        cold = run_phase("cold", ["decode"])
        cst = cold["stats"]
        if not cst["kv_store"] or cst["kv_store"]["records"] < 1:
            raise RuntimeError(
                "cold phase spilled nothing to the block store: "
                "publish-at-retire path dead (%r)" % (cst["kv_store"],))

        handoff = run_phase("handoff", ["prefill", "decode"])
        hst = handoff["stats"]
        if not hst["migrations"]:
            raise RuntimeError(
                "no prefill->decode migration on the tiered fleet: "
                "the handoff path was never exercised")
        if hst["tokens_recomputed_at_migration"] != 0:
            raise RuntimeError(
                "clean handoff phase re-prefilled %d token(s) at "
                "migration — block packages must make the target's "
                "re-prefill count ZERO (imports=%d fallbacks=%d)"
                % (hst["tokens_recomputed_at_migration"],
                   hst["handoff_imports"], hst["handoff_fallbacks"]))
        if not hst["handoff_imports"]:
            raise RuntimeError(
                "clean handoff phase imported no block package "
                "(packages=%d): every migration fell back"
                % hst["handoff_packages"])

        kill_at = max(1, n_requests // 3)
        kill = run_phase("kill", ["prefill", "decode", "decode"],
                         kill_at=kill_at)
        kst = kill["stats"]
        if kst["replicas"][2]["state"] != "dead":
            raise RuntimeError(
                "kill drill: replica 2 still %r after kill_replica"
                % kst["replicas"][2]["state"])

        warm = run_phase("warm", ["decode"])
        wst = warm["stats"]
        if not wst["store_warm_blocks"]:
            raise RuntimeError(
                "restarted fleet warmed zero blocks from the store: "
                "trie warm-start path dead (%r)" % (wst["kv_store"],))
        if warm["probe"]["prefill_tokens"] >= \
                cold["probe"]["prefill_tokens"]:
            raise RuntimeError(
                "warm restart re-decoded the shared header: first "
                "request prefilled %d token(s) vs %d cold — the "
                "store-warmed trie saved nothing"
                % (warm["probe"]["prefill_tokens"],
                   cold["probe"]["prefill_tokens"]))

        for name, rec in (("handoff", handoff), ("kill", kill),
                          ("warm", warm)):
            if rec["outputs"] != cold["outputs"]:
                raise RuntimeError(
                    "%s phase outputs diverge from the cold baseline: "
                    "a transferred/spilled block changed what a "
                    "request decodes to" % name)
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)

    return {
        # the durability columns (deterministic offline)
        "store_records_after_cold": cst["kv_store"]["records"],
        "store_spilled_blocks": cst["store_spilled_blocks"],
        "migrations_handoff": hst["migrations"],
        "handoff_packages": hst["handoff_packages"],
        "handoff_imports": hst["handoff_imports"],
        "handoff_blocks_imported": hst["handoff_blocks_imported"],
        "handoff_fallbacks_clean": hst["handoff_fallbacks"],
        "tokens_recomputed_at_migration": (
            hst["tokens_recomputed_at_migration"]),
        "kill_failovers": kst["failovers"],
        "kill_handoff_fallbacks": kst["handoff_fallbacks"],
        "store_warm_blocks": wst["store_warm_blocks"],
        "warm_first_prefill_tokens": warm["probe"]["prefill_tokens"],
        "cold_first_prefill_tokens": cold["probe"]["prefill_tokens"],
        "store_quarantined": wst["store_quarantined"],
        "outputs_identical": True,  # hard-raised above
        "journal_dfa": "green --expect-closed incl. J011 (hard-raised)",
        # latency/throughput contrast (wall-clock; on-chip-pending)
        "ttft_cold_s": (round(cold["probe"]["ttft_s"], 4)
                        if cold["probe"]["ttft_s"] is not None else None),
        "ttft_warm_s": (round(warm["probe"]["ttft_s"], 4)
                        if warm["probe"]["ttft_s"] is not None else None),
        "tokens_per_sec_handoff": (
            round(handoff["tokens_per_sec"], 1)
            if handoff["tokens_per_sec"] else None),
        "n_requests": n_requests,
        "arrival": "poisson(rate=%g/step, seed=0), %d-token shared "
                   "header" % (rate, t_hdr),
        "knobs": {"max_slots": max_slots, "kv_block_tokens": bt,
                  "kv_store_bytes": 1 << 20, "handoff": True,
                  "kv_fingerprints": True},
        "model": {"dim": dim, "heads": heads, "layers": layers_n,
                  "vocab": vocab, "max_len": max_len},
    }


def bench_serving_frontdoor(dim=None, heads=None, layers_n=None,
                            vocab=None, max_len=None, max_slots=None,
                            n_replicas=2, n_warm=None, prompt_len=None,
                            max_new=None, sweep_duration_s=None,
                            rate_factors=(0.25, 0.5, 1.0, 2.5),
                            settle_s=30.0):
    """Wire-protocol front door acceptance (ISSUE 18): a 2-tenant
    open-loop load harness against the REAL serving surface — TCP
    sockets, NDJSON frames, auth -> tenant admission, token streaming
    — swept to the capacity knee, then kill- and disconnect-drilled.

      warm     one connection, blocking generates — compiles the
               engine, pins wire-vs-direct output identity (serving
               through the socket must not change what a request
               decodes to), and measures a capacity estimate (a
               saturating concurrent wave straight into the fleet)
               that anchors the sweep's rates
      sweep    fixed-seed Poisson arrivals at 0.25x/0.5x/1x/2.5x the
               estimated capacity, every request streamed; open loop,
               so past the knee the backlog grows without bound and
               the fleet's bounded admission sheds it as typed
               FLEET_SATURATED refusals — `find_knee` must locate a
               measurable knee (goodput flat vs offered + sheds/p99
               inflection), hard-raised if the sweep never saturates
      kill     the chaos variant: the same open-loop load at 0.5x
               capacity with a replica killed mid-load — >= 1
               failover, zero lost, zero duplicated, and every
               streamed request's chunks still concatenate
               bit-identically to its done frame (the journal-fed
               stream splice across failover), scored on the TTFT
               SLO histogram
      drop     a client opens a long streamed generate and vanishes:
               the fleet must journal a `cancelled` terminal and
               free the abandoned stream (disconnect == cancel)

    Hard raises: wire-vs-direct identity; at EVERY swept rate zero
    stream divergence, zero duplicated rids, zero unresolved requests
    (a deadline miss must surface as a typed shed, never silence —
    the well-behaved tenant's bar), zero sheds for the well-behaved
    tenant at the baseline rate; a located knee; kill-drill failover
    with lost == duplicate_refused == 0; >= 1 disconnect cancel; and
    the journal green through the DFA --expect-closed including the
    cancelled terminal and conn/stream side-bands. All timings are
    host wall-clock around socket I/O — CPU-honest shape columns
    (PERF.md), not chip throughput claims."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis.diagnostics import format_diag
    from paddle_tpu.analysis.protocol_lint import verify_journal
    from paddle_tpu.models import transformer as tlm
    from paddle_tpu.serving import (FrontDoor, ServingFleet,
                                    TenantRegistry, WireClient)
    from paddle_tpu.serving.loadgen import find_knee, run_open_loop

    cpu = jax.default_backend() == "cpu"
    if cpu:  # smoke shape: the knee is relative, the drills absolute
        dim, heads, layers_n = dim or 32, heads or 4, layers_n or 2
        vocab, max_len = vocab or 64, max_len or 128
        max_slots = max_slots or 4
        n_warm = n_warm or 6
        prompt_len, max_new = prompt_len or 6, max_new or 8
        sweep_duration_s = sweep_duration_s or 1.2
        dtype = jnp.float32
    else:
        dim, heads, layers_n = dim or 512, heads or 8, layers_n or 8
        vocab, max_len = vocab or 32000, max_len or 1024
        max_slots = max_slots or 8
        n_warm = n_warm or 8
        prompt_len, max_new = prompt_len or 24, max_new or 32
        sweep_duration_s = sweep_duration_s or 3.0
        dtype = jnp.bfloat16

    cfg = tlm.TransformerConfig(vocab=vocab, dim=dim, heads=heads,
                                layers=layers_n, max_len=max_len,
                                dtype=dtype)
    params = tlm.init_params(cfg, jax.random.PRNGKey(0))
    treg = TenantRegistry()
    # generous quotas: the knee must come from the fleet's bounded
    # admission (FLEET_SATURATED), not a token bucket — quota sheds
    # have their own bench (serving_multitenant)
    treg.add("alice", rate=1e6, burst=1e6, weight=3.0)
    treg.add("bob", rate=1e6, burst=1e6, weight=1.0)
    auth = {"tok-alice": "alice", "tok-bob": "bob"}
    tenants = [{"name": "alice", "token": "tok-alice", "weight": 3.0},
               {"name": "bob", "token": "tok-bob", "weight": 1.0}]

    keep_dir = os.environ.get("PADDLE_TPU_KEEP_JOURNAL_DIR") or None
    if keep_dir is not None:
        os.makedirs(keep_dir, exist_ok=True)
    jpath = tempfile.mktemp(suffix=".jsonl",
                            prefix="frontdoor_journal_", dir=keep_dir)
    fleet = ServingFleet(
        params, cfg, n_replicas=n_replicas, journal_path=jpath,
        heartbeat_timeout_s=300.0, monitor_interval_s=0.02,
        max_pending=1 << 16, tenants=treg,
        engine_kw={"max_slots": max_slots})
    fd = FrontDoor(fleet, auth=auth).start()
    rng = np.random.RandomState(0)
    try:
        # -- warm + wire-vs-direct identity ---------------------------
        warm_prompt = rng.randint(1, vocab, prompt_len).astype(np.int32)
        dh = fleet.submit(warm_prompt, max_new, seed=3, tenant="alice")
        dh.result(timeout=600)
        direct = [int(t) for t in dh.tokens]  # generated-only, like
        # the wire's done.tokens (result() prepends the prompt)
        wc = WireClient(fd.address, token="tok-alice")
        got = wc.generate_blocking("warm", warm_prompt, max_new, seed=3,
                                   stream=True)
        wc.close()
        if got["tokens"] != direct:
            raise RuntimeError(
                "wire answer diverges from the direct fleet answer "
                "for the same (prompt, seed): %r vs %r"
                % (got["tokens"], direct))
        if [t for c in got["chunks"] for t in c] != got["tokens"]:
            raise RuntimeError(
                "warm streamed chunks do not concatenate to the done "
                "frame: %r vs %r" % (got["chunks"], got["tokens"]))
        # capacity estimate: a saturating concurrent wave straight
        # into the fleet (full batching; the open-loop sweep cannot
        # exceed it, so rates anchored on it bracket the knee). The
        # FIRST wave pays the batch-shape compiles; only the second,
        # compile-warm wave is timed — an anchor deflated by compile
        # time would park the whole sweep under the knee
        for wave in range(2):
            hs = [fleet.submit(
                      rng.randint(1, vocab,
                                  prompt_len).astype(np.int32),
                      max_new, seed=100 + 10 * wave + i,
                      tenant="alice")
                  for i in range(n_warm)]
            t0 = time.time()
            for h in hs:
                h.result(timeout=600)
        cap_rps = n_warm / max(time.time() - t0, 1e-6)
        # size bounded admission so the top swept rate MUST shed: the
        # open-loop backlog past the knee overflows it by design
        fleet.max_pending = max(8, int(round(
            0.5 * cap_rps * sweep_duration_s)))

        # -- open-loop rate sweep to the knee -------------------------
        rates = [max(2.0, round(f * cap_rps, 2)) for f in rate_factors]
        reports = []
        for i, r in enumerate(rates):
            rep = run_open_loop(
                fd.address, tenants, r, sweep_duration_s, seed=7 + i,
                prompt_len=prompt_len, max_new_tokens=max_new,
                vocab=vocab, stream=True, settle_s=settle_s)
            if rep["stream_divergent"]:
                raise RuntimeError(
                    "rate %.2f rps: %d streamed request(s) diverged "
                    "from their done frame" % (r, rep["stream_divergent"]))
            if rep["duplicate_rids"]:
                raise RuntimeError(
                    "rate %.2f rps: %d duplicated rid(s) on the wire"
                    % (r, rep["duplicate_rids"]))
            if rep["wire_unresolved"]:
                raise RuntimeError(
                    "rate %.2f rps: %d request(s) got NO typed verdict "
                    "(lost on the wire — a deadline miss or shed must "
                    "be typed, never silent)"
                    % (r, rep["wire_unresolved"]))
            reports.append(rep)
        base = reports[0]["per_tenant"]["alice"]
        if base["shed"]:
            raise RuntimeError(
                "well-behaved tenant shed at the baseline rate "
                "(%.2fx capacity): %r"
                % (rate_factors[0], base["shed"]))
        knee = find_knee(reports)
        if knee["knee_rate_rps"] is None:
            raise RuntimeError(
                "rate sweep exhibited no measurable knee: %s"
                % knee["reason"])

        # -- kill drill: open-loop load + mid-load replica kill -------
        fleet.max_pending = 1 << 16   # the drill is about failover,
        failovers_before = fleet.stats()["failovers"]  # not shedding

        def chaos():
            with fleet._cond:
                holders = [i for i, m in enumerate(fleet._in_flight)
                           if m]
            fleet.kill_replica(holders[0] if holders else 0)

        kill_rep = run_open_loop(
            fd.address, tenants, max(2.0, round(0.5 * cap_rps, 2)),
            sweep_duration_s, seed=31, prompt_len=prompt_len,
            max_new_tokens=max_new, vocab=vocab, stream=True,
            deadline_s=float(settle_s), settle_s=settle_s,
            chaos_after_s=0.3 * sweep_duration_s, chaos_fn=chaos)
        st = fleet.stats()
        if st["failovers"] <= failovers_before:
            raise RuntimeError("kill drill produced no failover")
        if kill_rep["stream_divergent"]:
            raise RuntimeError(
                "kill drill: %d streamed request(s) diverged across "
                "failover" % kill_rep["stream_divergent"])
        if kill_rep["wire_unresolved"] or kill_rep["duplicate_rids"]:
            raise RuntimeError(
                "kill drill: %d unresolved, %d duplicated rid(s)"
                % (kill_rep["wire_unresolved"],
                   kill_rep["duplicate_rids"]))
        if kill_rep["per_tenant"]["alice"]["shed"].get(
                "DEADLINE_EXCEEDED"):
            raise RuntimeError(
                "kill drill: the well-behaved tenant missed its "
                "deadline %d time(s) under failover load"
                % kill_rep["per_tenant"]["alice"]["shed"]
                ["DEADLINE_EXCEEDED"])
        if not kill_rep["completed"]:
            raise RuntimeError("kill drill completed nothing")

        # -- disconnect drill: a streaming client vanishes ------------
        cancelled_before = fleet.stats()["cancelled"]
        for attempt in range(5):
            dc = WireClient(fd.address, token="tok-bob")
            dc.generate("drop-%d" % attempt,
                        rng.randint(1, vocab, prompt_len),
                        8 * max_new, seed=50 + attempt, stream=True)
            f = dc.recv()
            while f is not None and f.get("op") != "accepted":
                f = dc.recv()
            dc.close()
            t1 = time.time()
            while fleet.stats()["cancelled"] <= cancelled_before \
                    and time.time() - t1 < 10:
                time.sleep(0.01)
            if fleet.stats()["cancelled"] > cancelled_before:
                break
        st = fleet.stats()
        if st["cancelled"] <= cancelled_before:
            raise RuntimeError(
                "disconnect drill: no request was cancelled (the "
                "dropped connection's stream was never clawed back)")
        if st["lost"] or st["duplicate_refused"]:
            raise RuntimeError(
                "front door run lost/duplicated requests: %r"
                % {k: st[k] for k in ("lost", "duplicate_refused")})
        fd_stats = fd.stats()
        if not fd_stats["disconnect_cancels"]:
            raise RuntimeError(
                "fleet cancelled %d but the front door counted no "
                "disconnect cancel" % st["cancelled"])
    finally:
        fd.close()
        fleet.close()
    diags = verify_journal(jpath, expect_closed=True)
    if diags:
        raise RuntimeError(
            "journal DFA violations:\n  %s"
            % "\n  ".join(format_diag(d) for d in diags))
    if keep_dir is None:
        os.unlink(jpath)

    def row(rep):
        return {k: rep[k] for k in
                ("rate_rps", "offered_rps", "goodput_rps",
                 "ttft_p50_s", "ttft_p99_s", "ttft_p999_s",
                 "itl_p50_s", "itl_p99_s", "completed", "sent",
                 "shed")}

    return {
        # the sweep (host wall-clock; shape, not chip throughput)
        "capacity_est_rps": round(cap_rps, 2),
        "sweep": [row(r) for r in reports],
        "knee_rate_rps": knee["knee_rate_rps"],
        "knee_reason": knee["reason"],
        "baseline_shed_alice": 0,  # hard-raised above
        # the kill drill (SLO histogram carries the failover mass)
        "kill_drill": dict(row(kill_rep),
                           slo_histogram=kill_rep["slo_histogram"],
                           per_tenant=kill_rep["per_tenant"]),
        "kill_failovers": st["failovers"] - failovers_before,
        # exactly-once + disconnect accounting
        "requests_lost": st["lost"],
        "duplicates": st["duplicate_refused"],
        "cancelled": st["cancelled"],
        "cancel_late_refused": st["cancel_late_refused"],
        "disconnect_cancels": fd_stats["disconnect_cancels"],
        "stream_divergent": 0,      # hard-raised above, every phase
        "wire_vs_direct_identical": True,
        "journal_dfa": "green --expect-closed incl. cancelled + "
                       "conn/stream side-bands (hard-raised)",
        "frontdoor_stats": fd_stats,
        "knobs": {"n_replicas": n_replicas, "max_slots": max_slots,
                  "prompt_len": prompt_len, "max_new": max_new,
                  "sweep_duration_s": sweep_duration_s,
                  "rate_factors": list(rate_factors)},
        "model": {"dim": dim, "heads": heads, "layers": layers_n,
                  "vocab": vocab, "max_len": max_len},
    }


def bench_input_pipeline(n_shards=4, chunks_per_shard=8,
                         records_per_chunk=64, batch=64, step_s=0.004,
                         decode_sleep_s=0.0001, num_workers=2,
                         prefetch_batches=4):
    """Host-side input pipeline (paddle_tpu/data): the SAME fixed-seed
    synthetic shards + consumer, measured twice — prefetch OFF
    (num_workers=0: chunk decode runs synchronously inside next(), the
    pre-ISSUE-3 one-record-at-a-time posture) vs prefetch ON (decode
    threads + bounded queue overlap decode under the consumer's
    simulated step). The columns that matter are `wait_fraction` (share
    of consumer time blocked on input — the accelerator-idle fraction
    an input-bound job would see) and batches/s; both are pure host
    work, so the row is fully offline-measurable and deterministic in
    WHAT it delivers (the per-record checksum must match between runs —
    prefetch must never change what the model sees).

    `decode_sleep_s` adds a fixed GIL-RELEASING per-record decode cost
    on top of the small numpy work — the stand-in for real decodes
    (JPEG, decompression, tokenization in C) which release the GIL and
    therefore actually parallelize across the loader's threads. A
    decode that is pure small-ndarray Python stays GIL-bound and gains
    little from threads (CPython); the knob keeps the measured overlap
    about the pipeline, not about the GIL."""
    import pickle
    import tempfile

    from paddle_tpu.data import DataLoader, ShardedDataset, ShardWriter

    dim = 1024
    root = os.environ.get("BENCH_DATA_DIR") or tempfile.gettempdir()
    sdir = os.path.join(
        root, "bench_input_pipeline_%dx%dx%dx%d"
        % (n_shards, chunks_per_shard, records_per_chunk, dim))
    os.makedirs(sdir, exist_ok=True)
    paths = []
    for s in range(n_shards):
        p = os.path.join(sdir, "shard_%03d.rs" % s)
        paths.append(p)
        if os.path.exists(p):
            continue
        # per-shard RNG stream: skipping cached shards must not shift
        # the draws of the ones still to be written (a partially
        # populated cache dir would otherwise silently produce a
        # different "fixed-seed" trace than a fresh run)
        rng = np.random.RandomState(7 * 1000003 + s)
        rid = s * chunks_per_shard * records_per_chunk
        with ShardWriter(p, records_per_chunk=records_per_chunk) as w:
            for _ in range(chunks_per_shard * records_per_chunk):
                vec = rng.rand(dim).astype(np.float32)
                w.write(struct.pack("<I", rid) + vec.tobytes())
                rid += 1

    def decode(rec):
        (r,) = struct.unpack_from("<I", rec)
        vec = np.frombuffer(rec[4:], np.float32).astype(np.float64)
        vec = (vec - vec.mean()) / (vec.std() + 1e-6)  # host normalise
        if decode_sleep_s:
            time.sleep(decode_sleep_s)
        return r, vec.astype(np.float32)

    def run(workers, prefetch):
        import zlib

        ds = ShardedDataset(paths, decode_fn=decode, seed=7)
        dl = DataLoader(ds, batch, num_workers=workers,
                        prefetch_batches=prefetch)
        # ORDER-SENSITIVE digest (crc chained over ids in delivery
        # order): reordered batches or records must change it, or the
        # "prefetch never changes what the model sees" assert could not
        # catch a broken reassembly
        checksum = 0
        try:
            for ids, _vecs in dl:
                checksum = zlib.crc32(
                    np.ascontiguousarray(ids, np.int64).tobytes(),
                    checksum)
                time.sleep(step_s)  # the consumer's simulated step
        finally:
            dl.close()
        rep = dl.metrics.report()
        rep["checksum"] = checksum
        return rep

    off = run(0, 1)
    on = run(num_workers, prefetch_batches)
    assert on["checksum"] == off["checksum"], \
        "prefetch changed the delivered record stream"
    rec = {
        "prefetch_off": off,
        "prefetch_on": on,
        "wait_fraction_off": off["wait_fraction"],
        "wait_fraction_on": on["wait_fraction"],
        "batches_per_sec_off": off["batches_per_sec"],
        "batches_per_sec_on": on["batches_per_sec"],
        "overlap_speedup": round(off["wall_s"] / on["wall_s"], 3)
        if on["wall_s"] else None,
        "records": n_shards * chunks_per_shard * records_per_chunk,
        "batch": batch,
        "num_workers": num_workers,
        "prefetch_batches": prefetch_batches,
        "trace": "fixed-seed(7) synthetic shards, step_s=%g" % step_s,
    }
    return rec


def _make_sentinel_shards(sdir, n_shards, chunks_per_shard,
                          records_per_chunk, dim, seed, poison_chunk=None):
    """Fixed-seed linear-regression shards for the sentinel drills.
    Record = <I rid> ++ f64 features[dim] ++ f64 target. `poison_chunk`
    (a GLOBAL chunk index) gets its features scaled by 1e200 — the
    first batch touching it overflows the f64 loss to inf, the silent
    failure the sentinel must catch. Per-chunk RNG streams, so the
    poison never shifts any other chunk's draws."""
    from paddle_tpu.data import ShardWriter

    os.makedirs(sdir, exist_ok=True)
    w_true = np.linspace(-1.0, 1.0, dim)
    paths = []
    rid = 0
    for s in range(n_shards):
        p = os.path.join(sdir, "shard_%02d.rs" % s)
        paths.append(p)
        with ShardWriter(p, records_per_chunk=records_per_chunk) as w:
            for k in range(chunks_per_shard):
                gci = s * chunks_per_shard + k
                rng = np.random.RandomState(seed * 7919 + gci)
                for _ in range(records_per_chunk):
                    vec = rng.randn(dim)
                    y = float(vec @ w_true)
                    if gci == poison_chunk:
                        vec = vec * 1e200
                    w.write(struct.pack("<I", rid)
                            + vec.astype("<f8").tobytes()
                            + struct.pack("<d", y))
                    rid += 1
    return paths


class _CkptScope(dict):
    """Minimal scope (keys/get/set) for distributed.checkpoint."""

    def get(self, name):
        return dict.get(self, name)

    def set(self, name, value):
        self[name] = value


def _sentinel_training_job(ckpt_dir, shard_paths, quarantine_path, *,
                           dim=8, batch=16, epochs=2, lr=0.05, seed=11,
                           promote_after=4, ckpt_every=2,
                           rollback_budget=2, spike_factor=4.0,
                           hysteresis=1, warmup=2, injector=None,
                           max_incarnations=12):
    """Deterministic in-process stand-in for a supervised training
    worker: an incarnation loop (each pass = one worker lifetime) over
    resume_or_init -> train -> sentinel.observe -> checkpoint, where a
    sentinel trip ends the incarnation exactly like the subprocess
    worker's SENTINEL_EXIT_CODE exit would (tests/sentinel_worker.py
    is the real-process twin driven by the Supervisor). Pure float64
    numpy SGD on the fixed-seed shards — bit-deterministic, so loss
    curves can be compared EXACTLY across runs.

    Returns the full audit: committed loss curve (last write per step
    wins — a rollback's replay overwrites the diverged suffix), per-step
    batch ids, trips, per-incarnation resume records, and the final
    outcome ("done" / "abandon" / "incomplete")."""
    from paddle_tpu.data import DataLoader, ShardedDataset
    from paddle_tpu.distributed import checkpoint as ckpt_mod
    from paddle_tpu.distributed import sentinel as sent_mod

    rec_bytes = 4 + 8 * dim + 8

    def decode(rec):
        (rid,) = struct.unpack_from("<I", rec)
        vec = np.frombuffer(rec[4:4 + 8 * dim], "<f8")
        (y,) = struct.unpack_from("<d", rec, 4 + 8 * dim)
        assert len(rec) == rec_bytes
        return rid, np.asarray(vec), y

    curve = {}        # step -> loss (committed history, last write wins)
    step_ids = {}     # step -> batch record ids (same discipline)
    step_epoch = {}   # step -> loader epoch the batch came from
    trips = []
    resumes = []
    outcome = "incomplete"
    for inc in range(1, max_incarnations + 1):
        ds = ShardedDataset(shard_paths, decode_fn=decode, seed=seed,
                            quarantine_path=quarantine_path)
        dl = DataLoader(ds, batch, num_workers=0)
        detector = sent_mod.DivergenceDetector(
            spike_factor=spike_factor, hysteresis=hysteresis,
            warmup=warmup)
        sent = sent_mod.TrainingSentinel(
            ckpt_dir, quarantine_path=quarantine_path, dataset=ds,
            promote_after=promote_after, rollback_budget=rollback_budget,
            detector=detector)
        scope = _CkptScope()
        meta = ckpt_mod.resume_or_init(
            scope, ckpt_dir,
            stateful={"loader": dl, "detector": detector})
        if meta is not None:
            step = int(meta["extra"]["step"])
            w = np.asarray(scope.get("w"), np.float64)
            sent.align(step)
        else:
            step = 0
            w = np.zeros(dim, np.float64)
        resumes.append({
            "incarnation": inc,
            "step": None if meta is None else step,
            "known_good": sent.known_good_step,
            "fallbacks": [] if meta is None else meta.get("fallbacks", []),
        })
        status = None
        while dl.epoch < epochs and status is None:
            for ids, X, y in dl:
                if injector is not None:
                    injector.tick()
                step += 1
                # poisoned records overflow f64 BY DESIGN: the inf loss
                # is the signal under test, not a numerics accident
                with np.errstate(over="ignore", invalid="ignore"):
                    err = X @ w - y
                    loss = float(np.mean(err * err))
                if injector is not None:
                    loss = injector.poison_loss(loss)
                decision = sent.observe(step, loss,
                                        cursor=dl.state_dict())
                if decision is not None:
                    trips.append(decision)
                    status = decision["action"]
                    break
                w = w - lr * (2.0 / len(y)) * (X.T @ err)
                curve[step] = loss
                step_ids[step] = [int(r) for r in ids]
                step_epoch[step] = dl.epoch
                if step % ckpt_every == 0:
                    scope.set("w", w)
                    ckpt_mod.save_checkpoint(
                        scope, ckpt_dir, step=step,
                        extra={"step": step}, keep_last=2,
                        stateful={"loader": dl, "detector": detector},
                        protect=sent.known_good_step)
                    sent.on_checkpoint(step, cursor=dl.state_dict())
        dl.close()
        if status is None:
            outcome = "done"
            break
        if status == "abandon":
            outcome = "abandon"
            break
        # rollback / quarantine: the next incarnation resumes from the
        # known-good step (the diverged dirs were set aside by the trip)
    return {
        "outcome": outcome,
        "incarnations": inc,
        "trips": trips,
        "resumes": resumes,
        "curve": curve,
        "step_ids": step_ids,
        "step_epoch": step_epoch,
        "final_w": w.tolist(),
    }


def bench_training_sentinel(n_shards=2, chunks_per_shard=4,
                            records_per_chunk=32, batch=16, dim=8,
                            epochs=2, promote_after=4, ckpt_every=2,
                            rollback_budget=2, poison_pos=5, seed=11):
    """Silent-failure tolerance acceptance (ISSUE 10), pure host work.

    A fixed-seed supervised-training job whose deterministic chunk
    stream contains ONE poisoned chunk (1e200-scaled features -> inf
    loss the first batch that touches it). The sentinel must: trip,
    roll back to the last KNOWN-GOOD checkpoint (not the latest), trip
    again on the replay, quarantine the poison chunk (journaled exactly
    once), and complete with a finite loss curve IDENTICAL, step for
    step and bit for bit, to a clean-baseline run whose quarantine was
    pre-seeded with the same chunk — proving exact step/cursor
    continuity through two rollbacks and a quarantine. A separate
    sub-drill corrupts the newest checkpoint of a finished run and
    proves resume walks back to the newest verifiable step (bad dir
    renamed `.corrupt`, the failing CRC named) with zero manual
    intervention. Every invariant is asserted IN the bench, so the row
    cannot decay into a no-op."""
    import tempfile

    from paddle_tpu.data import ShardedDataset
    from paddle_tpu.distributed import checkpoint as ckpt_mod
    from paddle_tpu.distributed import fault_injection as fi
    from paddle_tpu.distributed import sentinel as sent_mod

    root = tempfile.mkdtemp(prefix="bench_sentinel_")
    # the poison chunk is chosen BY POSITION in epoch 0's deterministic
    # visitation order (so the trip step is stable), then written into
    # the shards at the matching global index
    probe_paths = _make_sentinel_shards(
        os.path.join(root, "probe"), n_shards, chunks_per_shard,
        records_per_chunk, dim, seed)
    order0 = ShardedDataset(probe_paths, seed=seed).epoch_order(0)
    poison_chunk = int(order0[poison_pos])

    kw = dict(dim=dim, batch=batch, epochs=epochs, seed=seed,
              promote_after=promote_after, ckpt_every=ckpt_every,
              rollback_budget=rollback_budget)

    # --- poisoned run: the sentinel earns its keep -------------------
    poisoned_paths = _make_sentinel_shards(
        os.path.join(root, "poisoned"), n_shards, chunks_per_shard,
        records_per_chunk, dim, seed, poison_chunk=poison_chunk)
    qpath = os.path.join(root, "poisoned", "quarantine.jsonl")
    job = _sentinel_training_job(
        os.path.join(root, "poisoned", "ckpt"), poisoned_paths, qpath,
        **kw)
    assert job["outcome"] == "done", job["outcome"]
    assert len(job["trips"]) >= 1, "sentinel never tripped"
    # every rollback landed on the known-good step of its trip, and the
    # next incarnation resumed EXACTLY there
    for i, trip in enumerate(job["trips"]):
        resume = job["resumes"][i + 1]
        assert resume["step"] == trip["rollback_to"], (trip, resume)
    # the poison chunk is journaled exactly once, with the right blame
    q_entries = [e for e in sent_mod.quarantine_entries(qpath)
                 if e["chunk"] == poison_chunk]
    assert len(q_entries) == 1, q_entries
    quarantined = sorted(sent_mod.quarantined_chunks(qpath))
    # attribution is exact on this trace: the hard trip fires on the
    # first poisoned batch, so the healthy-cursor window names the
    # poison chunk ALONE — no clean chunk loses its data
    assert quarantined == [poison_chunk], quarantined
    curve = job["curve"]
    losses = [curve[s] for s in sorted(curve)]
    assert np.isfinite(losses).all(), "non-finite loss in committed curve"

    # --- clean baseline: same job, quarantine pre-seeded -------------
    clean_paths = _make_sentinel_shards(
        os.path.join(root, "clean"), n_shards, chunks_per_shard,
        records_per_chunk, dim, seed)
    q_clean = os.path.join(root, "clean", "quarantine.jsonl")
    sent_mod.quarantine_chunks(q_clean, quarantined,
                               reason="clean-baseline preseed")
    clean = _sentinel_training_job(
        os.path.join(root, "clean", "ckpt"), clean_paths, q_clean, **kw)
    assert clean["outcome"] == "done" and not clean["trips"], clean["trips"]
    assert sorted(curve) == sorted(clean["curve"]), "step sets differ"
    curve_matches = all(curve[s] == clean["curve"][s] for s in curve)
    assert curve_matches, "post-quarantine curve diverged from clean run"
    ids_match = all(job["step_ids"][s] == clean["step_ids"][s]
                    for s in curve)
    assert ids_match, "delivered record stream diverged from clean run"
    # no record double-delivered or skipped in the committed stream:
    # per epoch, every non-quarantined record id appears exactly once
    n_rec = n_shards * chunks_per_shard * records_per_chunk
    quarantined_ids = set()
    for c in quarantined:
        quarantined_ids |= set(range(c * records_per_chunk,
                                     (c + 1) * records_per_chunk))
    for epoch in range(epochs):
        ids = [r for s in curve if job["step_epoch"][s] == epoch
               for r in job["step_ids"][s]]
        assert len(ids) == len(set(ids)), "double-delivered records"
        assert set(ids) == set(range(n_rec)) - quarantined_ids

    # --- corrupted-latest resume: zero manual intervention -----------
    clean_ckpt = os.path.join(root, "clean", "ckpt")
    steps_before = ckpt_mod.retain(clean_ckpt, keep_last=10)
    newest = steps_before[0]
    npy = sorted(glob.glob(os.path.join(
        clean_ckpt, "step_%010d" % newest, "*.npy")))[0]
    fi.corrupt_file(npy)
    resumed = _sentinel_training_job(clean_ckpt, clean_paths, q_clean,
                                     **kw)
    assert resumed["outcome"] == "done"
    fallbacks = resumed["resumes"][0]["fallbacks"]
    assert fallbacks and fallbacks[0]["step"] == newest, fallbacks
    assert any("CRC" in p for p in fallbacks[0]["problems"]), fallbacks
    assert os.path.isdir(fallbacks[0]["renamed_to"])
    assert resumed["resumes"][0]["step"] == steps_before[1]

    return {
        "sentinel_trips": len(job["trips"]),
        "trip_verdicts": [t["verdict"] for t in job["trips"]],
        "rollback_to": [t["rollback_to"] for t in job["trips"]],
        "rollbacks_landed_on_known_good": True,
        "incarnations": job["incarnations"],
        "poison_chunk": poison_chunk,
        "quarantined_chunks": quarantined,
        "poison_journaled_once": True,
        "final_loss": losses[-1],
        "steps_total": len(curve),
        "curve_finite": True,
        "curve_matches_clean": curve_matches,
        "record_stream_matches_clean": ids_match,
        "corrupt_resume": {
            "ok": True,
            "corrupted_step": newest,
            "walked_back_to": resumed["resumes"][0]["step"],
            "renamed_to": os.path.basename(fallbacks[0]["renamed_to"]),
            "problem": fallbacks[0]["problems"][0],
        },
        "knobs": {"promote_after": promote_after,
                  "ckpt_every": ckpt_every,
                  "rollback_budget": rollback_budget},
        "trace": "fixed-seed(%d) shards, poison at epoch0 pos %d"
                 % (seed, poison_pos),
    }


def bench_flash_attention(B=4, T=4096, H=16, D=64, steps=(4, 16)):
    """Pallas flash attention vs XLA full-matrix attention, single chip,
    bf16, causal (parallel/flash_attention.py). Timing puts the
    iterations inside one lax.scan and differences two step counts —
    per-call timing is invalid on this harness (the tunnel acks
    dispatches before execution and memoizes repeated identical calls;
    both failure modes observed in r3)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from paddle_tpu.parallel import flash_attention, reference_attention

    if jax.default_backend() == "cpu":
        return {"skipped": "pallas flash timing needs the TPU backend "
                           "(CPU runs it in interpret mode only)"}

    rng = np.random.RandomState(0)
    base = rng.randn(B, T, H, D).astype(np.float32) * 0.1
    q = jnp.asarray(base + 1e-3, jnp.bfloat16)
    k = jnp.asarray(base, jnp.bfloat16)
    v = jnp.asarray(base * 0.5, jnp.bfloat16)

    def per_iter(attn):
        def multi(n):
            @jax.jit
            def f(q, k, v):
                def body(c, _):
                    o = attn(c, k, v)
                    # feed the output back so no iteration is dead code
                    return (c + 1e-6 * o).astype(c.dtype), ()

                out, _ = lax.scan(body, q, None, length=n)
                return out.sum()

            return f

        # scalar readback forces completion
        run_at = _jit_per_count(multi, lambda f: float(f(q, k, v)))
        return _diff_time(run_at, *steps, return_info=True)

    def per_iter_grad(attn):
        """fwd+bwd per-iteration cost: grads chain into the carry so no
        iteration is dead code (r5: exercises the pallas backward)."""
        def loss(c, kk, vv):
            return attn(c, kk, vv).astype(jnp.float32).sum()

        def multi(n):
            @jax.jit
            def f(q, k, v):
                def body(c, _):
                    gq = jax.grad(loss)(c, k, v)
                    return (c + 1e-6 * gq).astype(c.dtype), ()

                out, _ = lax.scan(body, q, None, length=n)
                return out.sum()

            return f

        run_at = _jit_per_count(multi, lambda f: float(f(q, k, v)))
        return _diff_time(run_at, *steps, return_info=True)

    dt_flash, t_flash = per_iter(
        lambda c, kk, vv: flash_attention(c, kk, vv, causal=True))
    dt_ref, t_ref = per_iter(
        lambda c, kk, vv: reference_attention(c, kk, vv, causal=True))
    dt_fb_flash, t_fb_flash = per_iter_grad(
        lambda c, kk, vv: flash_attention(c, kk, vv, causal=True))
    dt_fb_ref, t_fb_ref = per_iter_grad(
        lambda c, kk, vv: reference_attention(c, kk, vv, causal=True))
    ms_flash, ms_ref = dt_flash * 1e3, dt_ref * 1e3
    err = float(jnp.abs(
        flash_attention(q, k, v, causal=True).astype(jnp.float32)
        - reference_attention(q, k, v, causal=True).astype(jnp.float32)
    ).max())
    # causal attention fwd FLOPs: 2 matmuls, half the T^2 window
    flops = 2.0 * B * H * T * T * D
    return {
        "ms_flash": round(ms_flash, 3),
        "ms_xla_full": round(ms_ref, 3),
        "speedup": round(ms_ref / ms_flash, 3),
        "flash_tflops": round(flops / (ms_flash / 1e3) / 1e12, 1),
        # fwd+bwd: the pallas backward (two tiled passes off the lse
        # residual) vs XLA autodiff of the full-matrix attention
        "ms_fwdbwd_flash": round(dt_fb_flash * 1e3, 3),
        "ms_fwdbwd_xla": round(dt_fb_ref * 1e3, 3),
        "fwdbwd_speedup": round(dt_fb_ref / dt_fb_flash, 3),
        "max_err": err,
        "dtype": "bfloat16",
        "shape": [B, T, H, D],
        "timing": {"flash": t_flash, "xla_full": t_ref,
                   "fwdbwd_flash": t_fb_flash, "fwdbwd_xla": t_fb_ref},
    }


def main():
    os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "bfloat16")

    # bounded device-init wait: a dead tunnel otherwise hangs the bench
    # forever inside jax.devices() with no output at all (seen in r3:
    # multi-hour axon outage). The watchdog turns that into a diagnostic
    # line + clean nonzero exit the driver can act on.
    import threading

    _state = {"headline": None, "workloads": {}}

    def _run_offline(reason):
        """Regenerate BENCH_offline_r05.json (AOT v5e HLO + cost
        analysis — perf evidence that survives tunnel outages, r4
        verdict #2) in a subprocess on the host backend. Bounded by the
        SMALLER of BENCH_OFFLINE_TIMEOUT_S and the time left before the
        total-budget watchdog, so it can never eat the contract line."""
        if os.environ.get("BENCH_OFFLINE", "1") != "1":
            return {"skipped": "BENCH_OFFLINE=0"}
        import subprocess

        # 2200: the artifact now carries 14 AOT workloads (~25 min on a
        # loaded box — the r5 rehearsal hit the old 1500 s budget, and
        # before that two 900 s refreshes timed out racing capture
        # runs); worst case headline (~300 s) + sides (<=3600 s) + this
        # still clears the 7200 s watchdog. The stale committed
        # artifact remains the fallback either way.
        budget = float(os.environ.get("BENCH_OFFLINE_TIMEOUT_S", "2200"))
        if _DEADLINE is not None:
            budget = min(budget, _DEADLINE - time.monotonic() - 60)
        if budget < 120:
            return {"skipped": "under 120s of total budget left"}
        t0 = time.time()
        try:
            p = subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "bench_offline.py")],
                capture_output=True, text=True, timeout=budget,
            )
            rec = {"ok": p.returncode == 0,
                   "seconds": round(time.time() - t0, 1), "reason": reason}
            if p.returncode != 0:
                rec["tail"] = (p.stdout[-200:] + p.stderr[-200:])
            return rec
        except Exception as e:
            return {"error": "%s: %s" % (type(e).__name__, e)}

    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT_S", "1200"))
    total_timeout = float(os.environ.get("BENCH_TOTAL_TIMEOUT_S", "7200"))
    global _DEADLINE
    _DEADLINE = time.monotonic() + total_timeout
    init_done = threading.Event()

    def _watchdog():
        start = time.monotonic()
        if not init_done.wait(init_timeout):
            # outage day: still leave auditable perf evidence behind
            # (offline v5e AOT artifact), then the error contract line
            print(json.dumps({"offline_artifact":
                              _run_offline("device init timed out")}),
                  flush=True)
            err = {
                "metric": "bench_error",
                "error": "device init exceeded %gs — accelerator "
                         "backend unavailable" % init_timeout,
            }
            banked = _last_banked_headline()
            if banked:
                err["best_banked_stable_headline"] = banked
            print(json.dumps(err), flush=True)
            os._exit(3)
        # stay armed for the WHOLE run: a tunnel death mid-workload
        # otherwise blocks inside a device call with no output at all.
        # Budget from ACTUAL elapsed init time (a fast init must not
        # shrink the run budget; a total <= init_timeout must still arm)
        remaining = total_timeout - (time.monotonic() - start)
        if remaining <= 0:
            # init alone consumed the whole budget: report rather than
            # silently disarming mid-run coverage
            print(
                json.dumps({
                    "metric": "bench_error",
                    "error": "device init consumed the whole "
                             "BENCH_TOTAL_TIMEOUT_S=%g budget"
                             % total_timeout,
                }),
                flush=True,
            )
            os._exit(3)
        if not _bench_finished.wait(remaining):
            # the headline runs FIRST: if a later side workload hung,
            # mark the hang (not silent) and still emit the contract
            # line before exiting
            if _state.get("headline") is not None:
                _state["workloads"]["bench_watchdog"] = {
                    "error": "side workload hung past "
                             "BENCH_TOTAL_TIMEOUT_S=%g; headline was "
                             "already measured" % total_timeout,
                }
                _emit_headline()
                os._exit(0)
            print(
                json.dumps({
                    "metric": "bench_error",
                    "error": "bench exceeded BENCH_TOTAL_TIMEOUT_S=%g — "
                             "device call likely hung mid-run"
                             % total_timeout,
                }),
                flush=True,
            )
            os._exit(3)

    _bench_finished = threading.Event()
    threading.Thread(target=_watchdog, daemon=True).start()

    def _emit_headline():
        """The driver-contract line (LAST line printed). Called on the
        normal path and by the watchdog if a side workload hangs after
        the headline was already measured."""
        headline = _state.get("headline")
        if headline is None:
            return False
        print(
            json.dumps(
                {
                    "metric": "resnet50_train_images_per_sec_per_chip",
                    "value": headline["img_per_sec"],
                    "unit": "images/sec",
                    "vs_baseline": round(
                        headline["img_per_sec"] / BASELINE_IMG_PER_SEC, 4
                    ),
                    "mfu": headline["mfu"],
                    # measurement audit trail: raw chunk timings +
                    # spread; stable == spread <= BENCH_SPREAD_LIMIT on
                    # both step counts (r3 verdict falsifiability ask)
                    "stable": headline.get("timing", {}).get("stable"),
                    "timing": headline.get("timing"),
                    "workloads": _state["workloads"],
                }
            ),
            flush=True,
        )
        return True

    import jax

    jax.config.update(
        "jax_default_matmul_precision",
        os.environ["JAX_DEFAULT_MATMUL_PRECISION"],
    )
    # BENCH_PLATFORM=cpu runs the whole suite on the host backend (smoke
    # tests / outage days). The env var JAX_PLATFORMS alone is not enough
    # on this harness: the ambient sitecustomize imports jax at
    # interpreter boot with the axon platform latched, so re-select here.
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    jax.devices()  # force backend init under the watchdog
    init_done.set()
    from paddle_tpu.models.alexnet import alexnet
    from paddle_tpu.models.googlenet import googlenet
    from paddle_tpu.models.mobilenet import mobilenet_v1
    from paddle_tpu.models.resnet import resnet_imagenet
    from paddle_tpu.models.vgg import vgg16

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    # BENCH_STEPS="lo,hi" overrides the headline's two step counts (CPU
    # smoke tests use tiny counts; the TPU default stays 12,72)
    steps = tuple(
        int(s) for s in os.environ.get("BENCH_STEPS", "12,72").split(",")
    )

    quick = os.environ.get("BENCH_QUICK", "0") == "1"
    only = os.environ.get("BENCH_ONLY", "").split(",") if os.environ.get("BENCH_ONLY") else None
    # wall-clock budget for the SIDE workloads: on a slow-tunnel day the
    # driver must still get the headline line, so once the budget is
    # spent remaining side workloads are skipped (marked, not silent)
    # 3600 leaves room for the chunk-scaled workloads (probe chunks +
    # two extra compiles each) and the lm_large/lm_xl rows; worst case
    # headline (~300 s) + sides (3600 s) + offline refresh (2200 s) =
    # 6100 s, ~18 min under the 7200 s watchdog
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "3600"))
    workloads = _state["workloads"]

    def run(name, fn):
        """Side workloads only — the resnet50 headline runs outside run()
        so its failure fails the bench instead of being swallowed."""
        if only and name not in only:
            return
        if time.time() - t_start > budget_s:
            workloads[name] = {"skipped": "side-workload budget exhausted "
                                          "(BENCH_BUDGET_S=%g)" % budget_s}
        else:
            try:
                workloads[name] = fn()
            except Exception as e:  # a broken side workload must not kill the headline
                workloads[name] = {"error": "%s: %s" % (type(e).__name__, e)}
        rec = dict(workloads[name])
        rec["metric"] = name
        print(json.dumps(rec), flush=True)

    # headline FIRST (chip training throughput; device-resident data,
    # per-step cost by multi-step differencing — same semantics as
    # BENCH_r01/r02): a slow-tunnel day must not starve the driver-
    # contract number behind the side workloads. The line still prints
    # LAST (or from the watchdog on a hang).
    _state["headline"] = bench_image(
        "resnet50",
        lambda i, c: resnet_imagenet(i, class_dim=c, depth=50),
        batch,
        steps=steps,
        xla_cost=True,
    )
    workloads["resnet50"] = _state["headline"]
    # the side budget starts AFTER the headline: it belongs to the side
    # workloads alone
    t_start = time.time()

    # reference GPU baselines in img/s: AlexNet 334 ms/batch bs=128,
    # GoogLeNet 1149 ms/batch bs=128 (benchmark/README.md:37,50); no GPU
    # number exists in-tree for VGG16
    if not quick:
        run("alexnet", lambda: bench_image(
            "alexnet", lambda i, c: alexnet(i, c), 128, baseline_ips=383.2))
        run("googlenet", lambda: bench_image(
            "googlenet", lambda i, c: googlenet(i, c), 128, baseline_ips=111.4))
        run("vgg16", lambda: bench_image("vgg16", lambda i, c: vgg16(i, c), 64))
        run("mobilenet", lambda: bench_image(
            "mobilenet", lambda i, c: mobilenet_v1(i, c), 128))
        # the memory_optimize pass on the headline model: recompute
        # trades HBM residency for FLOPs — records the throughput cost
        run("resnet50_remat", lambda: bench_image(
            "resnet50", lambda i, c: resnet_imagenet(
                i, class_dim=c, depth=50), batch, remat=True))
        # serving-side: the reference's only published inference numbers
        # are the CPU MKL-DNN bs=16 table (IntelOptimizedPaddle.md:77-107)
        run("resnet50_infer", lambda: bench_image_infer(
            "resnet50",
            lambda i, c: resnet_imagenet(i, class_dim=c, depth=50),
            217.69))
        if os.environ.get("BENCH_INFER_ALL") == "1":
            # the rest of the reference inference table, opt-in to keep
            # the driver's side budget bounded. The reference's VGG row
            # is VGG-19 (IntelOptimizedPaddle.md:29,71), so the infer
            # bench runs the true vgg19 model against it.
            from paddle_tpu.models.vgg import vgg19

            run("vgg19_infer", lambda: bench_image_infer(
                "vgg19", lambda i, c: vgg19(i, c), 96.75))
            run("googlenet_infer", lambda: bench_image_infer(
                "googlenet", lambda i, c: googlenet(i, c), 600.94))
            run("alexnet_infer", lambda: bench_image_infer(
                "alexnet", lambda i, c: alexnet(i, c), 850.51))
        run("profiler_reconciliation", bench_profiler_reconciliation)
        run("lstm", bench_lstm)
        run("sparse_embedding", bench_sparse_embedding)
        run("flash_attention", bench_flash_attention)
        run("lm_decode", bench_lm_decode)
        # continuous-batching serving engine: many concurrent requests
        # through one compiled decode step (ISSUE 2); deterministic
        # Poisson trace — occupancy/compile counts meaningful offline,
        # tokens/s awaits an on-chip tunnel window
        run("serving_decode", bench_serving_decode)
        # prefix-cache acceptance: the SAME fixed-seed shared-header
        # trace with the pool off vs on — prefill-tokens-computed and
        # hit rate are deterministic offline, TTFT deltas on-chip
        run("serving_shared_prefix", bench_serving_shared_prefix)
        # paged KV block pool + speculative decoding (ISSUE 7): one
        # fixed KV budget, slab vs paged vs paged+spec — peak resident
        # slots, accept-rate, and output identity are deterministic
        # offline; the tokens/s contrast awaits an on-chip window
        run("serving_paged", bench_serving_paged)
        # fused paged-attention kernel (ISSUE 13): the same fixed-seed
        # shared-header trace gather vs fused — output identity, zero
        # _paged_view gathers, and the one-compiled-step discipline
        # are deterministic offline; the tokens/s contrast is only
        # meaningful compiled to Mosaic on-chip
        run("serving_paged_kernel", bench_serving_paged_kernel)
        # quantized serving (ISSUE 14): one fixed KV byte budget,
        # kv_quant none/int8/fp8 + weight-int8 — slots-resident,
        # bytes-per-resident-token, and the greedy-agreement quality
        # gate are deterministic offline; the tokens/s contrast (the
        # HBM-roofline win) awaits an on-chip window
        run("serving_quant", bench_serving_quant)
        # serving fleet (ISSUE 6): N replicas + kill drill on the same
        # fixed-seed shared-header trace — requests lost / duplicates /
        # failovers and the affinity-routing reuse contrast are
        # deterministic offline; tokens/s and speedup-vs-N×1 on-chip
        run("serving_fleet", bench_serving_fleet)
        # request-SLO / gray-failure drill (ISSUE 8): deadlines + one
        # replica gray-slowed mid-trace — expired (must be 0), demote/
        # probe/restore counts, journal-verified re-decode-zero resume,
        # and the p99 TTFT tail bound are deterministic offline
        run("serving_slo", bench_serving_slo)
        # disaggregated elastic fleet (ISSUE 11): the same burst trace
        # static vs elastic (tiers + autoscaler + one mid-trace weight
        # rollout + corrupted-candidate abort drill) — spawn/retire/
        # migration/rollout counts, the J009 version-fence audit, and
        # output identity are deterministic offline
        run("serving_elastic", bench_serving_elastic)
        # multi-tenant serving (ISSUE 12): tenant quotas + weighted
        # fair queueing + paged LoRA adapters + the zoo batch lane —
        # quota/fairness/adapter-paging/output-identity columns are
        # deterministic offline; per-tenant tok/s on-chip
        run("serving_multitenant", bench_serving_multitenant)
        # serving integrity (ISSUE 15): garble@ + flip@ silent-fault
        # drills — trip/quarantine exactly-once, output identity to
        # the uninjected run, and the J010 taint-fence audit are
        # deterministic offline; the overhead tokens/s column on-chip
        run("serving_integrity", bench_serving_integrity)
        # durable KV (ISSUE 16): checksummed block handoff at migration
        # + the crash-survivable tiered store — zero-recompute clean
        # handoff, counted kill-drill fallback, store-warmed restart,
        # output identity, and the J011 handoff-fence audit are
        # deterministic offline; the warm/cold TTFT contrast on-chip
        run("serving_kv_handoff", bench_serving_kv_handoff)
        # wire front door (ISSUE 18): open-loop Poisson load over real
        # sockets swept to the capacity knee + kill/disconnect drills —
        # stream bit-identity, typed sheds, exactly-once, and the
        # cancelled-terminal DFA audit are deterministic offline; every
        # timing is host wall-clock (CPU-honest shape, PERF.md)
        run("serving_frontdoor", bench_serving_frontdoor)
        # megabatch decode window (ISSUE 19): K-token compiled window +
        # async dispatch vs the K=1 sync baseline on one fixed-seed
        # Poisson trace — host-overhead fraction, steps/token, and
        # band uploads are deterministic offline; output identity and
        # the overhead drop hard-raise in-bench
        run("serving_megabatch", bench_serving_megabatch)
        run("transformer_lm", bench_transformer_lm)
        # larger-matmul flagship: dim=1024 keeps every matmul MXU-shaped
        # (the dim=512 row leaves lane headroom), so this is the MFU
        # headline for the LM family; beyond-reference, no 2018 baseline
        run("transformer_lm_large", lambda: bench_transformer_lm(
            B=8, T=2048, dim=1024, heads=16, layers_n=12))
        # dim=2048 runs the MXU near peak — 72% MFU measured r5; the
        # framework's utilization headline
        run("transformer_lm_xl", lambda: bench_transformer_lm(
            B=2, T=2048, dim=2048, heads=16, layers_n=16, steps=(2, 8)))

    # r3 batch sweep: 512 is past the knee (~2.4k img/s); 128 vs 256 is
    # within the tunnel's run-to-run noise (2.5-3.8k observed), so the
    # default stays at the historically comparable 128
    chunk_steps = int(os.environ.get("BENCH_CHUNK_STEPS", "25"))
    n_chunks = int(os.environ.get("BENCH_CHUNKS", "6"))

    # end-to-end input pipeline (recordio -> host decode -> h2d -> train):
    # on this harness it measures the tunnel, reported for honesty
    if not quick:
        # the pure-host loader-overlap row first (paddle_tpu/data): no
        # device work at all, so it is meaningful on every backend
        run("input_pipeline", bench_input_pipeline)
        # training sentinel (ISSUE 10): poisoned-chunk divergence ->
        # rollback-to-known-good -> quarantine -> finite curve identical
        # to the clean baseline, plus the corrupted-latest resume drill
        # — pure host work, deterministic on every backend
        run("training_sentinel", bench_training_sentinel)
        run("resnet50_input_pipeline",
            lambda: bench_resnet50_recordio(batch, chunk_steps, n_chunks))

    # refresh the offline v5e AOT artifact so it always matches the code
    # that produced this record (_run_offline itself skips when the
    # total budget is nearly spent: the artifact is committed, a stale
    # copy beats a watchdog kill)
    workloads["offline_artifact"] = _run_offline("post-run refresh")

    _bench_finished.set()
    _emit_headline()


if __name__ == "__main__":
    main()
