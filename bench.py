"""Benchmark: ResNet-50 training throughput on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: BASELINE.json north star, 1500 images/sec/chip (v5e).
Workload parity: benchmark/paddle/image/resnet.py with --job=time
(batch data-parallel train step, cross-entropy + momentum).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

BASELINE_IMG_PER_SEC = 1500.0


def main():
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    steps = int(os.environ.get("BENCH_STEPS", "100"))
    warmup = int(os.environ.get("BENCH_WARMUP", "1"))
    reps = int(os.environ.get("BENCH_REPS", "2"))

    # standard TPU mixed precision: f32 state, single-pass bf16 on the MXU
    os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "bfloat16")

    import jax

    jax.config.update(
        "jax_default_matmul_precision",
        os.environ["JAX_DEFAULT_MATMUL_PRECISION"],
    )
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models.resnet import resnet_imagenet

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        image = fluid.layers.data(name="image", shape=[3, 224, 224], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        predict = resnet_imagenet(image, class_dim=1000, depth=50)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg_cost = fluid.layers.mean(x=cost)
        opt = fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        opt.minimize(avg_cost)
    # mixed precision: bf16 forward/backward, f32 master weights
    main_prog.amp = os.environ.get("BENCH_AMP", "1") == "1"

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)

    rng = np.random.RandomState(0)
    img = rng.rand(batch, 3, 224, 224).astype(np.float32)
    lbl = rng.randint(0, 1000, (batch, 1)).astype(np.int64)
    feed = {"image": img, "label": lbl}

    # multi-step execution: `steps` train iterations inside one compiled
    # computation (host and data transfers out of the loop). The first
    # call compiles; timed calls replay the cached executable.
    for _ in range(max(1, warmup)):
        out = exe.run_repeated(main_prog, feed=feed, fetch_list=[avg_cost], steps=steps)
        assert np.isfinite(out[0]).all(), "non-finite loss in warmup: %r" % out[0]

    reps = max(1, reps)
    t0 = time.time()
    for _ in range(reps):
        out = exe.run_repeated(main_prog, feed=feed, fetch_list=[avg_cost], steps=steps)
        final_loss = float(np.ravel(out[0])[-1])  # forces full sync
    dt = time.time() - t0

    img_per_sec = batch * steps * reps / dt
    print(
        json.dumps(
            {
                "metric": "resnet50_train_images_per_sec_per_chip",
                "value": round(img_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
