"""PyDataProviderWrapper: the v1 (pre-PyDataProvider2) provider protocol
(reference python/paddle/trainer/PyDataProviderWrapper.py). v1 handlers
are `handler(obj, filename)` generators declared with slot-type objects;
this maps them onto the same reader factories the trainer consumes from
PyDataProvider2, so v1 provider modules keep working."""

from __future__ import annotations

__all__ = [
    "DenseSlot", "SlotType", "SparseNonValueSlot", "StringSlot",
    "SparseValueSlot", "IndexSlot", "PoolSize", "provider",
    "init_hook_wrapper",
]


class SlotType(object):
    """Base of the v1 slot declarations; carries the slot dimension."""

    def __init__(self, dim):
        self.dim = int(dim)

    def to_input_type(self):
        raise NotImplementedError


class DenseSlot(SlotType):
    def to_input_type(self):
        from ..v2.data_type import dense_vector

        return dense_vector(self.dim)


class SparseNonValueSlot(SlotType):
    def to_input_type(self):
        from ..v2.data_type import sparse_binary_vector

        return sparse_binary_vector(self.dim)


class SparseValueSlot(SlotType):
    def to_input_type(self):
        from ..v2.data_type import sparse_float_vector

        return sparse_float_vector(self.dim)


class IndexSlot(SlotType):
    def to_input_type(self):
        from ..v2.data_type import integer_value

        return integer_value(self.dim)


class StringSlot(SlotType):
    """Raw-string slot (the reference passed strings through untouched);
    no device lowering exists for it, so it stays a python object."""

    def to_input_type(self):
        return None


class PoolSize(object):
    """Max number of samples buffered by the provider."""

    def __init__(self, pool_size):
        self.size = pool_size


def default_init_hook(cls, *args, **kwargs):
    del cls, args, kwargs


def provider(slots=None, use_seq=False, should_shuffle=True, pool_size=1,
             can_over_batch_size=True, calc_batch_size=None, debug=False,
             init_hook=default_init_hook, profile_filename=None):
    """v1 decorator: `handler(obj, filename)` yields one sample per
    iteration, each a list/tuple with one entry per declared slot.
    Returns a factory `create(file_list, **kwargs)` producing a reader
    over all files — the same calling convention the trainer's provider
    loader uses for PyDataProvider2 modules."""

    def _wrapper(handler):
        def create(file_list, **kwargs):
            class _Obj(object):
                pass

            obj = _Obj()
            obj.logger = __import__("logging").getLogger("paddle")
            init_hook(obj, *([file_list] if file_list else []), **kwargs)
            slot_decl = slots
            if callable(slot_decl):
                slot_decl = slot_decl(
                    obj, *([file_list] if file_list else []), **kwargs
                )
            obj.slots = list(slot_decl or getattr(obj, "slots", []) or [])

            def reader():
                files = file_list if file_list else [None]
                for f in files:
                    yield from handler(obj, f)

            reader.settings = obj
            reader.input_types = [
                s.to_input_type() if isinstance(s, SlotType) else s
                for s in obj.slots
            ]
            return reader

        create.is_provider = True
        create.origin = handler
        return create

    return _wrapper


def init_hook_wrapper(func):
    """Mark `func` usable as an init_hook (kept for API parity)."""
    return func
