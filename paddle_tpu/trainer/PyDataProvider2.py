"""PyDataProvider2: the legacy data-provider protocol (reference
python/paddle/trainer/PyDataProvider2.py + its C++ consumer
gserver/dataproviders/PyDataProvider2.cpp).

A provider is `@provider(init_hook=...)` over a generator
`process(settings, file_list)` yielding per-instance tuples matching
`settings.slots`. The async double-buffering the C++ side did is served
by the same thread/queue machinery as paddle_tpu.v2.reader.buffered."""

from __future__ import annotations

from ..v2.data_type import (  # noqa: F401 — the legacy names
    dense_vector,
    dense_vector_sequence,
    integer_value,
    integer_value_sequence,
    sparse_binary_vector,
    sparse_float_vector,
)

__all__ = [
    "provider", "CacheType", "ProviderSettings",
    "dense_vector", "dense_vector_sequence", "integer_value",
    "integer_value_sequence", "sparse_binary_vector", "sparse_float_vector",
]


class CacheType(object):
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


class ProviderSettings(object):
    """Attribute bag the init_hook populates (height, width, slots, ...)."""

    def __init__(self):
        self.slots = None
        self.input_types = None

    @property
    def input_types_(self):
        return self.slots


def provider(input_types=None, init_hook=None, cache=CacheType.NO_CACHE,
             min_pool_size=-1, **provider_kwargs):
    """Decorator: fn(settings, file_list, ...) -> generator of instances."""

    def deco(fn):
        def create(file_list, **args):
            settings = ProviderSettings()
            if input_types is not None:
                settings.slots = list(input_types)
            if init_hook is not None:
                init_hook(settings, **args)
            if settings.slots is None and settings.input_types is not None:
                settings.slots = list(settings.input_types)

            def reader():
                yield from fn(settings, file_list)

            reader.settings = settings
            return reader

        create.is_provider = True
        create.origin = fn
        return create

    return deco
