"""Config-parser extension hooks (reference
python/paddle/trainer/config_parser_extension.py): extra data-source
constructors injected into config execution. The reference built
DataConfig protobufs; here a data-source declaration is a plain dict the
trainer's provider loader understands."""

from __future__ import annotations

__all__ = ["SimpleData", "get_config_funcs"]

g_config = None


def SimpleData(files=None, feat_dim=None, context_len=None,
               buffer_capacity=None):
    """Declare a 'simple' file-list data source of flat feature rows."""
    cfg = {
        "type": "simple",
        "files": files,
        "feat_dim": feat_dim,
    }
    if context_len is not None:
        cfg["context_len"] = context_len
    if buffer_capacity:
        cfg["buffer_capacity"] = buffer_capacity
    return cfg


def get_config_funcs(trainer_config):
    global g_config
    g_config = trainer_config
    return dict(SimpleData=SimpleData)
