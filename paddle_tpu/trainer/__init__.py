"""`paddle train`-style CLI (reference paddle/trainer/TrainerMain.cpp:32 +
Trainer.cpp): exec a trainer_config_helpers config, build the shared lazy
layer graph into a fluid Program, and run the train/time/test job.

Usage parity with benchmark/paddle/*/run.sh:

    python -m paddle_tpu.trainer --job=time --config=resnet.py \
        --use_gpu=True --trainer_count=1 --log_period=10 \
        --config_args=batch_size=64,layer_num=50
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys
import time
from typing import Any, Dict, List

import numpy as np

from .. import fluid
from .. import trainer_config_helpers as tch
from ..v2.topology import Topology
from ..v2.trainer import _convert_feed

__all__ = ["main", "run_config"]


def _parse_config_args(s: str) -> Dict[str, str]:
    out = {}
    for kv in (s or "").split(","):
        if not kv:
            continue
        k, _, v = kv.partition("=")
        out[k.strip()] = v.strip()
    return out


def _exec_config(path: str, config_args: Dict[str, str]):
    """Exec the config with the DSL star-imported (the reference runs
    configs through config_parser inside an embedded interpreter,
    TrainerConfigHelper.cpp -> PythonUtil)."""
    tch.reset_config(config_args)
    g: Dict[str, Any] = {"__name__": "__paddle_config__", "__file__": path}
    for name in tch.__all__:
        g[name] = getattr(tch, name)
    # verbatim reference configs open with
    # `from paddle.trainer_config_helpers import *` — alias the DSL under
    # that module path so they exec unchanged
    if "paddle.trainer_config_helpers" not in sys.modules:
        import importlib.util
        import types

        pkg = sys.modules.get("paddle")
        if pkg is None and importlib.util.find_spec("paddle") is None:
            # only claim the name when no real PaddlePaddle is installed
            pkg = types.ModuleType("paddle")
            sys.modules["paddle"] = pkg
        if pkg is not None:
            sys.modules["paddle.trainer_config_helpers"] = tch
            pkg.trainer_config_helpers = tch
    sys.path.insert(0, os.path.dirname(os.path.abspath(path)))
    try:
        with open(path) as f:
            code = compile(f.read(), path, "exec")
        exec(code, g)
    finally:
        sys.path.pop(0)
    return tch.get_config_state()


def _load_provider(data_sources, config_dir):
    spec = importlib.util.spec_from_file_location(
        data_sources["module"],
        os.path.join(config_dir, data_sources["module"] + ".py"),
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[data_sources["module"]] = mod
    spec.loader.exec_module(mod)
    create = getattr(mod, data_sources["obj"])
    file_list = []
    tl = data_sources.get("train_list")
    if tl and os.path.exists(tl):
        file_list = [l.strip() for l in open(tl) if l.strip()]
    return create(file_list, **data_sources["args"])


class _SimpleSlot(object):
    def __init__(self, type_, seq_type=0):
        self.type = type_
        self.seq_type = seq_type


def _simple_data_provider(data_nodes, n_samples=256, seed=0):
    """Reader + slots for TrainData(SimpleData(...)) configs (reference
    SimpleDataProvider): one dense slot per dense data layer, small
    random ids for Index (label) layers."""
    import numpy as np

    slots = []
    for node in data_nodes:
        t = node.attrs["type"]
        slots.append(_SimpleSlot(t.type, t.seq_type))

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n_samples):
            vals = []
            for node in data_nodes:
                t = node.attrs["type"]
                if t.type == 3:  # Index
                    vals.append(int(rng.randint(0, max(2, t.dim))))
                else:
                    vals.append(rng.randn(t.dim).astype("float32"))
            yield tuple(vals)

    return reader, slots


def _recordio_provider(paths, data_nodes):
    """Instances from recordio files through the native C++ prefetch
    queue (reference: the Go master dispatches RecordIO chunks;
    trainer-side records are pickled sample tuples as written by
    v2.dataset.common.convert). Slot order = data-layer declaration
    order, like every legacy provider."""
    import glob as _glob

    from ..v2.reader import creator

    if isinstance(paths, str):
        paths = paths.split(",")
    files, missing = [], []
    for p in paths:
        hits = sorted(_glob.glob(p))
        if hits:
            files.extend(hits)
        elif os.path.exists(p):
            files.append(p)
        else:
            missing.append(p)
    if missing:
        raise ValueError(
            "recordio provider: no files match %r" % (missing,)
        )

    slots = []
    for node in data_nodes:
        t = node.attrs["type"]
        slots.append(_SimpleSlot(t.type, t.seq_type))

    # non-tuple samples (single-data-layer configs) pass through
    # unchanged; _batches wraps them — same contract as every reader
    reader = creator.pickled_records(files, buf_size=256)
    return reader, slots


def _batches(reader, slots, data_nodes, batch_size):
    """Group provider instances into feed dicts (py_paddle
    DataProviderConverter's role). Provider slot order == data-layer
    declaration order, the legacy wiring."""
    for node, slot in zip(data_nodes, slots):
        node.attrs["type"].seq_type = slot.seq_type
        node.attrs["type"].type = slot.type
    buf = []
    for instance in reader():
        if not isinstance(instance, tuple):
            instance = (instance,)
        buf.append(instance)
        if len(buf) == batch_size:
            yield _convert_feed(buf, data_nodes, None)
            buf = []
    if buf:
        yield _convert_feed(buf, data_nodes, None)


def check_gradients(topo, cost_var, scope, exe, feed, eps=1e-3,
                    max_params=3, rtol=5e-2):
    """--job=checkgrad parity (reference TrainerMain.cpp:55,
    Trainer::checkGradient Trainer.cpp:303): compare analytic gradients
    (fetched grad vars) against central finite differences on the loss."""
    from ..fluid.backward import append_backward

    with fluid.program_guard(topo.main_program, topo.startup_program):
        params_grads = append_backward(cost_var)
    # smallest parameters first: cheap to perturb element-wise
    params_grads = sorted(
        params_grads, key=lambda pg: int(np.prod(pg[0].shape))
    )[:max_params]

    results = {}
    with fluid.executor.scope_guard(scope):
        for p, g in params_grads:
            (analytic,) = exe.run(
                topo.main_program, feed=feed, fetch_list=[g.name]
            )
            base = np.asarray(scope.get(p.name)).copy()
            flat = base.reshape(-1)
            idxs = np.linspace(0, flat.size - 1, min(4, flat.size)).astype(int)
            max_rel = 0.0
            for i in idxs:
                for sign, store in ((+1, "hi"), (-1, "lo")):
                    pert = base.copy().reshape(-1)
                    pert[i] += sign * eps
                    scope.set(p.name, pert.reshape(base.shape))
                    (c,) = exe.run(
                        topo.main_program, feed=feed, fetch_list=[cost_var]
                    )
                    if store == "hi":
                        hi = float(np.ravel(c)[0])
                    else:
                        lo = float(np.ravel(c)[0])
                numeric = (hi - lo) / (2 * eps)
                a = float(np.asarray(analytic).reshape(-1)[i])
                denom = max(abs(a), abs(numeric), 1e-6)
                max_rel = max(max_rel, abs(a - numeric) / denom)
            scope.set(p.name, base)
            results[p.name] = max_rel
            status = "ok" if max_rel < rtol else "FAIL"
            print("checkgrad %-40s max_rel=%.4g  %s" % (p.name, max_rel, status))
    return results


def resolve_config_outputs(state):
    """Resolve a config's output layers in place: legacy
    Outputs("name") forms map to nodes with clear errors (shared by
    run_config and utils/dump_config)."""
    if not state["outputs"] and state.get("output_names"):
        registry = state.get("layers_by_name") or {}
        missing = [n for n in state["output_names"] if n not in registry]
        if missing:
            raise ValueError(
                "Outputs(%r): no layer with that name in the config"
                % missing
            )
        state["outputs"] = [registry[n] for n in state["output_names"]]
    if not state["outputs"]:
        raise ValueError("config did not call outputs(...)")
    return state["outputs"]


def _write_gen_results(state, ids, lens, feed, config_dir,
                       gen_result_dir):
    """Write decoded id rows as dictionary words (reference
    SequenceTextPrinter: one "<source>\t<word word ...>" line per
    generated sequence). Relative dict paths resolve against the config
    dir and its ancestors; result files land in gen_result_dir when
    given (the reference tree is read-only here)."""
    written = []
    for spec in state.get("seqtext_printers", []):
        dict_path = spec.get("dict_file")
        words = None
        if dict_path:
            for base in (os.getcwd(), config_dir,
                         os.path.dirname(config_dir),
                         os.path.dirname(os.path.dirname(config_dir))):
                cand = os.path.normpath(os.path.join(base, dict_path))
                if os.path.exists(cand):
                    with open(cand) as f:
                        words = [w.strip() for w in f]
                    break
        result_path = spec.get("result_file") or "gen_result.txt"
        if gen_result_dir:
            result_path = os.path.join(
                gen_result_dir, os.path.basename(result_path)
            )
        src_raw = feed.get(spec.get("id_input"))
        src_flat = None if src_raw is None else np.ravel(src_raw)
        # beam decode emits beam_size rows PER SOURCE (source-major), so
        # row r belongs to source r // beam_width
        group = 1
        if src_flat is not None and src_flat.size \
                and ids.shape[0] % src_flat.size == 0:
            group = ids.shape[0] // src_flat.size
        with open(result_path, "w") as f:
            for row in range(ids.shape[0]):
                n = int(lens[row]) if row < len(lens) else ids.shape[1]
                toks = [int(t) for t in ids[row][:n]]
                text = " ".join(
                    words[t] if words and 0 <= t < len(words) else str(t)
                    for t in toks
                )
                si = row // group
                src = (
                    int(src_flat[si])
                    if src_flat is not None and si < src_flat.size
                    else si
                )
                f.write("%d\t%s\n" % (src, text))
        written.append(result_path)
    return written


def run_config(config_path, job="train", config_args=None, trainer_count=1,
               num_passes=1, log_period=10, use_gpu=None, save_dir=None,
               recordio=None, init_model_path=None, saving_period=1,
               gen_result_dir=None):
    """Programmatic entry (also used by tests). Returns summary dict."""
    state = _exec_config(config_path, config_args or {})
    resolve_config_outputs(state)
    settings = state["settings"]
    topo = Topology(state["outputs"])
    cost_var = topo.var_of[state["outputs"][0].name]

    mesh = None
    if trainer_count > 1:
        import jax

        from ..parallel.mesh import make_mesh

        n = min(trainer_count, jax.device_count())
        if n > 1:
            mesh = make_mesh({"data": n})

    # generation configs (rnn_gen.conf family): the output is decoded
    # sentence ids (the var carries a lens side-band), not a scalar cost
    gen_mode = bool(getattr(cost_var, "lens_name", None))
    with fluid.program_guard(topo.main_program, topo.startup_program):
        method = settings.get("learning_method")
        lr = settings.get("learning_rate", 1e-3)
        opt = (
            method.make(lr)
            if method is not None
            else fluid.optimizer.SGD(learning_rate=lr)
        )
        ma_spec = (settings.get("extra") or {}).get("model_average")
        pruning = None
        if job not in ("test", "checkgrad") and not gen_mode:
            opt.minimize(cost_var)
            # params with a legacy pruning update_hook get their static
            # mask built + re-applied after every update — BEFORE
            # ModelAverage so the EMA accumulates masked values
            pruning = fluid.optimizer.StaticPruning().build(
                topo.main_program, topo.startup_program
            )
            if ma_spec is not None:
                # settings(model_average=ModelAverage(...)): EMA slots
                # train inside the step and persist into every
                # checkpoint (live weights stay the resume state)
                fluid.optimizer.ModelAverage.from_spec(ma_spec).build(
                    topo.main_program
                )

    scope = fluid.executor.Scope()
    exe = fluid.Executor(fluid.CPUPlace(), mesh=mesh)
    with fluid.executor.scope_guard(scope):
        exe.run(topo.startup_program)
    if init_model_path:
        # resume/finetune (reference --init_model_path): a checkpoint
        # directory or a v2 Parameters tar
        if os.path.isdir(init_model_path):
            from ..distributed import load_checkpoint

            load_checkpoint(scope, init_model_path, strict=False)
            if pruning is not None and pruning.masks:
                # masks computed in startup reflected the now-discarded
                # random init; rebuild them from the LOADED weights
                with fluid.executor.scope_guard(scope):
                    pruning.recompute(scope)
        else:
            from ..v2.parameters import Parameters

            with open(init_model_path, "rb") as f:
                loaded = Parameters.from_tar(f)
            for name in loaded.names():
                scope.set(name, loaded.get(name))

    if recordio:
        provider_reader, slots = _recordio_provider(
            recordio, topo._data_layers
        )
    elif state.get("data_sources") is not None:
        provider_reader = _load_provider(
            state["data_sources"], os.path.dirname(os.path.abspath(config_path))
        )
        slots = provider_reader.settings.slots
    else:
        # legacy TrainData(SimpleData(...)) configs: synthesize dense/id
        # batches from the declared data layers (the framework's datasets
        # are hermetic synthetics; SimpleDataProvider parity)
        provider_reader, slots = _simple_data_provider(topo._data_layers)
    batch_size = settings.get("batch_size", 256)

    if gen_mode:
        all_ids, all_lens, all_src = [], [], {}
        with fluid.executor.scope_guard(scope):
            for feed in _batches(
                provider_reader, slots, topo._data_layers, batch_size
            ):
                ids, lens = exe.run(
                    topo.main_program, feed=feed,
                    fetch_list=[cost_var, cost_var.lens_name],
                )
                all_ids.append(np.asarray(ids))
                all_lens.append(np.ravel(np.asarray(lens)))
                for k, v in feed.items():
                    all_src.setdefault(k, []).append(
                        np.ravel(np.asarray(v[0] if isinstance(v, tuple)
                                            else v))
                    )
        # pad rows to one width before stacking (last batch may be short)
        width = max(a.shape[1] for a in all_ids)
        ids = np.concatenate([
            np.pad(a, ((0, 0), (0, width - a.shape[1])))
            for a in all_ids
        ])
        lens = np.concatenate(all_lens)
        merged_feed = {k: np.concatenate(v) for k, v in all_src.items()}
        written = _write_gen_results(
            state, ids, lens, merged_feed,
            os.path.dirname(os.path.abspath(config_path)), gen_result_dir,
        )
        return {
            "generated": int(ids.shape[0]),
            "ids": ids, "lens": lens,
            "result_files": written,
        }

    if job == "checkgrad":
        feed = next(
            _batches(provider_reader, slots, topo._data_layers, batch_size)
        )
        results = check_gradients(topo, cost_var, scope, exe, feed)
        worst = max(results.values()) if results else 0.0
        if worst > 5e-2:
            raise AssertionError("gradient check failed: %r" % results)
        return {"checkgrad": results}

    # AsyncSGD (reference TrainerConfig.proto OptimizationConfig.algorithm
    # = 'async_sgd'; legacy settings(algorithm='async_sgd')): on a mesh,
    # run the local-SGD redesign — buffer `async_sync_every` dense
    # batches and execute them as one run_async_local round
    # (parallel/async_sgd.py). Without a mesh (or with ragged feeds) the
    # loop below stays synchronous, which is the documented fallback.
    extra = settings.get("extra") or {}
    async_every = 0
    if extra.get("algorithm") == "async_sgd" and job == "train":
        if mesh is not None:
            async_every = max(int(extra.get("async_sync_every", 1)), 1)
        else:
            import warnings

            warnings.warn(
                "settings(algorithm='async_sgd') needs trainer_count>1 "
                "devices; running synchronously"
            )

    stats = dict(batches=0, cost=None, ms_per_batch=None, img_per_sec=None)
    times: List[float] = []
    state_box = {"async_every": async_every, "pass_id": 0}

    from ..distributed.fault_injection import FaultInjector

    # fresh injector per run: fault steps count THIS run's batches, not
    # a process-lifetime total
    fault = FaultInjector()

    def _record(costs, dt_per, skip_times=False):
        for cost in costs:
            stats["batches"] += 1
            stats["cost"] = cost
            if fault.active:
                # PADDLE_FAULT fixture: injected preemption/crash/stall
                # at this batch boundary (SURVEY 5.3)
                fault.tick()
            if stats["batches"] == 1:
                stats["first_cost"] = cost
            # the first batches include compilation; reference --job=time
            # also skips a warmup via log_period. Async rounds with a
            # fresh step-count signature compile too (skip_times).
            if stats["batches"] > min(log_period, 5) and not skip_times:
                times.append(dt_per)
            if stats["batches"] % log_period == 0:
                # reference Trainer.cpp log format — what
                # utils/plotcurve.py parses
                print(
                    "Pass=%d Batch=%d AvgCost=%.4f"
                    % (state_box["pass_id"], stats["batches"], cost)
                )

    def _run_sync(feed):
        (cost,) = exe.run(
            topo.main_program, feed=feed, fetch_list=[cost_var]
        )
        return [float(np.ravel(np.asarray(cost))[0])]

    def _async_fallback(msg):
        import warnings

        warnings.warn("async_sgd: %s; running synchronously" % msg)
        state_box["async_every"] = 0

    def _run_async_buffer(buf):
        """Stack buffered feeds [K, B, ...] and run one local-SGD round.
        Batches the mesh cannot shard evenly run synchronously instead
        (the sync executor replicates such feeds; shard_map cannot).
        Flags a compile-bearing run (fresh step-count signature) in
        state_box so its wall time stays out of the throughput stats."""
        n_data = mesh.shape["data"]
        first = next(iter(buf[0].values()))
        if np.shape(first)[0] % n_data:
            costs = []
            for f in buf:
                costs += _run_sync(f)
            return costs
        seen = state_box.setdefault("async_seen_steps", set())
        state_box["async_cold"] = len(buf) not in seen
        seen.add(len(buf))
        stacked = {
            k: np.stack([f[k] for f in buf]) for k in buf[0]
        }
        losses = exe.run_async_local(
            topo.main_program, feed=stacked, fetch_list=[cost_var],
            steps=len(buf), sync_every=len(buf),
        )[0]
        return [float(v) for v in np.ravel(np.asarray(losses))]

    import contextlib

    eval_avg_ctx = contextlib.nullcontext()
    if job == "test" and ma_spec is not None:
        # evaluate on the averaged weights a checkpoint carries (same
        # apply/restore the v2 tester does)
        _ma = fluid.optimizer.ModelAverage.from_spec(ma_spec).attach(scope)
        if _ma._param_names and _ma._steps_name:
            eval_avg_ctx = _ma.apply(scope=scope)

    from ..fluid.data_feeder import AsyncDeviceFeeder

    def _pass_feeds():
        """One pass's batches; the synchronous path double-buffers
        (reference DataProvider.h:249 DoubleBuffer): a background
        thread decodes + uploads batch k+1 while the device trains on
        batch k. The async-SGD path stacks host batches itself, so it
        reads the provider directly."""
        src = _batches(provider_reader, slots, topo._data_layers,
                       batch_size)
        if state_box["async_every"]:
            return src, None
        # multi-process meshes globalize feeds from host data — keep the
        # prefetch host-side there (decode still overlaps)
        from ..parallel.mesh import spans_processes

        up = not (mesh is not None and spans_processes(mesh))
        feeder = AsyncDeviceFeeder(src, capacity=2, upload=up)
        return feeder, feeder

    try:
        with eval_avg_ctx, fluid.executor.scope_guard(scope):
            for pass_id in range(num_passes):
                state_box["pass_id"] = pass_id
                buf = []
                feed_src, _feeder = _pass_feeds()
                state_box["feeder"] = _feeder
                for feed in feed_src:
                    t0 = time.time()
                    if state_box["async_every"] and any(
                        isinstance(v, tuple) for v in feed.values()
                    ):
                        # ragged (LoD) batches change shape per step; the
                        # documented fallback is the synchronous loop
                        for f in buf:
                            tf = time.time()
                            _record(_run_sync(f), time.time() - tf)
                        buf = []
                        _async_fallback("LoD feeds cannot stack across steps")
                        t0 = time.time()
                    if state_box["async_every"]:
                        costs = []
                        if buf and any(
                            np.shape(feed[k]) != np.shape(buf[0][k])
                            for k in feed
                        ):
                            # flush a buffer the new batch can't stack with
                            costs += _run_async_buffer(buf)
                            buf = []
                        buf.append(feed)
                        if len(buf) == state_box["async_every"]:
                            costs += _run_async_buffer(buf)
                            buf = []
                        if not costs:
                            continue
                    else:
                        costs = _run_sync(feed)
                    _record(costs, (time.time() - t0) / len(costs),
                            skip_times=state_box.pop("async_cold", False))
                if buf:
                    t0 = time.time()
                    costs = _run_async_buffer(buf)
                    _record(costs, (time.time() - t0) / len(costs),
                            skip_times=state_box.pop("async_cold", False))
                if save_dir and saving_period and \
                        job not in ("test", "checkgrad") and \
                        (pass_id + 1) % saving_period == 0:
                    from ..distributed import save_checkpoint_async

                    # async: the step loop pauses only for the host
                    # snapshot; CRC + disk + commit run in the background.
                    # One save in flight at a time.
                    prev = state_box.pop("ckpt_handle", None)
                    if prev is not None:
                        prev.result()
                    state_box["ckpt_handle"] = save_checkpoint_async(
                        scope, os.path.join(save_dir, "pass-%05d" % pass_id),
                        step=stats["batches"],
                    )
    finally:
        # a raise mid-pass must not leave the prefetch producer pinning
        # device buffers
        feeder = state_box.pop("feeder", None)
        if feeder is not None:
            feeder.close()
        # the in-flight async checkpoint must commit even when a pass
        # raises (durability parity with the old synchronous save);
        # result() also re-raises any writer error
        pending = state_box.pop("ckpt_handle", None)
        if pending is not None:
            pending.result()
    if times:
        stats["ms_per_batch"] = 1000.0 * float(np.mean(times))
        stats["img_per_sec"] = batch_size / float(np.mean(times))
    if job == "time" and times:
        print(
            "Time: %.2f ms/batch (%.1f samples/sec)"
            % (stats["ms_per_batch"], stats["img_per_sec"])
        )
    if save_dir and not (
        saving_period and num_passes % saving_period == 0
        and job not in ("test", "checkgrad")
    ):
        # root-level final save only when the last pass did NOT already
        # land in save_dir/pass-NNNNN (avoids double checkpoint I/O)
        from ..distributed import save_checkpoint

        save_checkpoint(scope, save_dir, step=stats["batches"])
    return stats


def main(argv=None):
    # honor a JAX_PLATFORMS request even when an ambient sitecustomize
    # imported jax at interpreter boot with another platform latched
    # (same re-application the driver hooks do)
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        try:
            if jax.config.jax_platforms != want:
                jax.config.update("jax_platforms", want)
        except Exception as e:
            print(
                "warning: could not apply JAX_PLATFORMS=%s (%s); "
                "continuing on the ambient platform" % (want, e),
                file=sys.stderr,
            )
    p = argparse.ArgumentParser(prog="paddle_tpu.trainer")
    p.add_argument("command", nargs="?", default="train")
    p.add_argument("--config", required=True)
    p.add_argument("--job", default="train",
                   choices=["train", "time", "test", "checkgrad"])
    p.add_argument("--config_args", default="")
    p.add_argument("--trainer_count", type=int, default=1)
    p.add_argument("--num_passes", type=int, default=1)
    p.add_argument("--log_period", type=int, default=10)
    p.add_argument("--test_period", type=int, default=0)
    p.add_argument("--use_gpu", default=None)
    p.add_argument("--save_dir", default=None)
    p.add_argument("--init_model_path", default=None,
                   help="checkpoint dir or Parameters tar to start from")
    p.add_argument("--saving_period", type=int, default=1,
                   help="save into save_dir/pass-NNNNN every N passes")
    p.add_argument("--gen_result_dir", default=None,
                   help="redirect generation result files into this "
                        "directory (the config's own paths may be "
                        "read-only)")
    p.add_argument("--recordio", default=None,
                   help="comma-separated recordio files/globs of pickled "
                        "sample tuples; feeds training through the native "
                        "prefetch queue")
    args = p.parse_args(argv)
    run_config(
        args.config,
        job=args.job,
        config_args=_parse_config_args(args.config_args),
        trainer_count=args.trainer_count,
        num_passes=args.num_passes,
        log_period=args.log_period,
        use_gpu=args.use_gpu,
        save_dir=args.save_dir,
        recordio=args.recordio.split(",") if args.recordio else None,
        init_model_path=args.init_model_path,
        saving_period=args.saving_period,
        gen_result_dir=args.gen_result_dir,
    )
