"""Config parser entry points (reference
python/paddle/trainer/config_parser.py:4350 parse_config — 4.4k LoC of
protobuf assembly driven from an embedded interpreter). Here configs
exec against the trainer_config_helpers DSL and lower to a fluid
Program; parse_config returns that lowered form with the recorded
optimizer settings, and parse_config_and_serialize emits the JSON wire
schema the native runtime loads."""

from __future__ import annotations

import logging
from typing import Any, Dict

__all__ = [
    "logger", "parse_config", "parse_config_and_serialize",
]

logger = logging.getLogger("paddle")
logger.setLevel(logging.INFO)


class ParsedConfig(object):
    """What parse_config returns: the lowered model (Topology with
    main/startup programs) plus the optimizer settings dict — the
    TPU-native equivalents of the reference's ModelConfig/
    OptimizationConfig protobuf pair."""

    def __init__(self, topology, settings):
        self.topology = topology
        self.settings = settings
        # protobuf-era aliases
        self.model_config = topology
        self.opt_config = settings


def parse_config(trainer_config, config_arg_str=""):
    """trainer_config: a config file path (.py/.conf) or a callable.
    config_arg_str: 'key=value,key2=value2' overrides (reference
    get_config_arg)."""
    from paddle_tpu.trainer import (
        _exec_config,
        _parse_config_args,
        resolve_config_outputs,
    )
    from paddle_tpu.v2.topology import Topology
    import paddle_tpu.trainer_config_helpers as tch

    args = _parse_config_args(config_arg_str or "")
    if callable(trainer_config):
        tch.reset_config(args)
        trainer_config()
        state = tch.get_config_state()
    else:
        state = _exec_config(str(trainer_config), args)
    topology = Topology(resolve_config_outputs(state))
    return ParsedConfig(topology, state.get("settings", {}))


def parse_config_and_serialize(trainer_config, config_arg_str=""):
    """The serialized (JSON wire schema) form of the parsed config."""
    from paddle_tpu.fluid.core.serialization import dumps_program

    parsed = parse_config(trainer_config, config_arg_str)
    return dumps_program(parsed.topology.main_program)
