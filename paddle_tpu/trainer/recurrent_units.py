"""paddle.trainer.recurrent_units (reference
python/paddle/trainer/recurrent_units.py): the pre-DSL LSTM/GRU
recurrent-unit helpers some legacy configs import. Each delegates to
the modern composite helpers (trainer_config_helpers/networks.py),
which build the identical step graph (input+recurrent projection, step
layer, state memory via get_output_layer)."""

from __future__ import annotations

from ..trainer_config_helpers import (
    LinearActivation,
    ParamAttr,
    SigmoidActivation,
    TanhActivation,
    networks,
)

__all__ = [
    "LstmRecurrentUnit", "LstmRecurrentUnitNaive",
    "LstmRecurrentLayerGroup",
    "GatedRecurrentUnit", "GatedRecurrentUnitNaive",
    "GatedRecurrentLayerGroup",
]

_ACTS = {
    "tanh": TanhActivation,
    "sigmoid": SigmoidActivation,
    "linear": LinearActivation,
    "": LinearActivation,
    None: LinearActivation,
}


def _act(name):
    if not isinstance(name, (str, type(None))):
        return name  # already an activation object
    try:
        return _ACTS[name]()
    except KeyError:
        raise ValueError("unknown active_type %r" % (name,))


def _one_input(inputs):
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if len(ins) != 1:
        raise NotImplementedError(
            "recurrent_units helpers here take ONE input layer (the "
            "reference's projection lists pre-date mixed_layer; project "
            "and sum inputs beforehand)"
        )
    return ins[0]


def LstmRecurrentUnit(name, size, active_type, state_active_type,
                      gate_active_type, inputs, para_prefix=None,
                      error_clipping_threshold=0, out_memory=None):
    """One LSTM step (use inside a recurrent_group step function)."""
    return networks.lstmemory_unit(
        input=_one_input(inputs), out_memory=out_memory, name=name,
        size=size, act=_act(active_type), gate_act=_act(gate_active_type),
        state_act=_act(state_active_type),
        param_attr=ParamAttr(name=(para_prefix or name) + "_w"),
    )


LstmRecurrentUnitNaive = LstmRecurrentUnit


def LstmRecurrentLayerGroup(name, size, active_type, state_active_type,
                            gate_active_type, inputs, para_prefix=None,
                            error_clipping_threshold=0, seq_reversed=False):
    """LSTM over a sequence (recurrent_group form)."""
    return networks.lstmemory_group(
        input=_one_input(inputs), size=size, name=name,
        reverse=seq_reversed, act=_act(active_type),
        gate_act=_act(gate_active_type),
        state_act=_act(state_active_type),
        param_attr=ParamAttr(name=(para_prefix or name) + "_w"),
    )


def GatedRecurrentUnit(name, size, active_type, gate_active_type, inputs,
                       para_prefix=None, error_clipping_threshold=0,
                       out_memory=None):
    """One GRU step (use inside a recurrent_group step function); the
    input must already be the 3*size projection, like the reference's
    mixed input_proj."""
    return networks.gru_unit(
        input=_one_input(inputs), memory_boot=out_memory, size=size,
        name=name, act=_act(active_type),
        gate_act=_act(gate_active_type),
        gru_param_attr=ParamAttr(name=(para_prefix or name) + "_w"),
    )


GatedRecurrentUnitNaive = GatedRecurrentUnit


def GatedRecurrentLayerGroup(name, size, active_type, gate_active_type,
                             inputs, para_prefix=None,
                             error_clipping_threshold=0,
                             seq_reversed=False):
    """GRU over a sequence (recurrent_group form); input is the 3*size
    projection sequence."""
    return networks.gru_group(
        input=_one_input(inputs), size=size, name=name,
        reverse=seq_reversed, act=_act(active_type),
        gate_act=_act(gate_active_type),
        gru_param_attr=ParamAttr(name=(para_prefix or name) + "_w"),
    )
