"""Plot training curves from trainer logs (reference
python/paddle/utils/plotcurve.py). Parses `Pass=N ... Key=V` lines the
CLI emits, one curve per requested key; test-pass lines (`Test
samples=...`) plot as companion curves."""

from __future__ import annotations

import argparse
import re
import sys

import numpy as np

__all__ = ["plot_paddle_curve", "parse_log", "main"]


def parse_log(keys, inputfile):
    """Extract ([pass, key1, key2...] rows, test rows) from a log
    stream."""
    pass_pat = r"Pass=([0-9]*)"
    test_pat = r"Test samples=([0-9]*)"
    for k in keys:
        pass_pat += r".*?%s=([0-9e\-\.]*)" % re.escape(k)
        test_pat += r".*?%s=([0-9e\-\.]*)" % re.escape(k)
    cp, ct = re.compile(pass_pat), re.compile(test_pat)
    data, test_data = [], []
    for line in inputfile:
        m = cp.search(line)
        if m:
            data.append([float(x) for x in m.groups()])
        mt = ct.search(line)
        if mt:
            test_data.append([float(x) for x in mt.groups()])
    return np.asarray(data), np.asarray(test_data)


def plot_paddle_curve(keys, inputfile, outputfile, format="png",
                      show_fig=False):
    """Plot the requested keys over passes; writes `outputfile`."""
    keys = list(keys) or ["AvgCost"]
    x, x_test = parse_log(keys, inputfile)
    if x.shape[0] <= 0:
        sys.stderr.write("No data to plot. Exiting!\n")
        return
    import matplotlib

    matplotlib.use("Agg")  # headless-safe
    import matplotlib.pyplot as pyplot
    from matplotlib import cm

    m = len(keys) + 1
    # test lines are one per pass while train lines come every
    # log_period batches, so test curves get their own x coordinates
    if x_test.shape[0] == x.shape[0]:
        xs_test = x[:, 0]
    else:
        # one test line per pass vs several train lines per pass: align
        # test points to the actual pass ids
        passes = np.unique(x[:, 0])
        xs_test = (
            passes[: x_test.shape[0]]
            if x_test.shape[0] <= passes.shape[0]
            else np.arange(x_test.shape[0])
        )
    for i in range(1, m):
        pyplot.plot(
            x[:, 0], x[:, i],
            color=cm.jet(1.0 * (i - 1) / (2 * m)), label=keys[i - 1],
        )
        if x_test.shape[0] > 0:
            pyplot.plot(
                xs_test, x_test[:, i],
                color=cm.jet(1.0 - 1.0 * (i - 1) / (2 * m)),
                label="Test " + keys[i - 1],
            )
    pyplot.xlabel("number of epoch")
    pyplot.legend(loc="best")
    if show_fig:
        pyplot.show()
    pyplot.savefig(outputfile, format=format, bbox_inches="tight")
    pyplot.clf()


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Plot curves from a trainer log."
    )
    parser.add_argument("-i", "--input", default=None,
                        help="log file (default stdin)")
    parser.add_argument("-o", "--output", default=None,
                        help="figure file (default stdout)")
    parser.add_argument("--format", default="png")
    parser.add_argument("key", nargs="*", help="score keys (default AvgCost)")
    args = parser.parse_args(argv)
    inp = open(args.input) if args.input else sys.stdin
    out = args.output or sys.stdout.buffer
    try:
        plot_paddle_curve(args.key, inp, out, format=args.format)
    finally:
        if args.input:
            inp.close()


if __name__ == "__main__":
    main()
