"""Classic image preprocessing helpers (reference
python/paddle/utils/image_util.py): resize/crop/flip/mean-subtract in the
CHW float layout the image models feed. Implemented on numpy + PIL; the
device never sees these — they run host-side in the input pipeline."""

from __future__ import annotations

import io

import numpy as np

__all__ = [
    "resize_image", "flip", "crop_img", "decode_jpeg", "preprocess_img",
    "load_meta", "load_image", "oversample", "ImageTransformer",
]


def _pil():
    from PIL import Image

    return Image


def resize_image(img, target_size):
    """Resize a PIL image so its SHORT side equals target_size, keeping
    aspect ratio (the standard eval-pipeline resize)."""
    w, h = img.size
    if w < h:
        size = (target_size, int(round(h * target_size / float(w))))
    else:
        size = (int(round(w * target_size / float(h))), target_size)
    return img.resize(size, _pil().BILINEAR)


def flip(im):
    """Horizontal mirror of a CHW (color) or HW (gray) array."""
    im = np.asarray(im)
    return im[..., ::-1].copy()


def crop_img(im, inner_size, color=True, test=True):
    """Crop a CHW/HW array to inner_size x inner_size: center crop in
    test mode, random crop + random mirror in train mode."""
    im = np.asarray(im)
    h, w = im.shape[-2], im.shape[-1]
    if test:
        top, left = (h - inner_size) // 2, (w - inner_size) // 2
        mirror = False
    else:
        top = np.random.randint(0, h - inner_size + 1)
        left = np.random.randint(0, w - inner_size + 1)
        mirror = bool(np.random.randint(0, 2))
    out = im[..., top:top + inner_size, left:left + inner_size]
    return flip(out) if mirror else out.copy()


def decode_jpeg(jpeg_string):
    """JPEG bytes -> CHW (color) or HW (gray) uint8 array."""
    img = _pil().open(io.BytesIO(jpeg_string))
    arr = np.asarray(img)
    if arr.ndim == 3:
        arr = arr.transpose(2, 0, 1)
    return arr


def preprocess_img(im, img_mean, crop_size, is_train, color=True):
    """Crop (+train-time mirror) then subtract the mean image; returns
    float32 flattened to the layer's input layout."""
    cropped = crop_img(im, crop_size, color=color, test=not is_train)
    out = cropped.astype(np.float32) - np.asarray(img_mean, np.float32).reshape(
        cropped.shape
    )
    return out.ravel()

def load_meta(meta_path, mean_img_size, crop_size, color=True):
    """Load a dataset meta file (the pickled dict
    ImageClassificationDatasetCreater writes, flattened mean image under
    'data_mean') and center-crop the mean to crop_size."""
    import pickle

    with open(meta_path, "rb") as f:
        meta = pickle.load(f)
    mean = np.asarray(meta["data_mean"], np.float32)
    if color:
        mean = mean.reshape(3, mean_img_size, mean_img_size)
    else:
        mean = mean.reshape(mean_img_size, mean_img_size)
    return crop_img(mean, crop_size, color=color, test=True)


def load_image(img_path, is_color=True):
    """Load an image file as a PIL image in RGB (or L) mode."""
    img = _pil().open(img_path)
    return img.convert("RGB" if is_color else "L")


def oversample(img, crop_dims):
    """10-crop oversampling (reference image_util.py:144): the 4 corners
    + center, plus their mirrors, for HWC input images; returns
    [10*N, ch, cw, C]-style stacked crops for a [N, H, W, C] batch."""
    img = np.asarray(img)
    if img.ndim == 3:
        img = img[None]
    n, h, w, c = img.shape
    ch, cw = int(crop_dims[0]), int(crop_dims[1])
    tops = [0, 0, h - ch, h - ch, (h - ch) // 2]
    lefts = [0, w - cw, 0, w - cw, (w - cw) // 2]
    crops = []
    for im in img:
        views = [
            im[t:t + ch, l:l + cw] for t, l in zip(tops, lefts)
        ]
        crops.extend(views)
        crops.extend(v[:, ::-1] for v in views)
    return np.stack(crops)


class ImageTransformer:
    """Configurable HWC<->CHW, channel-swap, mean-subtract, scale pipeline
    (reference image_util.py:183)."""

    def __init__(self, transpose=None, channel_swap=None, mean=None,
                 is_color=True):
        self.transpose = transpose
        self.channel_swap = channel_swap
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        self.is_color = is_color
        self.scale = None

    def set_transpose(self, order):
        self.transpose = order

    def set_channel_swap(self, order):
        self.channel_swap = order

    def set_scale(self, scale):
        self.scale = scale

    def set_mean(self, mean):
        self.mean = None if mean is None else np.asarray(mean, np.float32)

    def transformer(self, data):
        data = np.asarray(data, np.float32)
        if self.transpose is not None:
            data = data.transpose(self.transpose)
        if self.channel_swap is not None:
            data = data[np.asarray(self.channel_swap)]
        if self.scale is not None:
            data = data * self.scale
        if self.mean is not None:
            mean = self.mean
            if mean.ndim == 1 and data.ndim == 3:
                mean = mean.reshape(-1, 1, 1)
            data = data - mean
        return data
