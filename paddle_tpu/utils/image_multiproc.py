"""Multi-process image preprocessing (reference
python/paddle/utils/image_multiproc.py): decode + resize + crop/flip +
mean-subtract in a worker pool so the host input pipeline keeps up with
the device. cv2 is optional (not in this image); the PIL path is the
default transformer."""

from __future__ import annotations

import io

import numpy as np

from .image_util import ImageTransformer

__all__ = ["PILTransformer", "MultiProcessImageTransformer"]


class PILTransformer(ImageTransformer):
    """Decode (bytes or file), short-side resize, crop/flip, normalize
    — one sample at a time, picklable for worker processes."""

    def __init__(self, min_size=None, crop_size=None, transpose=(2, 0, 1),
                 channel_swap=None, mean=None, is_train=True, is_color=True):
        ImageTransformer.__init__(self, transpose, channel_swap, mean,
                                  is_color)
        self.min_size = min_size
        self.crop_size = crop_size
        self.is_train = is_train

    def _load(self, data):
        from PIL import Image

        if isinstance(data, (bytes, bytearray)):
            img = Image.open(io.BytesIO(bytes(data)))
        else:
            img = Image.open(data)
        return img.convert("RGB" if self.is_color else "L")

    def resize(self, im, min_size):
        from .image_util import resize_image

        return resize_image(im, min_size)

    def crop_and_flip(self, arr):
        h, w = arr.shape[:2]
        if self.is_train:
            top = np.random.randint(0, h - self.crop_size + 1)
            left = np.random.randint(0, w - self.crop_size + 1)
        else:
            top, left = (h - self.crop_size) // 2, (w - self.crop_size) // 2
        arr = arr[top:top + self.crop_size, left:left + self.crop_size]
        if self.is_train and np.random.randint(0, 2):
            arr = arr[:, ::-1]
        return arr

    def transform(self, im):
        arr = np.asarray(im)
        if self.crop_size:
            arr = self.crop_and_flip(arr)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return self.transformer(arr).astype(np.float32)

    def load_image_from_string(self, data):
        im = self._load(data)
        if self.min_size:
            im = self.resize(im, self.min_size)
        return self.transform(im)

    load_image_from_file = load_image_from_string

    def __call__(self, data, label):
        return self.load_image_from_string(data), label


class MultiProcessImageTransformer(object):
    """Fan the per-sample transformer over a multiprocessing pool;
    `run(data, labels)` yields transformed (image, label) pairs as they
    complete (reference image_multiproc.py MultiProcessImageTransformer)."""

    def __init__(self, procnum=10, resize_size=None, crop_size=None,
                 transpose=(2, 0, 1), channel_swap=None, mean=None,
                 is_train=True, is_color=True):
        import multiprocessing

        self.procnum = procnum
        self.transformer = PILTransformer(
            resize_size, crop_size, transpose, channel_swap, mean,
            is_train, is_color,
        )
        self.pool = multiprocessing.Pool(procnum)

    def run(self, data, label):
        return self.pool.imap(
            _TransformJob(self.transformer), zip(data, label)
        )


class _TransformJob(object):
    """Picklable callable for pool workers."""

    def __init__(self, transformer):
        self.transformer = transformer

    def __call__(self, pair):
        data, label = pair
        return self.transformer(data, label)
