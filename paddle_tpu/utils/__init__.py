"""Flags, logging and scoped timers (reference paddle/utils: Flags.cpp
gflags registry, Logging.h glog shim, Stat.h REGISTER_TIMER RAII timers
aggregated in a global StatSet, printed per pass)."""

from .flags import DEFINE_bool, DEFINE_float, DEFINE_int, DEFINE_string, FLAGS
from .logging import get_logger, vlog
from .stat import StatSet, global_stats, timer

__all__ = [
    "FLAGS", "DEFINE_bool", "DEFINE_int", "DEFINE_float", "DEFINE_string",
    "get_logger", "vlog", "timer", "StatSet", "global_stats",
]
