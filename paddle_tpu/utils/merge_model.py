"""Merge a v2 net config + trained parameters into a deployable
inference bundle (reference python/paddle/utils/merge_model.py
merge_v2_model, which packed ModelConfig proto + tar'd params for the
capi runner).

Here the bundle is the JSON program + npy parameters directory that
both `fluid.io.load_inference_model` and the dependency-free C++
runner (`native/inference.cc`) consume.

Usage:
    from paddle_tpu.utils.merge_model import merge_v2_model
    net = softmax_output_layer(...)          # a v2/DSL layer node
    merge_v2_model(net, "trained.tar", "./deploy_model")
"""

from __future__ import annotations

__all__ = ["merge_v2_model"]


def merge_v2_model(net, param_file, output_dir):
    """net: the network's output layer node; param_file: a Parameters
    tar (v2 wire format) path or file object; output_dir: bundle
    directory (created)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.v2.parameters import Parameters
    from paddle_tpu.v2.topology import Topology

    topo = Topology([net])
    if hasattr(param_file, "read"):
        loaded = Parameters.from_tar(param_file)
    else:
        with open(param_file, "rb") as f:
            loaded = Parameters.from_tar(f)

    scope = fluid.executor.Scope()
    with fluid.executor.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(topo.startup_program)
        # every persistable (parameters AND batch-norm moving stats —
        # Parameters.to_tar writes both) must come from the tar
        net_persist = {
            v.name
            for v in topo.main_program.list_vars()
            if v.persistable
        }
        tar_names = set(loaded.names())
        missing = sorted(net_persist - tar_names)
        if missing:
            raise ValueError(
                "parameter tar does not cover the net: missing %r "
                "(tar has %r) — a bundle with random weights would be "
                "silently wrong" % (missing, sorted(tar_names))
            )
        for name in tar_names & net_persist:
            scope.set(name, loaded.get(name))
        out_var = topo.var_of[net.name]
        feed_names = [n.name for n in topo._data_layers]
        fluid.io.save_inference_model(
            output_dir, feed_names, [out_var], exe,
            main_program=topo.main_program,
        )
    return output_dir
