"""Predefined networks for image classification (reference
python/paddle/utils/predefined_net.py). The originals were written in
the pre-DSL v1 config idiom (`img_conv_bn_pool`, `Settings`,
`end_of_network`); here they build through the trainer_config_helpers
DSL — same topologies, same entry points, modern config plumbing."""

from __future__ import annotations

import os

import numpy as np

from .. import trainer_config_helpers as tch

__all__ = [
    "image_data", "get_extra_layer_attr", "image_data_layers",
    "simple_conv_net", "vgg_conv_net", "vgg16_conv_net", "small_vgg",
    "training_settings",
]


def image_data(data_dir, processed_image_size, overwrite=False, color=True,
               train_list="batches/train.list",
               test_list="batches/test.list",
               meta_file="batches/batches.meta", use_jpeg=1):
    """Declare the batched image dataset written by
    ImageClassificationDatasetCreater as this config's data source."""
    import pickle

    meta_path = os.path.join(data_dir, meta_file)
    with open(meta_path, "rb") as f:
        conf = pickle.load(f)
    args = {
        "meta": meta_path,
        "mean_img_size": conf["mean_image_size"],
        "img_size": processed_image_size,
        "num_classes": conf["num_classes"],
        "use_jpeg": use_jpeg != 0,
        "color": "color" if conf["color"] else "gray",
    }
    tch.define_py_data_sources2(
        os.path.join(data_dir, train_list),
        os.path.join(data_dir, test_list),
        module="image_provider",
        obj="processData",
        args=args,
    )
    return {
        "image_size": processed_image_size,
        "num_classes": conf["num_classes"],
        "is_color": conf["color"],
    }


def get_extra_layer_attr(drop_rate):
    if not drop_rate:
        return None
    return tch.ExtraLayerAttribute(drop_rate=drop_rate)


def image_data_layers(image_size, num_classes, is_color=False,
                      is_predict=False):
    """The input(+label) data layers of an image classifier."""
    channels = 3 if is_color else 1
    data_input = tch.data_layer("input", image_size * image_size * channels)
    if is_predict:
        return data_input, None, channels
    label_input = tch.data_layer("label", 1)
    return data_input, label_input, channels


def _conv_bn_pool(name, input, filter_size, num_channel, num_filters):
    conv = tch.img_conv_layer(
        input=input, filter_size=filter_size, num_channels=num_channel,
        num_filters=num_filters, stride=1, padding=0,
        act=tch.LinearActivation(), name="%s_conv" % name,
    )
    bn = tch.batch_norm_layer(
        input=conv, act=tch.ReluActivation(), name="%s_bn" % name
    )
    return tch.img_pool_layer(
        input=bn, pool_size=3, stride=2, name="%s_pool" % name
    )


def simple_conv_net(data_conf, is_color=False, is_predict=False):
    """Two conv+bn+pool groups, one hidden fc with dropout, softmax
    output (the reference's MNIST-scale net)."""
    image_size = data_conf["image_size"]
    num_classes = data_conf["num_classes"]
    data_input, label_input, channels = image_data_layers(
        image_size, num_classes, is_color, is_predict
    )
    g1 = _conv_bn_pool("g1", data_input, 5, channels, 32)
    g2 = _conv_bn_pool("g2", g1, 5, 32, 64)
    fc3 = tch.fc_layer(
        input=g2, size=500, act=tch.ReluActivation(), name="fc3"
    )
    fc3_dropped = tch.dropout_layer(input=fc3, dropout_rate=0.5)
    output = tch.fc_layer(
        input=fc3_dropped, size=num_classes,
        act=tch.SoftmaxActivation(), name="output",
    )
    if is_predict:
        tch.outputs(output)
        return output
    cost = tch.classification_cost(input=output, label=label_input)
    tch.outputs(cost)
    return cost


def _vgg_group(name, input, num_channel, num_filters, n_convs, drop_rate):
    h = input
    for i in range(n_convs):
        h = tch.img_conv_layer(
            input=h, filter_size=3, padding=1,
            num_channels=num_channel if i == 0 else num_filters,
            num_filters=num_filters, act=tch.ReluActivation(),
            name="%s_conv%d" % (name, i),
            layer_attr=get_extra_layer_attr(drop_rate),
        )
    return tch.img_pool_layer(
        input=h, pool_size=2, stride=2, name="%s_pool" % name
    )


def vgg_conv_net(image_size, num_classes, num_layers, is_color=False,
                 is_predict=False):
    """VGG-style stack: conv groups doubling channels, two dropout fc
    layers, softmax output. num_layers 16 -> groups (2,2,3,3,3)."""
    depth_conf = {
        11: (1, 1, 2, 2, 2),
        13: (2, 2, 2, 2, 2),
        16: (2, 2, 3, 3, 3),
        19: (2, 2, 4, 4, 4),
    }
    groups = depth_conf.get(num_layers)
    if groups is None:
        raise ValueError("unsupported vgg depth %r" % num_layers)
    data_input, label_input, channels = image_data_layers(
        image_size, num_classes, is_color, is_predict
    )
    h = data_input
    filters = [64, 128, 256, 512, 512]
    ch = channels
    for gi, (n_convs, nf) in enumerate(zip(groups, filters)):
        h = _vgg_group("vgg_g%d" % gi, h, ch, nf, n_convs,
                       0.0 if gi < 2 else 0.1)
        ch = nf
    fc1 = tch.fc_layer(input=h, size=512, act=tch.ReluActivation())
    fc1 = tch.dropout_layer(input=fc1, dropout_rate=0.5)
    fc2 = tch.fc_layer(input=fc1, size=512, act=tch.ReluActivation())
    fc2 = tch.dropout_layer(input=fc2, dropout_rate=0.5)
    output = tch.fc_layer(
        input=fc2, size=num_classes, act=tch.SoftmaxActivation(),
        name="output",
    )
    if is_predict:
        tch.outputs(output)
        return output
    cost = tch.classification_cost(input=output, label=label_input)
    tch.outputs(cost)
    return cost


def vgg16_conv_net(image_size, num_classes, is_color=True,
                   is_predict=False):
    return vgg_conv_net(image_size, num_classes, 16, is_color, is_predict)


def small_vgg(data_conf, is_predict=False):
    """VGG-11 at dataset scale (the reference's CIFAR-sized variant)."""
    return vgg_conv_net(
        data_conf["image_size"], data_conf["num_classes"], 11,
        data_conf.get("is_color", True), is_predict,
    )


def training_settings(learning_rate=0.1, batch_size=128, algorithm="sgd",
                      momentum=0.9, decay_rate=0.001):
    """The reference's standard optimization settings block."""
    tch.settings(
        batch_size=batch_size,
        learning_rate=learning_rate / float(batch_size),
        learning_method=tch.MomentumOptimizer(momentum)
        if algorithm == "sgd"
        else {
            "adagrad": tch.AdaGradOptimizer(),
            "adadelta": tch.AdaDeltaOptimizer(),
            "rmsprop": tch.RMSPropOptimizer(),
        }[algorithm],
        regularization=tch.L2Regularization(decay_rate * batch_size),
    )
