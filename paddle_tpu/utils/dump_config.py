"""Print the lowered Program of a legacy config (reference
python/paddle/utils/dump_config.py, which printed the TrainerConfig
protobuf).

Usage:
    python -m paddle_tpu.utils.dump_config CONFIG.py [key=value,...]
"""

from __future__ import annotations

import sys

__all__ = ["dump_config"]


def dump_config(config_path, config_args=None):
    """Returns the program-code text of the config's main program."""
    from paddle_tpu.fluid.debugger import program_to_code
    from paddle_tpu.trainer import _exec_config, resolve_config_outputs
    from paddle_tpu.v2.topology import Topology

    state = _exec_config(config_path, config_args or {})
    topo = Topology(resolve_config_outputs(state))
    return program_to_code(topo.main_program)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__)
        return 1
    from paddle_tpu.trainer import _parse_config_args

    args = _parse_config_args(argv[1]) if len(argv) > 1 else {}
    print(dump_config(argv[0], args))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
