"""Dataset batching/pickling utilities (reference
python/paddle/utils/preprocess_util.py): turn a directory of raw samples
into shuffled pickled batch files plus train/test list files — the wire
format the legacy image configs consumed."""

from __future__ import annotations

import collections
import math
import os
import pickle
import random

__all__ = [
    "save_file", "save_list", "exclude_pattern", "list_dirs",
    "list_images", "list_files", "get_label_set_from_dir", "Label",
    "Dataset", "DataBatcher", "DatasetCreater",
]


def save_file(data, filename):
    """Pickle `data` to `filename` (highest protocol)."""
    with open(filename, "wb") as f:
        pickle.dump(data, f, pickle.HIGHEST_PROTOCOL)


def save_list(l, outfile):
    """Write one entry per line."""
    with open(outfile, "w") as f:
        for item in l:
            f.write("%s\n" % item)


def exclude_pattern(f):
    """Hidden/system entries are excluded from directory listings."""
    return f.startswith(".") or f.startswith("_")


def list_dirs(path):
    return sorted(
        d
        for d in os.listdir(path)
        if os.path.isdir(os.path.join(path, d)) and not exclude_pattern(d)
    )


def list_images(path, exts=set(["jpg", "png", "bmp", "jpeg"])):
    return sorted(
        f
        for f in os.listdir(path)
        if os.path.isfile(os.path.join(path, f))
        and not exclude_pattern(f)
        and f.rsplit(".", 1)[-1].lower() in exts
    )


def list_files(path):
    return sorted(
        f
        for f in os.listdir(path)
        if os.path.isfile(os.path.join(path, f)) and not exclude_pattern(f)
    )


def get_label_set_from_dir(path):
    """label name -> id, from the sub-directory names of a dataset laid
    out as path/<label>/<images>."""
    return {name: i for i, name in enumerate(list_dirs(path))}


class Label:
    """One label slot value."""

    def __init__(self, label, name):
        self.label = label
        self.name = name

    def convert_to_paddle_format(self):
        return int(self.label)

    def __hash__(self):
        return hash(self.label)


class Dataset:
    """A list of items, each a tuple of slots; every slot value provides
    convert_to_paddle_format()."""

    def __init__(self, data, keys):
        self.data = data
        self.keys = keys

    def check_valid(self):
        for d in self.data:
            assert len(d) == len(self.keys)

    def permute(self, key_id, num_per_batch):
        if key_id is None:
            self.uniform_permute()
        else:
            self.permute_by_key(key_id, num_per_batch)

    def uniform_permute(self):
        random.shuffle(self.data)

    def permute_by_key(self, key_id, num_per_batch):
        """Shuffle so the values of slot `key_id` are evenly spread over
        batches of num_per_batch (stratified batching)."""
        by_key = collections.defaultdict(list)
        for idx, item in enumerate(self.data):
            by_key[item[key_id].label].append(idx)
        for k in by_key:
            random.shuffle(by_key[k])
        per_key = int(math.ceil(num_per_batch / float(len(by_key))))
        if per_key < 2:
            raise Exception("The number of data in a batch is too small")
        permuted, cursor = [], collections.defaultdict(int)
        while len(permuted) < len(self.data):
            for k in by_key:
                lo = cursor[k]
                hi = min(lo + per_key, len(by_key[k]))
                permuted.extend(self.data[i] for i in by_key[k][lo:hi])
                cursor[k] = hi
        self.data = permuted


class DataBatcher:
    """Write pickled batch files + list files for train/test datasets."""

    def __init__(self, train_data, test_data, label_set):
        self.train_data = train_data
        self.test_data = test_data
        self.label_set = label_set
        self.num_per_batch = 5000
        assert self.train_data.keys == self.test_data.keys

    def create_batches_and_list(self, output_path, train_list_name,
                                test_list_name, label_set_name):
        train_list = self.create_batches(
            self.train_data, output_path, "train_", self.num_per_batch
        )
        test_list = self.create_batches(
            self.test_data, output_path, "test_", self.num_per_batch
        )
        save_list(train_list, os.path.join(output_path, train_list_name))
        save_list(test_list, os.path.join(output_path, test_list_name))
        save_file(self.label_set, os.path.join(output_path, label_set_name))

    def create_batches(self, data, output_path, prefix="",
                       num_data_per_batch=5000):
        data.check_valid()
        n_batches = int(
            math.ceil(len(data.data) / float(num_data_per_batch))
        )
        names = []
        for b in range(n_batches):
            name = os.path.join(output_path, prefix + "batch_%03d" % b)
            out = {k: [] for k in data.keys}
            for item in data.data[
                b * num_data_per_batch:(b + 1) * num_data_per_batch
            ]:
                for key, slot in zip(data.keys, item):
                    out[key].append(slot.convert_to_paddle_format())
            save_file(out, name)
            names.append(name)
        return names


class DatasetCreater(object):
    """Base for dataset creators: walks data_path/{train,test}/<label>/,
    builds Datasets via the subclass's create_dataset_from_dir, batches
    and writes meta. Subclasses implement create_dataset_from_dir /
    create_meta_file."""

    def __init__(self, data_path):
        self.data_path = data_path
        self.train_dir_name = "train"
        self.test_dir_name = "test"
        self.batch_dir_name = "batches"
        self.num_per_batch = 5000
        self.meta_filename = "batches.meta"
        self.train_list_name = "train.list"
        self.test_list_name = "test.list"
        self.label_set_name = "labels.pkl"
        self.output_path = os.path.join(self.data_path, self.batch_dir_name)
        self.overwrite = False

    def create_dataset_from_dir(self, path):
        raise NotImplementedError

    def create_meta_file(self, data):
        raise NotImplementedError

    def create_batches(self):
        train_path = os.path.join(self.data_path, self.train_dir_name)
        test_path = os.path.join(self.data_path, self.test_dir_name)
        out_path = self.output_path
        if os.path.exists(out_path) and not self.overwrite:
            return out_path
        os.makedirs(out_path, exist_ok=True)
        train_data = self.create_dataset_from_dir(train_path)
        test_data = self.create_dataset_from_dir(test_path)
        permute_key = getattr(self, "permute_key", None)
        key_id = (
            self.keys.index(permute_key)
            if permute_key and permute_key in getattr(self, "keys", [])
            else None
        )
        train_data.permute(key_id, self.num_per_batch)
        batcher = DataBatcher(
            train_data, test_data, get_label_set_from_dir(train_path)
        )
        batcher.num_per_batch = self.num_per_batch
        batcher.create_batches_and_list(
            out_path, self.train_list_name, self.test_list_name,
            self.label_set_name,
        )
        self.create_meta_file(train_data)
        return out_path
