"""Convert PyTorch weights into the v2 Parameters tar wire format
(reference python/paddle/utils/torch2paddle.py, which converted torch7
serialized models).

Modernised for torch state_dicts: map each tensor to a parameter name
in this framework and write the same tar the v2 trainer/Parameters
load (`v2/parameters.py` wire format), so converted weights drop into
`Parameters.from_tar` / `merge_v2_model` / the trainer CLI.

Usage:
    from paddle_tpu.utils.torch2paddle import torch2paddle
    torch2paddle(model.state_dict(),
                 name_map={"fc.weight": "__fc_0__.w0",
                           "fc.bias": "__fc_0__.wbias"},
                 output="params.tar")

Linear layers: torch stores [out, in]; paddle stores [in, out] — by
default every 2-D tensor whose torch name ends with 'weight' is
transposed; pass an explicit `transpose` iterable to override.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

import numpy as np

__all__ = ["torch2paddle"]


def torch2paddle(state_dict, name_map: Dict[str, str], output,
                 transpose: Optional[Iterable[str]] = None):
    """state_dict: torch name -> tensor (torch.Tensor or ndarray);
    name_map: torch name -> paddle parameter name; output: path or file
    object for the tar. Unmapped state_dict entries are skipped;
    name_map entries missing from the state_dict raise."""
    import tarfile

    from paddle_tpu.v2.parameters import write_tar_param

    missing = [k for k in name_map if k not in state_dict]
    if missing:
        raise KeyError("name_map entries not in state_dict: %r" % missing)

    def _np(t):
        if hasattr(t, "detach"):
            t = t.detach().cpu().numpy()
        return np.asarray(t, np.float32)

    transpose_set = None if transpose is None else set(transpose)
    arrays = {}
    for torch_name, paddle_name in name_map.items():
        a = _np(state_dict[torch_name])
        auto_t = transpose_set is None and torch_name.endswith("weight") \
            and a.ndim == 2
        if auto_t or (transpose_set is not None
                      and torch_name in transpose_set):
            a = a.T
        arrays[paddle_name] = np.ascontiguousarray(a)

    close = False
    if not hasattr(output, "write"):
        output = open(output, "wb")
        close = True
    try:
        with tarfile.open(fileobj=output, mode="w") as tar:
            for name, a in arrays.items():
                write_tar_param(tar, name, a)
    finally:
        if close:
            output.close()
    return sorted(arrays)
