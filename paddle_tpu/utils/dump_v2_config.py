"""Dump a v2 network topology to a file (reference
python/paddle/utils/dump_v2_config.py). The reference wrote the
ModelConfig protobuf (text or serialized) for the C-API; here the
language-neutral wire format is the JSON program schema
(fluid/core/serialization.py), which the native C++ inference runner
consumes — `binary=True` writes it gzip-compressed."""

from __future__ import annotations

import gzip

__all__ = ["dump_v2_config"]


def dump_v2_config(topology, save_path, binary=False):
    """Dump the network reachable from `topology`'s output layers.

    topology: LayerOutput, list/tuple of them, or a v2 Topology.
    save_path: destination file.
    binary: gzip the JSON (the compact form the serving path ships).
    """
    from paddle_tpu.fluid.core.serialization import dumps_program
    from paddle_tpu.v2.topology import Topology

    if not isinstance(topology, Topology):
        topology = Topology(topology)
    payload = dumps_program(topology.main_program, indent=None if binary else 2)
    if binary:
        with gzip.open(save_path, "wb") as f:
            f.write(payload.encode("utf-8"))
    else:
        with open(save_path, "w") as f:
            f.write(payload)
