"""Image-classification dataset preprocessing (reference
python/paddle/utils/preprocess_img.py): walk data_path/{train,test}/
<label>/*.jpg, resize, batch-pickle, and write a meta file with the mean
image — the on-disk format the legacy image configs trained from."""

from __future__ import annotations

import io
import os

import numpy as np

from . import preprocess_util
from .image_util import crop_img

__all__ = ["resize_image", "DiskImage", "ImageClassificationDatasetCreater"]


def resize_image(img, target_size):
    """Resize a PIL image so the SHORT edge equals target_size."""
    from PIL import Image

    percent = target_size / float(min(img.size[0], img.size[1]))
    size = (
        int(round(img.size[0] * percent)),
        int(round(img.size[1] * percent)),
    )
    return img.resize(size, Image.LANCZOS)


class DiskImage:
    """Lazily-read image on disk; converts to CHW array or stored JPEG
    bytes for the pickled batch."""

    def __init__(self, path, target_size):
        self.path = path
        self.target_size = target_size
        self.img = None

    def read_image(self):
        if self.img is None:
            from PIL import Image

            self.img = resize_image(Image.open(self.path), self.target_size)

    def convert_to_array(self):
        self.read_image()
        arr = np.array(self.img)
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        return arr

    def convert_to_paddle_format(self):
        """JPEG bytes — batches store compressed images."""
        self.read_image()
        out = io.BytesIO()
        self.img.convert("RGB").save(out, "jpeg")
        return out.getvalue()


class ImageClassificationDatasetCreater(preprocess_util.DatasetCreater):
    """Walks <data_path>/{train,test}/<label>/ images into pickled
    batches + a meta file carrying the mean image."""

    def __init__(self, data_path, target_size, color=True):
        preprocess_util.DatasetCreater.__init__(self, data_path)
        self.target_size = target_size
        self.color = color
        self.keys = ["images", "labels"]
        self.permute_key = "labels"
        self.num_classes = 0

    def create_dataset_from_dir(self, path):
        labels = preprocess_util.get_label_set_from_dir(path)
        self.num_classes = len(labels)
        items = []
        for name, label_id in labels.items():
            d = os.path.join(path, name)
            for f in preprocess_util.list_images(d):
                items.append((
                    DiskImage(os.path.join(d, f), self.target_size),
                    preprocess_util.Label(label_id, name),
                ))
        return preprocess_util.Dataset(items, self.keys)

    def create_meta_file(self, data):
        out = os.path.join(
            self.data_path, self.batch_dir_name, self.meta_filename
        )
        shape = (
            (3, self.target_size, self.target_size)
            if self.color
            else (self.target_size, self.target_size)
        )
        mean_img = np.zeros(shape, np.float64)
        for item in data.data:
            mean_img += crop_img(
                item[0].convert_to_array(), self.target_size, self.color
            )
        if data.data:
            mean_img /= len(data.data)
        preprocess_util.save_file(
            {
                "data_mean": mean_img.astype("int32").flatten(),
                "image_size": self.target_size,
                "mean_image_size": self.target_size,
                "num_classes": self.num_classes,
                "color": self.color,
            },
            out,
        )
