"""Print a saved model/program file in human-readable form (reference
python/paddle/utils/show_pb.py, which printed the ModelConfig/
ParameterConfig protobufs). Here model programs ship as the JSON schema
(plain or gzipped), so this pretty-prints block/op/var structure."""

from __future__ import annotations

import gzip
import json
import sys

__all__ = ["read_program", "show_program", "main"]


def read_program(path):
    """Load a serialized program (JSON, optionally gzipped) as a dict."""
    with open(path, "rb") as f:
        head = f.read(2)
    opener = gzip.open if head == b"\x1f\x8b" else open
    with opener(path, "rb") as f:
        return json.loads(f.read().decode("utf-8"))


def show_program(d, out=sys.stdout):
    out.write("format: %s v%s\n" % (d.get("format"), d.get("version")))
    for blk in d.get("blocks", []):
        out.write(
            "block %d (parent %s): %d vars, %d ops\n"
            % (
                blk["idx"], blk["parent_idx"],
                len(blk["vars"]), len(blk["ops"]),
            )
        )
        for v in blk["vars"]:
            out.write(
                "  var %s: shape=%s dtype=%s%s\n"
                % (
                    v["name"], v.get("shape"), v.get("dtype"),
                    " [param]" if v.get("is_parameter") else "",
                )
            )
        for op in blk["ops"]:
            out.write(
                "  op %s(%s) -> %s\n"
                % (
                    op["type"],
                    ", ".join(
                        "%s=%s" % (k, v) for k, v in sorted(
                            op.get("inputs", {}).items()
                        )
                    ),
                    ", ".join(
                        "%s=%s" % (k, v) for k, v in sorted(
                            op.get("outputs", {}).items()
                        )
                    ),
                )
            )


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        sys.stderr.write("usage: python -m paddle_tpu.utils.show_pb "
                         "<program.json[.gz]>\n")
        return 1
    show_program(read_program(argv[0]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
