"""EWMA/hysteresis trip detector — the ONE shared core (ISSUE 15
satellite).

PR 10 built this logic inside `distributed/sentinel.py`'s
DivergenceDetector for training-loss health; the serving integrity
sentinel (serving/integrity.py) needs exactly the same verdict machine
over a different scalar (per-step logit magnitude instead of loss).
Two copies of a hysteresis detector WILL drift — the suspect-holdout
rule in particular is easy to get subtly wrong — so the core lives
here once and both sides subclass/instantiate it.

Verdict machine (unchanged from PR 10, byte-for-byte the same
behavior):

  observe(value, aux_finite=...) -> "ok" | "nonfinite" | "spike"

    nonfinite  the value (or any auxiliary signal) is non-finite:
               trips IMMEDIATELY — a NaN is already in the future of
               whatever consumed it
    spike      |value| > spike_factor * EWMA(|value|) for `hysteresis`
               consecutive observations (after `warmup` healthy ones
               seed the EWMA)

Suspect observations never update the EWMA (a slow-motion blowup must
not drag its own baseline up); a sub-hysteresis excursion resets the
streak and decays normally. State is JSON-serializable
(`state_dict`/`load_state_dict`) so the training side can ride it in a
checkpoint and roll it BACK with the model.
"""

from __future__ import annotations

import math

__all__ = ["TripDetector"]


class TripDetector(object):
    """Hard trip on non-finite signals, soft trip on a sustained spike
    vs the signal's own EWMA. Single-threaded by design (called once
    per step from whatever loop owns it — trainer step loop, serving
    scheduler); fields are domain-confined, not locked."""

    def __init__(self, spike_factor: float = 4.0, hysteresis: int = 2,
                 ewma_alpha: float = 0.2, warmup: int = 3):
        if spike_factor <= 1.0:
            raise ValueError("spike_factor must be > 1")
        if hysteresis < 1:
            raise ValueError("hysteresis must be >= 1")
        if not 0.0 < ewma_alpha <= 1.0:
            raise ValueError("ewma_alpha must be in (0, 1]")
        self.spike_factor = float(spike_factor)
        self.hysteresis = int(hysteresis)
        self.ewma_alpha = float(ewma_alpha)
        self.warmup = int(warmup)
        self._ewma = None      # guarded-by: owner
        self._seen = 0         # guarded-by: owner
        self._streak = 0       # guarded-by: owner

    @property
    def ewma(self):
        return self._ewma

    @property
    def suspect(self) -> bool:
        """True while a spike streak is open (recent observations were
        held out of the EWMA): the divergence may already have begun."""
        return self._streak > 0

    def observe(self, value, aux_finite=None) -> str:
        """One observation. `aux_finite` is an optional second signal
        checked ONLY for finiteness (the training side's grad norm)."""
        value = float(value)
        if not math.isfinite(value) or (
                aux_finite is not None
                and not math.isfinite(float(aux_finite))):
            self._streak = 0  # a recovery restarts the soft window clean
            return "nonfinite"
        if (self._ewma is not None and self._seen >= self.warmup
                and abs(value) > self.spike_factor * max(abs(self._ewma),
                                                         1e-12)):
            self._streak += 1
            if self._streak >= self.hysteresis:
                self._streak = 0
                return "spike"
            return "ok"  # suspect, but within hysteresis: hold the EWMA
        self._streak = 0
        self._ewma = (value if self._ewma is None
                      else (1.0 - self.ewma_alpha) * self._ewma
                      + self.ewma_alpha * value)
        self._seen += 1
        return "ok"

    def state_dict(self) -> dict:
        return {"ewma": self._ewma, "seen": self._seen,
                "streak": self._streak}

    def load_state_dict(self, state: dict):
        self._ewma = state.get("ewma")
        self._seen = int(state.get("seen", 0))
        self._streak = int(state.get("streak", 0))
