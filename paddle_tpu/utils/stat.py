"""Scoped timers aggregated in a global StatSet (reference
paddle/utils/Stat.h:63 StatSet, :230 REGISTER_TIMER — RAII timers used
throughout the reference hot loop, TrainerInternal.cpp:94-152)."""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict


class _Stat(object):
    __slots__ = ("total", "count", "max")

    def __init__(self):
        self.total = 0.0
        self.count = 0
        self.max = 0.0

    def add(self, dt):
        self.total += dt
        self.count += 1
        self.max = max(self.max, dt)


class StatSet(object):
    def __init__(self, name="global"):
        self.name = name
        self._stats: Dict[str, _Stat] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def timer(self, name):
        t0 = time.time()
        try:
            yield
        finally:
            dt = time.time() - t0
            with self._lock:
                self._stats.setdefault(name, _Stat()).add(dt)

    def reset(self):
        with self._lock:
            self._stats.clear()

    def summary(self) -> str:
        lines = ["======= StatSet: [%s] =======" % self.name]
        lines.append(
            "%-30s %10s %10s %12s %10s"
            % ("name", "calls", "total(ms)", "avg(ms)", "max(ms)")
        )
        with self._lock:
            for name in sorted(self._stats):
                s = self._stats[name]
                lines.append(
                    "%-30s %10d %10.2f %12.3f %10.2f"
                    % (
                        name, s.count, s.total * 1e3,
                        s.total / max(s.count, 1) * 1e3, s.max * 1e3,
                    )
                )
        return "\n".join(lines)

    def print_summary(self):
        print(self.summary())


_global = StatSet()


def global_stats() -> StatSet:
    return _global


def timer(name):
    """with timer("forwardBackward"): ... — REGISTER_TIMER parity."""
    return _global.timer(name)


class RunningStat(object):
    """O(1) mean/max accumulator for long-lived metric streams. A
    process that records one value per step / request / batch forever
    must not grow a Python float list without bound — aggregates are
    running sums, not history (shared by serving.ServingMetrics and
    data.DataMetrics)."""

    __slots__ = ("count", "total", "max")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = None

    def append(self, x):
        x = float(x)
        self.count += 1
        self.total += x
        if self.max is None or x > self.max:
            self.max = x

    @property
    def mean(self):
        return self.total / self.count if self.count else None

    def __len__(self):
        return self.count
