"""glog-shim logging (reference paddle/utils/Logging.h; VLOG levels are
used as tracing throughout the fluid executor)."""

from __future__ import annotations

import logging
import sys

_configured = False


def get_logger(name="paddle_tpu", level=logging.INFO):
    global _configured
    logger = logging.getLogger(name)
    if not _configured:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(
            logging.Formatter("%(levelname).1s %(asctime)s %(name)s] %(message)s")
        )
        logger.addHandler(h)
        logger.setLevel(level)
        logger.propagate = False
        _configured = True
    return logger


def vlog(level, msg, *args):
    """VLOG(level) — gated on the `v` flag."""
    from .flags import FLAGS

    if FLAGS.v >= level:
        get_logger().info(msg, *args)
