"""Process flags (reference paddle/utils/Flags.cpp:18-100 defines the
central gflags: use_gpu, trainer_count, port, trainer_id, ... ; fluid
re-exposes them through pybind init_gflags). Here: a plain registry with
environment overrides (PADDLE_FLAGS="a=1,b=2" or PADDLE_FLAG_<NAME>)."""

from __future__ import annotations

import os
from typing import Any, Dict


class _Flags(object):
    def __init__(self):
        self._defs: Dict[str, Any] = {}

    def _define(self, name, default, cast):
        env = os.environ.get("PADDLE_FLAG_%s" % name.upper())
        if env is None:
            pairs = os.environ.get("PADDLE_FLAGS", "")
            for kv in pairs.split(","):
                k, _, v = kv.partition("=")
                if k.strip() == name:
                    env = v.strip()
        if env is not None:
            if cast is bool:
                default = env not in ("0", "false", "False", "")
            else:
                default = cast(env)
        self._defs[name] = default

    def __getattr__(self, name):
        try:
            return self.__dict__["_defs"][name]
        except KeyError:
            raise AttributeError("undefined flag %r" % name)

    def __setattr__(self, name, value):
        if name == "_defs":
            object.__setattr__(self, name, value)
        else:
            self._defs[name] = value

    def as_dict(self):
        return dict(self._defs)


FLAGS = _Flags()


def DEFINE_bool(name, default, help=""):
    FLAGS._define(name, bool(default), bool)


def DEFINE_int(name, default, help=""):
    FLAGS._define(name, int(default), int)


def DEFINE_float(name, default, help=""):
    FLAGS._define(name, float(default), float)


def DEFINE_string(name, default, help=""):
    FLAGS._define(name, default, str)


# the central flags the reference defines (Flags.cpp)
DEFINE_bool("use_gpu", True, "accelerator on (TPU here; kept for parity)")
DEFINE_int("trainer_count", 1, "data-parallel width (mesh 'data' axis)")
DEFINE_int("trainer_id", 0, "this process's index")
DEFINE_int("port", 7164, "service port (coordinator)")
DEFINE_int("num_gradient_servers", 1, "kept for parity; collectives now")
DEFINE_bool("check_nan_inf", False, "scan step outputs for NaN/Inf")
DEFINE_int("v", 0, "vlog verbosity")
