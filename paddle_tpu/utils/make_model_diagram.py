"""Generate a graphviz diagram of a legacy model config (reference
python/paddle/utils/make_model_diagram.py drew the ModelConfig layer
graph). Here the config executes to a fluid Program, and the existing
net drawer renders it."""

from __future__ import annotations

import sys

__all__ = ["make_diagram"]


def make_diagram(config_file, dot_file, config_arg_str=""):
    """Execute a trainer config (.py or .conf) and write its program
    graph as a .dot file."""
    from paddle_tpu.fluid.net_drawer import draw_graph
    from paddle_tpu.trainer import (
        _exec_config,
        _parse_config_args,
        resolve_config_outputs,
    )
    from paddle_tpu.v2.topology import Topology

    state = _exec_config(config_file, _parse_config_args(config_arg_str))
    topo = Topology(resolve_config_outputs(state))
    dot = draw_graph(topo.startup_program, topo.main_program)
    with open(dot_file, "w") as f:
        f.write(dot)
    return dot_file


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) < 2:
        sys.stderr.write(
            "usage: python -m paddle_tpu.utils.make_model_diagram "
            "<config> <out.dot> [config_args]\n"
        )
        return 1
    make_diagram(argv[0], argv[1], argv[2] if len(argv) > 2 else "")
    return 0


if __name__ == "__main__":
    sys.exit(main())
