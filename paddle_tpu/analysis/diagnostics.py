"""Shared diagnostic framework for the static analyzers.

The reference Fluid stack validated a ProgramDesc op-by-op in C++
(framework/op_desc.cc CheckAttrs / InferShape, operator.cc:484 runtime
re-check) and surfaced violations as PADDLE_ENFORCE failures with a code
location. Here every analyzer — the program verifier, the trace-hazard
linter, and the lock-discipline linter — emits the same `Diagnostic`
record: a stable code (P/T/L + number), a severity, a file:line anchor,
and a *fingerprint* that survives unrelated edits (no line numbers in
it), so a checked-in baseline can accept pre-existing findings without
blocking CI on new ones.

Baseline file format (one finding per line, `#` comments allowed):

    <CODE> <path>::<symbol>::<detail>  # one-line justification

The fingerprint is exactly the part before the justification comment.
An entry with no matching finding is reported as *stale* and FAILS the
full-scope gate (CLI and tier-1 self-check alike) so the baseline
shrinks as fixes land; an entry with a missing or TODO justification
fails the same way.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Diagnostic", "ProgramVerifyError", "CODES", "make",
    "load_baseline", "split_new", "format_diag", "repo_root", "rel_path",
    "default_baseline_path",
]

# code -> (short name, severity). Severity is informational: the CLI
# fails on ANY non-baselined finding, error or warning.
CODES: Dict[str, Tuple[str, str]] = {
    # program verifier (program_lint.py)
    "P001": ("dangling-input", "error"),
    "P002": ("dead-write", "warning"),
    "P003": ("dtype-mismatch", "error"),
    "P004": ("shape-mismatch", "error"),
    "P005": ("duplicate-parameter", "error"),
    "P006": ("unpaired-grad", "error"),
    # trace-hazard linter (trace_lint.py)
    "T001": ("host-sync-in-trace", "error"),
    "T002": ("impure-call-in-trace", "error"),
    "T003": ("tracer-branch", "warning"),
    "T004": ("unhashable-static-arg", "warning"),
    "T005": ("device-dispatch-in-scheduler", "error"),
    # lock-discipline linter (lock_lint.py)
    "L001": ("unguarded-mutation", "error"),
    "L002": ("lock-order-inversion", "error"),
    "L003": ("wait-outside-while", "warning"),
    "L004": ("notify-outside-lock", "error"),
    # band-lifecycle verifier (band_lint.py)
    "B001": ("band-not-propagated", "error"),
    "B002": ("dirty-flag-gap", "error"),
    "B003": ("wire-schema-asymmetry", "error"),
    "B004": ("device-adoption-drift", "error"),
    # mesh sharding-spec lint (shard_lint.py)
    "S001": ("unbound-axis-name", "error"),
    "S002": ("shard-spec-arity", "error"),
    "S003": ("host-sync-on-sharded", "error"),
    "S004": ("spec-rank-mismatch", "error"),
    # journal state-machine verifier (protocol_lint.py) — runs over
    # RequestJournal FILES (runtime artifacts), never in --all
    "J001": ("orphan-record", "error"),
    "J002": ("duplicate-terminal", "error"),
    "J003": ("record-after-terminal", "error"),
    "J004": ("stale-fence", "error"),
    "J005": ("progress-terminal-mismatch", "error"),
    "J006": ("unassigned-progress", "error"),
    "J007": ("open-at-close", "error"),
    "J008": ("malformed-journal", "error"),
    "J009": ("version-fence", "error"),
    "J010": ("taint-fence", "error"),
}

# codes whose analyzer runs inside `--all` / `run_all()` — the only
# scope whose baseline entries a full-scope run may judge stale. The
# J-codes verify journal FILES the CLI is pointed at explicitly, so a
# J baseline entry is never stale from --all's point of view.
REPO_SCOPE_CODES = ("P", "T", "L", "B", "S")


@dataclass
class Diagnostic:
    code: str       # stable code, e.g. "P001"
    path: str       # repo-relative file, or a program label like "<fit_a_line>"
    line: int       # 1-based anchor (0 = whole file/program)
    symbol: str     # enclosing scope: "Class.method", "func", or "block0"
    detail: str     # stable anchor inside the scope (var/attr/call name)
    message: str    # human-readable one-liner
    name: str = field(default="")
    severity: str = field(default="error")

    def __post_init__(self):
        if not self.name:
            self.name, self.severity = CODES.get(
                self.code, (self.code, "error")
            )

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity used for baseline matching."""
        return "%s %s::%s::%s" % (self.code, self.path, self.symbol,
                                  self.detail)


class ProgramVerifyError(ValueError):
    """Raised by the Executor's opt-in pre-flight when the program
    verifier reports error-severity findings. Carries the diagnostics."""

    def __init__(self, diagnostics: List[Diagnostic]):
        self.diagnostics = list(diagnostics)
        super().__init__(
            "program verification failed (%d finding%s):\n  %s"
            % (len(self.diagnostics),
               "" if len(self.diagnostics) == 1 else "s",
               "\n  ".join(format_diag(d) for d in self.diagnostics))
        )


def make(code: str, path: str, line: int, symbol: str, detail: str,
         message: str) -> Diagnostic:
    return Diagnostic(code=code, path=path, line=int(line), symbol=symbol,
                      detail=detail, message=message)


def format_diag(d: Diagnostic, baselined: bool = False) -> str:
    tail = "  [baselined]" if baselined else ""
    return "%s:%d: %s %s (%s) %s: %s%s" % (
        d.path, d.line, d.code, d.name, d.severity, d.symbol, d.message,
        tail,
    )


# --- repo anchoring ----------------------------------------------------

def repo_root() -> str:
    """The directory holding the `paddle_tpu` package (= repo root)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def rel_path(path: str) -> str:
    """Repo-relative, forward-slash path for stable fingerprints; paths
    outside the repo (test corpora in tmp dirs) pass through as given."""
    root = repo_root()
    ap = os.path.abspath(path)
    if ap.startswith(root + os.sep):
        return os.path.relpath(ap, root).replace(os.sep, "/")
    return path.replace(os.sep, "/")


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.txt")


def walk_python_files(paths, default_paths):
    """Yield .py files from `paths` (files or dirs, recursively; falls
    back to `default_paths` resolved against the repo root). The ONE
    file-scope definition shared by the AST linters, so their walkers
    cannot drift. A typo'd explicit path is a usage error (the CLI
    turns it into exit 2), never a traceback or a phantom-clean run."""
    root = repo_root()
    if not paths:
        paths = [os.path.join(root, p) for p in default_paths]
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, files in os.walk(p):
                dirnames.sort()  # deterministic traversal everywhere
                for fname in sorted(files):
                    if fname.endswith(".py"):
                        yield os.path.join(dirpath, fname)
        elif not os.path.exists(p):
            raise FileNotFoundError("no such file or directory: %r" % p)
        elif not p.endswith(".py"):
            raise ValueError("not a python file: %r" % p)
        else:
            yield p


# --- baseline ----------------------------------------------------------

def load_baseline(path: Optional[str] = None) -> Dict[str, str]:
    """fingerprint -> justification. Missing file = empty baseline."""
    path = path or default_baseline_path()
    out: Dict[str, str] = {}
    if not os.path.exists(path):
        return out
    import re

    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            # any run of whitespace before '#' separates fingerprint
            # from justification — a hand-edit that normalises the
            # canonical two spaces to one must not corrupt the entry
            parts = re.split(r"\s+#", line, maxsplit=1)
            why = parts[1].strip() if len(parts) > 1 else ""
            out[parts[0].strip()] = why
    return out


def split_new(diags: Iterable[Diagnostic], baseline: Dict[str, str]):
    """Partition findings into (new, baselined) and compute the stale
    baseline entries (accepted findings that no longer occur)."""
    new: List[Diagnostic] = []
    old: List[Diagnostic] = []
    seen = set()
    for d in diags:
        if d.fingerprint in baseline:
            old.append(d)
            seen.add(d.fingerprint)
        else:
            new.append(d)
    stale = [fp for fp in baseline if fp not in seen]
    return new, old, stale
