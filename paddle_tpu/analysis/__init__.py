"""paddle_tpu.analysis — custom static analyzers for this codebase.

Three analyzers over one shared diagnostic framework (stable codes,
file:line anchors, checked-in baseline in `baseline.txt`):

  * program verifier  (`program_lint`)  P001-P006 — validates
    Program/Block/Operator IR the way the reference's C++ ProgramDesc
    checks did, before the executor lowers it
  * trace-hazard linter (`trace_lint`)  T001-T004 — AST pass over the
    jitted hot paths for host-sync / retrace / impurity hazards inside
    traced functions
  * lock-discipline linter (`lock_lint`) L001-L002 — learns guarded
    attributes from `# guarded-by:` annotations and checks mutations +
    lock-acquisition ordering

Run everything:  python -m paddle_tpu.analysis --all
One analyzer:    python -m paddle_tpu.analysis program <entry.py>
                 python -m paddle_tpu.analysis trace [files...]
                 python -m paddle_tpu.analysis locks [paths...]

The tier-1 test
`tests/test_static_analysis.py::test_repo_is_clean_modulo_baseline`
asserts `run_all()` reports nothing beyond the baseline — new code
cannot merge with a fresh finding.

This package deliberately imports nothing heavy at module level: the
trace/lock linters are pure-AST and must run without jax. The program
verifier imports the fluid IR lazily.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .diagnostics import (  # noqa: F401
    CODES,
    Diagnostic,
    ProgramVerifyError,
    default_baseline_path,
    format_diag,
    load_baseline,
    split_new,
)

__all__ = [
    "Diagnostic", "ProgramVerifyError", "CODES", "run_all",
    "collect_diagnostics", "load_baseline", "split_new", "format_diag",
    "default_baseline_path",
]


def collect_diagnostics(with_programs: bool = True) -> List[Diagnostic]:
    """Run every analyzer over the repo and return the raw findings —
    the ONE assembly point shared by run_all() and the CLI's --all, so
    the tier-1 self-check and the lint gate cannot diverge."""
    from . import lock_lint, trace_lint

    diags: List[Diagnostic] = []
    if with_programs:
        from .entries import verify_entries

        diags.extend(verify_entries())
    diags.extend(trace_lint.lint_paths())
    diags.extend(lock_lint.lint_paths())
    return diags


def run_all(baseline_path: Optional[str] = None,
            with_programs: bool = True,
            ) -> Tuple[List[Diagnostic], List[Diagnostic], List[str]]:
    """Run every analyzer over the repo; returns (new, baselined,
    stale_baseline_entries). `with_programs=False` skips the built-in
    program entries (they import jax via fluid)."""
    diags = collect_diagnostics(with_programs)
    baseline = load_baseline(baseline_path)
    new, old, stale = split_new(diags, baseline)
    if not with_programs:
        # the program verifier did not run: its baseline entries are
        # out of scope, not stale (same scoping the CLI applies)
        stale = [fp for fp in stale if fp[:1] in ("T", "L")]
    return new, old, stale
