"""paddle_tpu.analysis — custom static analyzers for this codebase.

Seven engines over one shared diagnostic framework (stable codes,
file:line anchors, checked-in baseline in `baseline.txt`):

  * program verifier  (`program_lint`)  P001-P006 — validates
    Program/Block/Operator IR the way the reference's C++ ProgramDesc
    checks did, before the executor lowers it
  * trace-hazard linter (`trace_lint`)  T001-T005 — AST pass over the
    jitted hot paths for host-sync / retrace / impurity hazards inside
    traced functions, and accidental device dispatch from host-side
    scheduler loops
  * lock-discipline linter (`lock_lint`) L001-L004 — learns guarded
    attributes from `# guarded-by:` annotations and checks mutations,
    lock-acquisition ordering, and `threading.Condition` discipline
  * journal verifier (`protocol_lint`) J001-J008 — a per-rid DFA over
    `RequestJournal` files (the serving fleet's durable protocol
    history); `PADDLE_TPU_AUDIT_JOURNAL=1` audits every
    `ServingFleet.close()` for free
  * schedule explorer (`sched_explore`) — CHESS-lite deterministic
    interleaving enumeration over the fleet's SchedulerHook seam with
    recorded, replayable schedules and invariant probes
  * band-lifecycle verifier (`band_lint`) B001-B004 — derives the band
    registry from `engine._BANDS`/`_DEVICE_ADVANCED` and the paged-
    cache side-bands, then checks every `# band-verb:` annotated
    lifecycle function propagates every band (COW/serialize/import/
    resume/…), `_mark_dirty` coverage of host mirror mutations, wire
    serialize/import schema symmetry, and `_DEVICE_ADVANCED` drift
  * mesh sharding-spec lint (`shard_lint`) S001-S004 — unbound axis
    names in PartitionSpec/collectives, shard_map in/out_specs arity
    vs the wrapped signature, host materialization of mesh-placed
    values (scheduler-thread aware), and spec-rank overruns

Run everything:  python -m paddle_tpu.analysis --all
One analyzer:    python -m paddle_tpu.analysis program <entry.py>
                 python -m paddle_tpu.analysis trace [files...]
                 python -m paddle_tpu.analysis locks [paths...]
                 python -m paddle_tpu.analysis bands [files...]
                 python -m paddle_tpu.analysis shard [paths...]
                 python -m paddle_tpu.analysis journal <journal.jsonl>
                 python -m paddle_tpu.analysis explore [--scenario X]

The tier-1 test
`tests/test_static_analysis.py::test_repo_is_clean_modulo_baseline`
asserts `run_all()` reports nothing beyond the baseline — new code
cannot merge with a fresh finding.

This package deliberately imports nothing heavy at module level: the
trace/lock linters are pure-AST and must run without jax. The program
verifier imports the fluid IR lazily.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .diagnostics import (  # noqa: F401
    CODES,
    Diagnostic,
    ProgramVerifyError,
    default_baseline_path,
    format_diag,
    load_baseline,
    split_new,
)

__all__ = [
    "Diagnostic", "ProgramVerifyError", "CODES", "run_all",
    "collect_diagnostics", "load_baseline", "split_new", "format_diag",
    "default_baseline_path", "verify_journal",
]


def verify_journal(path, expect_closed=False):
    """Re-export of `protocol_lint.verify_journal` (lazy: the journal
    DFA is pure-stdlib but keeps the package's import-light rule)."""
    from .protocol_lint import verify_journal as _vj

    return _vj(path, expect_closed=expect_closed)


def collect_diagnostics(with_programs: bool = True) -> List[Diagnostic]:
    """Run every analyzer over the repo and return the raw findings —
    the ONE assembly point shared by run_all() and the CLI's --all, so
    the tier-1 self-check and the lint gate cannot diverge."""
    from . import band_lint, lock_lint, shard_lint, trace_lint

    diags: List[Diagnostic] = []
    if with_programs:
        from .entries import verify_entries

        diags.extend(verify_entries())
    diags.extend(trace_lint.lint_paths())
    diags.extend(lock_lint.lint_paths())
    diags.extend(band_lint.lint_paths())
    diags.extend(shard_lint.lint_paths())
    return diags


def run_all(baseline_path: Optional[str] = None,
            with_programs: bool = True,
            ) -> Tuple[List[Diagnostic], List[Diagnostic], List[str]]:
    """Run every analyzer over the repo; returns (new, baselined,
    stale_baseline_entries). `with_programs=False` skips the built-in
    program entries (they import jax via fluid)."""
    from .diagnostics import REPO_SCOPE_CODES

    diags = collect_diagnostics(with_programs)
    baseline = load_baseline(baseline_path)
    new, old, stale = split_new(diags, baseline)
    # journal (J) entries verify runtime artifacts — out of run_all's
    # scope, never stale here; without programs the P entries are out
    # of scope too (same scoping the CLI applies)
    scope = ("T", "L", "B", "S") if not with_programs \
        else REPO_SCOPE_CODES
    stale = [fp for fp in stale if fp[:1] in scope]
    return new, old, stale
