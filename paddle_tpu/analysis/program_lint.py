"""Program verifier: static validation of a `fluid.core.program.Program`.

Reference parity: the C++ stack validated every ProgramDesc before the
Executor interpreted it — OpDesc::CheckAttrs + InferShapeContext input/
output existence checks (framework/op_desc.cc, operator.cc:484) made a
malformed graph fail loudly at submit time. Our executor lowers a whole
block into one traced JAX function, so a malformed Program (dangling
input, dtype clash, dead write) otherwise surfaces as a cryptic tracer
error deep inside `Executor.run`. This pass walks the object graph
op-by-op and reports `Diagnostic` records with stable P-codes instead:

  P001 dangling-input       op input never produced by a prior op, a
                            feed (is_data), a fed name, or a persistable
  P002 dead-write           op whose every output is non-persistable,
                            never consumed downstream, and not fetched
  P003 dtype-mismatch       binary elementwise/sum inputs with clashing
                            declared dtypes
  P004 shape-mismatch       same-rank elementwise inputs whose declared
                            shapes cannot broadcast
  P005 duplicate-parameter  one Parameter name defined in >1 block
  P006 unpaired-grad        a @GRAD var whose base var does not exist

Sub-blocks (while / dynamic_rnn) are walked with the availability the
owning op sees, mirroring Program._sub_block_outer_reads' order-aware
contract. The `autodiff` op differentiates the forward region, so it
implicitly *consumes* every value produced before it (dead-write
analysis treats it that way) and legitimately has no declared inputs.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Set

from .diagnostics import Diagnostic, ProgramVerifyError, make

__all__ = ["verify_program", "preflight", "ELEMENTWISE_OPS"]

# ops whose value is their side effect (or that manage their own
# dataflow): never reported as dead writes
SIDE_EFFECT_OPS = {
    "print", "autodiff", "while", "dynamic_rnn", "conditional_block",
    "parallel_do", "feed", "fetch", "save", "load", "send", "recv",
    "increment", "beam_search_decode",
}

# binary ops whose two inputs must agree in dtype (and broadcast in shape)
ELEMENTWISE_OPS = {
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_pow", "elementwise_max",
    "elementwise_min",
}

GRAD_SUFFIX = "@GRAD"


def _is_parameter(var) -> bool:
    # duck-typed so corpora can hand-build IR without importing fluid here
    return type(var).__name__ == "Parameter" or getattr(
        var, "trainable", None) is not None


def _find_var(block, name):
    try:
        return block._find_var_recursive(name)
    except AttributeError:
        return None


def verify_program(program, feeds: Iterable[str] = (),
                   fetches: Iterable[str] = (),
                   label: str = "<program>") -> List[Diagnostic]:
    """Validate `program`; returns diagnostics (empty = clean).

    `feeds` are names the caller will feed at run time (beyond is_data
    vars); `fetches` are the run's fetch targets — both extend liveness
    so a verifier pass over a real (program, feed, fetch_list) triple
    has no false positives. With no `fetches`, dead-write analysis
    treats the final op's outputs as the program's result."""
    feeds = set(feeds)
    fetches = set(str(f) if not hasattr(f, "name") else f.name
                  for f in fetches)
    diags: List[Diagnostic] = []

    _check_duplicate_parameters(program, label, diags)
    _check_grad_pairing(program, label, diags)

    top = program.global_block()
    if not fetches and top.ops:
        fetches = set(top.ops[-1].output_arg_names)
    _check_block(program, top, set(), feeds, diags, label)
    _check_dead_writes(program, feeds, fetches, diags, label)
    return diags


# --- P005 --------------------------------------------------------------

def _check_duplicate_parameters(program, label, diags):
    owner = {}
    for blk in program.blocks:
        for name, var in blk.vars.items():
            if not _is_parameter(var):
                continue
            if name in owner and owner[name] is not blk:
                diags.append(make(
                    "P005", label, 0, "block%d" % blk.idx, name,
                    "parameter %r is defined in block %d and block %d"
                    % (name, owner[name].idx, blk.idx)))
            else:
                owner[name] = blk
    return diags


# --- P006 --------------------------------------------------------------

def _check_grad_pairing(program, label, diags):
    names: Set[str] = set()
    for blk in program.blocks:
        names.update(blk.vars)
        for op in blk.ops:
            names.update(op.output_arg_names)
    for blk in program.blocks:
        for name in sorted(blk.vars):
            if GRAD_SUFFIX not in name:
                continue
            base = name[: name.index(GRAD_SUFFIX)]
            if base and base not in names:
                diags.append(make(
                    "P006", label, 0, "block%d" % blk.idx, name,
                    "gradient var %r has no forward var %r"
                    % (name, base)))


# --- P001 / P003 / P004 ------------------------------------------------

def _check_block(program, blk, outer_avail, feeds, diags, label):
    produced = set(outer_avail)
    for op in blk.ops:
        for name in op.input_arg_names:
            if name in produced or name in feeds:
                continue
            var = _find_var(blk, name)
            if var is not None and (var.persistable
                                    or getattr(var, "is_data", False)
                                    or _is_parameter(var)):
                continue
            diags.append(make(
                "P001", label, 0, "block%d" % blk.idx,
                "%s:%s" % (op.type, name),
                "op %r reads %r, which no prior op, feed, or "
                "persistable produces" % (op.type, name)))
        _check_op_types(blk, op, diags, label)
        sub_idx = op.attrs.get("sub_block")
        if isinstance(sub_idx, int) and 0 <= sub_idx < len(program.blocks):
            _check_block(program, program.block(sub_idx), produced,
                         feeds, diags, label)
        produced.update(op.output_arg_names)


def _broadcastable(a, b) -> bool:
    if a is None or b is None or len(a) != len(b):
        return True  # rank mismatch / unknown: paddle's axis-broadcast,
        # not checkable without attr semantics — stay conservative
    for x, y in zip(a, b):
        if -1 in (x, y) or 1 in (x, y) or x == y:
            continue
        return False
    return True


def _check_op_types(blk, op, diags, label):
    if op.type in ELEMENTWISE_OPS:
        xs = op.input("X")
        ys = op.input("Y")
        if not (xs and ys):
            return
        vx, vy = _find_var(blk, xs[0]), _find_var(blk, ys[0])
        if vx is None or vy is None:
            return
        if vx.dtype and vy.dtype and vx.dtype != vy.dtype:
            diags.append(make(
                "P003", label, 0, "block%d" % blk.idx,
                "%s:%s|%s" % (op.type, xs[0], ys[0]),
                "op %r mixes dtypes: %s is %s but %s is %s"
                % (op.type, xs[0], vx.dtype, ys[0], vy.dtype)))
        elif not _broadcastable(vx.shape, vy.shape):
            diags.append(make(
                "P004", label, 0, "block%d" % blk.idx,
                "%s:%s|%s" % (op.type, xs[0], ys[0]),
                "op %r shapes cannot broadcast: %s is %s but %s is %s"
                % (op.type, xs[0], vx.shape, ys[0], vy.shape)))
    elif op.type == "sum":
        dtypes = {}
        for name in op.input("X"):
            v = _find_var(blk, name)
            if v is not None and v.dtype:
                dtypes.setdefault(v.dtype, name)
        if len(dtypes) > 1:
            pretty = ", ".join("%s:%s" % (n, d)
                               for d, n in sorted(dtypes.items()))
            diags.append(make(
                "P003", label, 0, "block%d" % blk.idx,
                "sum:%s" % "|".join(sorted(dtypes)),
                "op 'sum' mixes dtypes across inputs (%s)" % pretty))


# --- P002 --------------------------------------------------------------

def _collect_reads(program, blk, consumed):
    for op in blk.ops:
        consumed.update(op.input_arg_names)
        sub_idx = op.attrs.get("sub_block")
        if isinstance(sub_idx, int) and 0 <= sub_idx < len(program.blocks):
            _collect_reads(program, program.block(sub_idx), consumed)


def _check_dead_writes(program, feeds, fetches, diags, label):
    consumed: Set[str] = set(fetches)
    _collect_reads(program, program.global_block(), consumed)
    for blk in program.blocks:
        # autodiff differentiates the forward region, implicitly
        # consuming every value produced before it
        produced_before_autodiff: Set[str] = set()
        acc: Set[str] = set()
        for op in blk.ops:
            if op.type == "autodiff":
                produced_before_autodiff = acc
                break
            acc.update(op.output_arg_names)
        for op in blk.ops:
            if op.type in SIDE_EFFECT_OPS or "sub_block" in op.attrs:
                continue
            outs = op.output_arg_names
            if not outs:
                continue
            live = []
            for name in outs:
                var = _find_var(blk, name)
                if (name in consumed
                        or name in produced_before_autodiff
                        or (var is not None
                            and (var.persistable or _is_parameter(var)))):
                    live.append(name)
            if not live:
                diags.append(make(
                    "P002", label, 0, "block%d" % blk.idx,
                    "%s:%s" % (op.type, outs[0]),
                    "op %r writes only %s — never consumed, fetched, "
                    "or persisted" % (op.type, ", ".join(map(repr, outs)))))


# --- executor pre-flight ----------------------------------------------

def preflight(program, feeds: Iterable[str] = (),
              fetches: Iterable[str] = ()) -> None:
    """Opt-in Executor.run pre-flight: raise ProgramVerifyError on
    error-severity findings (dead writes are pruning fodder at run
    time, so P002 warnings never block a run). Memoized per
    (program version, feed/fetch signature) — the pre-run cost on a
    cached training step is one dict lookup."""
    key = (program.version, frozenset(feeds),
           tuple(sorted(str(f) if not hasattr(f, "name") else f.name
                        for f in fetches)))
    memo = getattr(program, "_preflight_ok", None)
    if memo is not None and key in memo:
        return
    diags = [d for d in verify_program(program, feeds=feeds,
                                       fetches=fetches,
                                       label="<program uid=%d>" % program.uid)
             if d.severity == "error"]
    if diags:
        raise ProgramVerifyError(diags)
    if memo is None:
        memo = program._preflight_ok = set()
    if len(memo) > 64:  # programs mutate; don't hoard dead signatures
        memo.clear()
    memo.add(key)
