"""Lock-discipline linter: `guarded-by` annotations, checked by AST.

The threaded subsystems (distributed/ supervisor+coordinator, data/
loader, serving/ engine) repeatedly grew the same review findings:
a field the coordinator lock protects mutated on a path that forgot
`with self._lock:`, or two locks taken in opposite orders on two paths.
This pass turns the convention into code:

  self._lock = threading.Lock()
  self.todo = []          # guarded-by: _lock
  self._pos = 0           # guarded-by: consumer

* A guard that names a lock attribute of the class (assigned from
  `threading.Lock/RLock/Condition/Semaphore`) demands every mutation of
  the guarded attribute happen lexically under `with self.<lock>:` —
  or inside a method whose call sites all hold it (inferred through the
  same-class call graph), or one annotated `def m(self): # holds: _lock`
  (caller contract). `__init__` (and helpers only it calls) is
  construction — exempt.
* Any other guard names a thread-confinement DOMAIN. Methods declare
  their domain with `def _produce(self): # thread: producer`; mutating
  an attribute guarded by domain D inside a method declared to run on a
  different domain is a finding; a private undeclared method called
  EXCLUSIVELY from one domain's methods inherits that domain (the same
  call-site inference locks get). Otherwise-undeclared methods are
  assumed to run on the owning domain — the check is about catching
  the annotated producer/consumer split drifting, with zero noise
  elsewhere.

Codes:
  L001 unguarded-mutation     guarded attribute mutated outside its
                              lock scope / on the wrong thread domain
  L002 lock-order-inversion   cycle in the lock-acquisition graph
                              (lexical nesting + same-class calls)
  L003 wait-outside-while     `Condition.wait()` not lexically inside a
                              `while` loop: a notify is not a promise
                              the predicate holds (spurious wakeups,
                              stolen wakeups, notify_all storms) —
                              `wait_for()` loops internally and is
                              exempt
  L004 notify-outside-lock    `notify()/notify_all()` on a Condition
                              whose lock is not held at the call site:
                              the runtime raises for this, but only on
                              the path that reaches it — the linter
                              finds the path first
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .diagnostics import Diagnostic, make, rel_path, walk_python_files

__all__ = ["lint_file", "lint_paths", "DEFAULT_PATHS"]

DEFAULT_PATHS = [
    "paddle_tpu/distributed",
    "paddle_tpu/data",
    "paddle_tpu/serving",
]

# the value must START with a word char: a placeholder like
# `# guarded-by: <lock>` (docs template) must not parse as a guard
_ANNOT_RE = re.compile(
    r"#\s*(guarded-by|holds|thread)\s*:\s*([\w.\-][\w.,\- ]*)")
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft",
    "appendleft", "extendleft", "clear", "add", "discard", "update",
    "setdefault", "popitem", "sort", "reverse", "rotate",
}
# Condition-discipline ops (L003/L004). `wait_for` is recorded but
# never L003-flagged: it re-evaluates its predicate internally.
_COND_OPS = {"wait", "wait_for", "notify", "notify_all"}

# sentinel context: "only construction has reached this method"
_EXEMPT = "exempt"
# sentinel context: "no information yet" (fixpoint top element)
_TOP = "top"


def _line_annotations(src: str) -> Dict[int, List[Tuple[str, str]]]:
    out: Dict[int, List[Tuple[str, str]]] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        for m in _ANNOT_RE.finditer(line):
            out.setdefault(i, []).append((m.group(1), m.group(2).strip()))
    return out


def _self_attr(node) -> Optional[str]:
    """`self.X` -> "X" (also the base of `self.X[k]` / `self.X[k].y`)."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        node = node.value
    return None


class _Method(object):
    def __init__(self, node, cls_name):
        self.node = node
        self.name = node.name
        self.symbol = "%s.%s" % (cls_name, node.name)
        self.holds: Set[str] = set()     # holds: annotation
        self.domain: Optional[str] = None  # thread: annotation
        # declared domain, or the one inferred from call sites (a
        # private helper called only from producer-declared methods
        # runs on the producer thread too)
        self.eff_domain: Optional[str] = None
        # (attr, lineno, frozenset(held locks at the mutation))
        self.mutations: List[Tuple[str, int, FrozenSet[str]]] = []
        # (lock, lineno, frozenset(held locks BEFORE acquiring))
        self.acquisitions: List[Tuple[str, int, FrozenSet[str]]] = []
        # (callee, lineno, frozenset(held locks at the call))
        self.calls: List[Tuple[str, int, FrozenSet[str]]] = []
        # Condition-discipline sites: (cond attr, op, lineno,
        # frozenset(held locks), lexically-inside-a-while)
        self.cond_calls: List[Tuple[str, str, int, FrozenSet[str],
                                    bool]] = []
        self.context = _TOP  # fixpoint: _TOP -> _EXEMPT | frozenset


class _Class(object):
    def __init__(self, node):
        self.node = node
        self.name = node.name
        self.locks: Set[str] = set()
        # Condition attr -> the lock that must be held to wait/notify
        # on it: itself, or the explicit `threading.Condition(self.X)`
        # lock argument
        self.conditions: Dict[str, str] = {}
        self.guards: Dict[str, str] = {}   # attr -> guard name
        self.guard_lines: Dict[str, int] = {}
        self.methods: Dict[str, _Method] = {}


def _collect_class(node: ast.ClassDef, annots) -> _Class:
    cls = _Class(node)
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        meth = _Method(item, cls.name)
        cls.methods[item.name] = meth
        body_start = item.body[0].lineno if item.body else item.lineno
        for ln in range(item.lineno, body_start + 1):
            for kind, val in annots.get(ln, ()):
                if kind == "holds":
                    meth.holds.update(
                        v.strip().split()[0] for v in val.split(",")
                        if v.strip())
                elif kind == "thread":
                    toks = val.split(",")[0].split()
                    if toks:
                        meth.domain = toks[0]
        _scan_method_decls(cls, meth, annots)
    for meth in cls.methods.values():
        _scan_method_body(cls, meth)
    return cls


def _scan_method_decls(cls: _Class, meth: _Method, annots):
    """Lock attrs + guarded-attr declarations (any method may declare,
    __init__ in practice)."""
    for node in ast.walk(meth.node):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            value = node.value
            for t in targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                if (isinstance(value, ast.Call)
                        and isinstance(value.func, (ast.Attribute,
                                                    ast.Name))):
                    fname = (value.func.attr
                             if isinstance(value.func, ast.Attribute)
                             else value.func.id)
                    if fname in _LOCK_CTORS:
                        cls.locks.add(attr)
                    if fname == "Condition":
                        lock_arg = (value.args[0] if value.args
                                    else None)
                        for kw in value.keywords:
                            if kw.arg == "lock":
                                lock_arg = kw.value
                        explicit = (_self_attr(lock_arg)
                                    if lock_arg is not None else None)
                        cls.conditions[attr] = explicit or attr
                end = getattr(node, "end_lineno", node.lineno)
                for ln in range(node.lineno, end + 1):
                    for kind, val in annots.get(ln, ()):
                        if kind == "guarded-by":
                            toks = val.split(",")[0].split()
                            if toks:
                                cls.guards[attr] = toks[0]
                                cls.guard_lines.setdefault(attr, ln)


def _scan_method_body(cls: _Class, meth: _Method):
    # suite carriers whose nested statements do_stmt walks itself —
    # scan_exprs must not blind-walk them with the OUTER held-set
    suite_nodes = (ast.stmt, ast.excepthandler)
    if hasattr(ast, "match_case"):
        suite_nodes += (ast.match_case,)

    def scan_exprs(stmt, held, in_while=False):
        """Calls (mutator methods + same-class self.m() + Condition
        wait/notify ops) in the statement's OWN expressions — child
        statement suites (including except handlers and match cases)
        are walked by do_stmt with their own held sets. A lambda body
        is DEFERRED execution: it cannot assume the caller's locks, so
        its mutations record with an empty held-set (a
        `pool.submit(lambda: self.q.append(x))` under the lock still
        runs lockless later)."""
        for _name, value in ast.iter_fields(stmt):
            values = value if isinstance(value, list) else [value]
            for v in values:
                if not isinstance(v, ast.AST) or isinstance(
                        v, suite_nodes):
                    continue
                stack = [(v, held)]
                while stack:
                    sub, h = stack.pop()
                    if isinstance(sub, ast.Lambda):
                        h = frozenset()
                    for c in ast.iter_child_nodes(sub):
                        stack.append((c, h))
                    if not isinstance(sub, ast.Call):
                        continue
                    func = sub.func
                    if not isinstance(func, ast.Attribute):
                        continue
                    base_attr = _self_attr(func.value)
                    if base_attr is not None and func.attr in _MUTATORS:
                        meth.mutations.append(
                            (base_attr, sub.lineno, h))
                    if base_attr is not None and func.attr in _COND_OPS:
                        meth.cond_calls.append(
                            (base_attr, func.attr, sub.lineno, h,
                             in_while))
                    if (isinstance(func.value, ast.Name)
                            and func.value.id == "self"
                            and func.attr in cls.methods):
                        meth.calls.append((func.attr, sub.lineno, h))

    def do_stmt(node, held: FrozenSet[str], in_while: bool = False):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs: out of scope for this pass
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            scan_exprs(node, held, in_while)
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and attr in cls.locks:
                    meth.acquisitions.append(
                        (attr, node.lineno, frozenset(inner)))
                    inner.add(attr)
            for s in node.body:
                do_stmt(s, frozenset(inner), in_while)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                _record_mut(cls, meth, t, node.lineno, held)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            # a bare annotation (`self.x: T` with no value) declares,
            # it does not mutate
            if not (isinstance(node, ast.AnnAssign)
                    and node.value is None):
                _record_mut(cls, meth, node.target, node.lineno, held)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                _record_mut(cls, meth, t, node.lineno, held)
        # a While's test AND body both re-run per iteration: a wait()
        # anywhere under it gets its predicate re-checked (the
        # `while True: ... if p: break ... wait()` idiom included);
        # the else: suite runs once, after the loop — not re-checked
        here = in_while or isinstance(node, ast.While)
        scan_exprs(node, held, here)
        for fname, value in ast.iter_fields(node):
            # a While's own body re-runs per iteration; its else:
            # suite runs once after the loop — but inherits any OUTER
            # while's re-run context
            suite_while = in_while or (
                isinstance(node, ast.While) and fname != "orelse")
            values = value if isinstance(value, list) else [value]
            for v in values:
                if isinstance(v, ast.stmt):
                    do_stmt(v, held, suite_while)
                elif isinstance(v, suite_nodes):
                    # except handlers / match cases: their OWN
                    # expressions (case guard/pattern, except type)
                    # scan here; their bodies are statement suites
                    # under the same held-set
                    scan_exprs(v, held, suite_while)
                    for s in getattr(v, "body", ()):
                        do_stmt(s, held, suite_while)

    for s in meth.node.body:
        do_stmt(s, frozenset())


def _record_mut(cls, meth, target, lineno, held):
    if isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            _record_mut(cls, meth, el, lineno, held)
        return
    attr = _self_attr(target)
    if attr is not None:
        meth.mutations.append((attr, lineno, held))


# --- call-context inference -------------------------------------------

def _infer_contexts(cls: _Class):
    """Fixpoint: which locks is a method's body guaranteed to run
    under? __init__ is construction (exempt); public methods assume an
    unguarded external caller; private methods inherit the
    INTERSECTION of their observed same-class call sites."""
    callers: Dict[str, List[Tuple[_Method, FrozenSet[str]]]] = {
        name: [] for name in cls.methods
    }
    for meth in cls.methods.values():
        for callee, _ln, held in meth.calls:
            callers[callee].append((meth, held))

    for name, meth in cls.methods.items():
        if name == "__init__":
            meth.context = _EXEMPT
        elif not name.startswith("_") or (
                name.startswith("__") and name.endswith("__")):
            meth.context = frozenset(meth.holds)
        else:
            meth.context = _TOP

    for _ in range(len(cls.methods) + 2):
        changed = False
        for name, meth in cls.methods.items():
            if name == "__init__" or not name.startswith("_") or (
                    name.startswith("__") and name.endswith("__")):
                continue
            sites = callers[name]
            if not sites:
                new = frozenset(meth.holds)
            else:
                lock_sets = []
                all_exempt = True
                unresolved = False
                for caller, held in sites:
                    if caller.context == _TOP:
                        unresolved = True
                        continue
                    if caller.context == _EXEMPT:
                        continue
                    all_exempt = False
                    lock_sets.append(frozenset(caller.context) | held)
                if unresolved and not lock_sets:
                    continue  # wait for callers to resolve
                if all_exempt and not lock_sets:
                    new = _EXEMPT
                else:
                    inter = lock_sets[0]
                    for s in lock_sets[1:]:
                        inter &= s
                    new = inter | frozenset(meth.holds)
            if new != meth.context:
                meth.context = new
                changed = True
        if not changed:
            break
    for meth in cls.methods.values():
        if meth.context == _TOP:  # recursion-only cluster: conservative
            meth.context = frozenset(meth.holds)

    # thread-domain inference mirrors the lock inference: a private
    # undeclared method called EXCLUSIVELY from methods of one domain
    # inherits it; mixed or unknown callers leave it unchecked (no
    # false positives — the inline num_workers==0 path legitimately
    # runs producer code on the consumer thread).
    for meth in cls.methods.values():
        meth.eff_domain = meth.domain
    for _ in range(len(cls.methods) + 1):
        changed = False
        for name, meth in cls.methods.items():
            if (meth.domain is not None or name == "__init__"
                    or not name.startswith("_")
                    or (name.startswith("__") and name.endswith("__"))):
                continue
            sites = [c for c, _held in callers[name]
                     if c.name != "__init__"]
            if not sites:
                continue
            doms = {c.eff_domain for c in sites}
            new = doms.pop() if len(doms) == 1 else None
            if new is not None and meth.eff_domain != new:
                meth.eff_domain = new
                changed = True
        if not changed:
            break


# --- checks ------------------------------------------------------------

def _check_class(cls: _Class, path: str, diags: List[Diagnostic]):
    if not cls.guards and not cls.locks:
        return
    _infer_contexts(cls)

    for meth in cls.methods.values():
        if meth.name == "__init__" or meth.context == _EXEMPT:
            continue
        assumed = meth.context if isinstance(meth.context, frozenset) \
            else frozenset()
        for attr, lineno, held in meth.mutations:
            guard = cls.guards.get(attr)
            if guard is None:
                continue
            if guard in cls.locks:
                if guard not in (held | assumed):
                    diags.append(make(
                        "L001", path, lineno, meth.symbol, attr,
                        "%r is guarded by lock %r but mutated without "
                        "holding it (held here: %s)"
                        % (attr, guard,
                           sorted(held | assumed) or "nothing")))
            else:
                dom = meth.eff_domain
                if dom is not None and dom != guard:
                    how = ("declared" if meth.domain is not None
                           else "inferred (from its callers) as")
                    diags.append(make(
                        "L001", path, lineno, meth.symbol, attr,
                        "%r is confined to the %r domain but mutated "
                        "in a method %s '# thread: %s'"
                        % (attr, guard, how, dom)))

        for attr, op, lineno, held, in_while in meth.cond_calls:
            owner = cls.conditions.get(attr)
            if owner is None:
                continue  # .wait()/.notify() on a non-Condition attr
            if op == "wait" and not in_while:
                diags.append(make(
                    "L003", path, lineno, meth.symbol, attr,
                    "%r.wait() outside a while-predicate loop: a "
                    "notify is not a promise the predicate holds "
                    "(spurious/stolen wakeups) — re-test in a while, "
                    "or use wait_for()" % attr))
            # holding the Condition ITSELF counts: `with self._cv:`
            # acquires the (possibly explicit) lock it wraps
            if op in ("notify", "notify_all") \
                    and not ({owner, attr} & (held | assumed)):
                diags.append(make(
                    "L004", path, lineno, meth.symbol, attr,
                    "%r.%s() without holding %r (held here: %s): the "
                    "runtime raises RuntimeError on whichever path "
                    "reaches this first"
                    % (attr, op, owner,
                       sorted(held | assumed) or "nothing")))

    _check_lock_order(cls, path, diags)


def _acquires_closure(cls: _Class) -> Dict[str, Set[str]]:
    acq = {name: {a for a, _, _ in m.acquisitions}
           for name, m in cls.methods.items()}
    for _ in range(len(cls.methods) + 1):
        changed = False
        for name, meth in cls.methods.items():
            for callee, _ln, _held in meth.calls:
                extra = acq.get(callee, set()) - acq[name]
                if extra:
                    acq[name] |= extra
                    changed = True
        if not changed:
            break
    return acq


def _check_lock_order(cls: _Class, path: str, diags: List[Diagnostic]):
    if len(cls.locks) < 2:
        return
    edges: Dict[str, Set[str]] = {}
    first_line: Dict[Tuple[str, str], int] = {}

    def add_edge(a, b, ln):
        if a == b:
            return
        edges.setdefault(a, set()).add(b)
        first_line.setdefault((a, b), ln)

    acq_closure = _acquires_closure(cls)
    for meth in cls.methods.values():
        assumed = meth.context if isinstance(meth.context, frozenset) \
            else frozenset()
        for lock, ln, held in meth.acquisitions:
            for a in held | assumed | frozenset(meth.holds):
                add_edge(a, lock, ln)
        for callee, ln, held in meth.calls:
            for b in acq_closure.get(callee, ()):
                for a in held | assumed | frozenset(meth.holds):
                    add_edge(a, b, ln)

    # cycle detection (DFS); report each cycle once by its sorted key
    reported = set()

    def dfs(start, node, stack, seen):
        for nxt in sorted(edges.get(node, ())):
            if nxt == start:
                key = tuple(sorted(stack))
                if key not in reported:
                    reported.add(key)
                    order = stack + [start]
                    diags.append(make(
                        "L002", path,
                        first_line.get((order[0], order[1]),
                                       cls.node.lineno),
                        cls.name, "->".join(key),
                        "lock-order inversion: %s — two paths acquire "
                        "these locks in opposite orders (deadlock risk)"
                        % " -> ".join(order)))
            elif nxt not in seen:
                dfs(start, nxt, stack + [nxt], seen | {nxt})

    for node in sorted(edges):
        dfs(node, node, [node], {node})


# --- entry points ------------------------------------------------------

def lint_file(path: str) -> List[Diagnostic]:
    with open(path) as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    annots = _line_annotations(src)
    rel = rel_path(path)
    diags: List[Diagnostic] = []
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            _check_class(_collect_class(node, annots), rel, diags)
    diags.sort(key=lambda d: (d.path, d.line, d.code))
    return diags


def lint_paths(paths=None) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for f in walk_python_files(paths, DEFAULT_PATHS):
        diags.extend(lint_file(f))
    return diags
