"""Mesh sharding-spec lint: static checks on the shard_map/
PartitionSpec surface before decode goes multi-chip (ISSUE 20).

ROADMAP item 1 moves the paged pool and the one compiled serving step
onto the device mesh. Every defect class this pass targets is one the
`parallel/` training stack has already paid for in review rounds, and
each becomes strictly harder to debug once serving traffic rides the
mesh: a typo'd axis name raises (or silently replicates) only at trace
time on real topology, an in_specs tuple that drifted from the wrapped
function's signature produces a pytree-structure error pages away from
the edit, and a host materialization of a mesh-placed value stalls
every chip in the mesh — not one. In the spirit of the static
interface checking GSPMD/pjit push into tracing time (PAPERS.md), run
it at lint time instead:

  S001 unbound-axis-name    a string axis name in a PartitionSpec or a
                            collective (psum/all_gather/ppermute/…)
                            that no mesh convention or in-file binding
                            (Mesh(...) names, make_mesh axes dicts,
                            axis-parameter defaults) declares — the
                            classic `"modle"` typo that XLA reports as
                            an unbound axis deep inside tracing
  S002 shard-spec-arity     shard_map in_specs/out_specs tuple length
                            vs the wrapped function's signature /
                            returned tuple — a drifted spec tuple is a
                            pytree-structure mismatch at trace time
  S003 host-sync-on-sharded host materialization (np.asarray / .item()
                            / float()) of a shard_map product, or of
                            device band state (`self._dev[...]` /
                            `self._band(...)`) from a `# thread:`
                            scheduler method — the sharding-aware
                            extension of T001/T005: on a mesh this
                            blocks EVERY participating chip
  S004 spec-rank-mismatch   a PartitionSpec with more entries than the
                            statically-known rank of the array it
                            places (device_put/with_sharding_constraint
                            on a literal-shaped jnp.zeros/ones/reshape)
                            — longer-than-rank is a hard error JAX only
                            raises at placement time

Axis-name vocabulary for S001 = the repo's documented mesh conventions
(parallel/mesh.py: 'data', 'model', 'seq', 'expert', plus the
'dcn'/'dcn_*' slice-crossing tier and the pipeline stack's 'pipe')
UNION every name the linted file itself binds: string defaults of
`axis`/`axis_name`/`*_axis` parameters, Mesh(devices, names) literals,
and make_mesh/make_hybrid_mesh axes-dict keys. Names flow through
parameters in this codebase (`def moe(..., axis: str = "expert")`), so
non-literal axis arguments are out of scope by design — the lint hunts
literal typos, not dataflow.

Pure AST — no jax import; reuses trace_lint's module index (aliases,
scopes, call sites) so the two passes cannot disagree on resolution.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .diagnostics import Diagnostic, make, rel_path, walk_python_files
from .trace_lint import (_Fn, _ModuleIndex, _dotted, _own_stmt_nodes,
                         _resolve, _sched_roots)

__all__ = ["lint_file", "lint_paths", "DEFAULT_PATHS", "CANONICAL_AXES"]

# the mesh-facing surface; `--all` lints exactly these. The whole
# parallel/ stack (not just the four ROADMAP-named files) shares the
# axis/shard_map idioms, and the serving engine is linted from day one
# so the mesh PR inherits a clean gate instead of installing one.
DEFAULT_PATHS = [
    "paddle_tpu/parallel",
    "paddle_tpu/serving/engine.py",
]

# parallel/mesh.py's documented axis conventions + the pipeline axis;
# 'dcn'-prefixed names are the make_hybrid_mesh slice-crossing tier
CANONICAL_AXES = frozenset(("data", "model", "seq", "expert", "pipe"))

# collective -> index of the positional axis-name operand (the
# `axis_name` keyword is checked for all of them)
_COLLECTIVE_AXIS_ARG: Dict[str, int] = {
    "jax.lax.psum": 1,
    "jax.lax.pmean": 1,
    "jax.lax.pmax": 1,
    "jax.lax.pmin": 1,
    "jax.lax.psum_scatter": 1,
    "jax.lax.all_gather": 1,
    "jax.lax.all_to_all": 1,
    "jax.lax.ppermute": 1,
    "jax.lax.pshuffle": 1,
    "jax.lax.pcast": 1,
    "jax.lax.axis_index": 0,
    "jax.lax.axis_size": 0,
}

_ARRAY_CTORS = {"jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.full",
                "jax.numpy.empty", "numpy.zeros", "numpy.ones",
                "numpy.full", "numpy.empty"}
_MATERIALIZERS = {"numpy.asarray", "numpy.array"}


def _is_partition_spec(dotted: Optional[str]) -> bool:
    return dotted is not None and dotted.split(".")[-1] == "PartitionSpec"


def _is_shard_map(dotted: Optional[str]) -> bool:
    return dotted is not None and dotted.split(".")[-1] == "shard_map"


def _extend_assign_aliases(tree, index: _ModuleIndex):
    """Fold module-level `P = PartitionSpec` style rebinds into the
    alias table (mesh.py's idiom — ImportFrom alone misses it)."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Name):
            src = node.value.id
            if src in index.aliases:
                index.aliases[node.targets[0].id] = index.aliases[src]


def _axis_strings(node) -> List[Tuple[str, int]]:
    """(axis-name, lineno) for a string constant or a tuple/list of
    them — the shapes an axis operand takes."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [(node.value, node.lineno)]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append((e.value, e.lineno))
        return out
    return []


def _axis_vocab(tree, index: _ModuleIndex) -> Set[str]:
    """Every axis name the file binds, plus the repo conventions."""
    vocab = set(CANONICAL_AXES)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            args = node.args
            named = list(zip(reversed(args.posonlyargs + args.args),
                             reversed(args.defaults)))
            named += [(a, d) for a, d in zip(args.kwonlyargs,
                                             args.kw_defaults)
                      if d is not None]
            for a, d in named:
                if a.arg in ("axis", "axis_name") \
                        or a.arg.endswith("_axis"):
                    for name, _ in _axis_strings(d):
                        vocab.add(name)
        elif isinstance(node, ast.Call):
            dotted, _ = _dotted(node.func, index.aliases)
            if dotted and dotted.split(".")[-1] == "Mesh" \
                    and len(node.args) >= 2:
                for name, _ in _axis_strings(node.args[1]):
                    vocab.add(name)
            if dotted and dotted.split(".")[-1] in (
                    "make_mesh", "make_hybrid_mesh"):
                for sub in list(node.args) + [k.value
                                              for k in node.keywords]:
                    if isinstance(sub, ast.Dict):
                        for k in sub.keys:
                            if isinstance(k, ast.Constant) \
                                    and isinstance(k.value, str):
                                vocab.add(k.value)
    return vocab


def _scope_qual(scope: Optional[_Fn]) -> str:
    return scope.qualname if scope is not None else "<module>"


# --- S001 --------------------------------------------------------------

def _check_axis_names(index: _ModuleIndex, vocab: Set[str], rel: str,
                      diags: List[Diagnostic]):
    for call, scope in index.calls:
        dotted, known = _dotted(call.func, index.aliases)
        sites: List[Tuple[str, int]] = []
        if dotted in _COLLECTIVE_AXIS_ARG and known:
            pos = _COLLECTIVE_AXIS_ARG[dotted]
            if len(call.args) > pos:
                sites += _axis_strings(call.args[pos])
            for kw in call.keywords:
                if kw.arg == "axis_name":
                    sites += _axis_strings(kw.value)
        elif _is_partition_spec(dotted) and known:
            for a in call.args:
                sites += _axis_strings(a)
        for name, lineno in sites:
            if name in vocab or name.startswith("dcn"):
                continue
            diags.append(make(
                "S001", rel, lineno, _scope_qual(scope), name,
                "axis name %r is bound by no mesh convention or "
                "in-file binding (have: %s) — an unbound axis is a "
                "trace-time error on real topology, or silent "
                "replication" % (name, ", ".join(sorted(vocab)))))


# --- S002 --------------------------------------------------------------

def _wrapped_fn(call, scope, index: _ModuleIndex) -> Optional[_Fn]:
    if not call.args:
        return None
    target = call.args[0]
    if isinstance(target, ast.Name):
        return _resolve(target.id, scope, index)
    if isinstance(target, ast.Lambda):
        for fn in index.all_fns:
            if fn.node is target:
                return fn
    return None


def _return_arity(fn: _Fn) -> Optional[int]:
    """Length of the wrapped function's returned tuple when every
    return is a tuple literal of one consistent length, else None."""
    if isinstance(fn.node, ast.Lambda):
        return len(fn.node.body.elts) \
            if isinstance(fn.node.body, ast.Tuple) else None
    arity: Optional[int] = None
    for sub in _own_stmt_nodes(fn.node):
        if not isinstance(sub, ast.Return) or sub.value is None:
            continue
        if not isinstance(sub.value, ast.Tuple):
            return None
        n = len(sub.value.elts)
        if arity is not None and arity != n:
            return None
        arity = n
    return arity


def _check_shard_map_arity(index: _ModuleIndex, rel: str,
                           diags: List[Diagnostic]):
    for call, scope in index.calls:
        dotted, known = _dotted(call.func, index.aliases)
        if not (_is_shard_map(dotted) and known):
            continue
        fn = _wrapped_fn(call, scope, index)
        if fn is None:
            continue
        in_specs = out_specs = None
        for kw in call.keywords:
            if kw.arg == "in_specs":
                in_specs = kw.value
            elif kw.arg == "out_specs":
                out_specs = kw.value
        has_vararg = fn.node.args.vararg is not None
        if isinstance(in_specs, ast.Tuple) and not has_vararg \
                and not any(isinstance(e, ast.Starred)
                            for e in in_specs.elts):
            n_specs = len(in_specs.elts)
            n_params = len(fn.arg_order)
            n_required = n_params - len(fn.defaults)
            if not (n_required <= n_specs <= n_params):
                diags.append(make(
                    "S002", rel, call.lineno, _scope_qual(scope),
                    "in_specs:%s" % fn.qualname,
                    "shard_map in_specs has %d entries but %r takes "
                    "%s positional argument%s — the spec tuple and the "
                    "signature have drifted (pytree-structure error at "
                    "trace time)"
                    % (n_specs, fn.qualname,
                       str(n_params) if n_required == n_params
                       else "%d-%d" % (n_required, n_params),
                       "" if n_params == 1 else "s")))
        if isinstance(out_specs, ast.Tuple) \
                and not any(isinstance(e, ast.Starred)
                            for e in out_specs.elts):
            ret = _return_arity(fn)
            if ret is not None and ret != len(out_specs.elts):
                diags.append(make(
                    "S002", rel, call.lineno, _scope_qual(scope),
                    "out_specs:%s" % fn.qualname,
                    "shard_map out_specs has %d entries but %r "
                    "returns a %d-tuple"
                    % (len(out_specs.elts), fn.qualname, ret)))


# --- S003 --------------------------------------------------------------

def _names_in_targets(targets) -> List[str]:
    out: List[str] = []
    stack = list(targets)
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Name):
            out.append(t.id)
    return out


def _check_host_sync(index: _ModuleIndex, rel: str,
                     diags: List[Diagnostic]):
    """S003(a): np.asarray/.item()/float() on a value produced by a
    shard_map-wrapped callable, per function scope."""
    for fn in index.all_fns:
        if getattr(fn, "is_class", False):
            continue
        wrapped: Set[str] = set()
        placed: Set[str] = set()
        assigns = [sub for sub in _own_stmt_nodes(fn.node)
                   if isinstance(sub, ast.Assign)
                   and isinstance(sub.value, ast.Call)]
        # two passes: the walk is not source-ordered, so bind the
        # shard_map wrappers before attributing their call products
        for sub in assigns:
            dotted, known = _dotted(sub.value.func, index.aliases)
            if _is_shard_map(dotted) and known:
                wrapped.update(_names_in_targets(sub.targets))
        for sub in assigns:
            val = sub.value
            if isinstance(val.func, ast.Name) and val.func.id in wrapped:
                placed.update(_names_in_targets(sub.targets))
            elif isinstance(val.func, ast.Call):
                d2, k2 = _dotted(val.func.func, index.aliases)
                if _is_shard_map(d2) and k2:
                    placed.update(_names_in_targets(sub.targets))
        if not placed:
            continue
        for sub in _own_stmt_nodes(fn.node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            dotted, known = _dotted(f, index.aliases)
            hit = None
            if ((dotted in _MATERIALIZERS and known)
                    or (isinstance(f, ast.Name) and f.id == "float"
                        and "float" not in index.aliases)) \
                    and sub.args \
                    and isinstance(sub.args[0], ast.Name) \
                    and sub.args[0].id in placed:
                hit = sub.args[0].id
            elif isinstance(f, ast.Attribute) and f.attr == "item" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in placed:
                hit = f.value.id
            if hit is not None:
                diags.append(make(
                    "S003", rel, sub.lineno, fn.qualname, hit,
                    "host materialization of %r, a shard_map product "
                    "— on a mesh this blocks every participating "
                    "chip, not one device" % hit))


def _mentions_device_band(node) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "_dev":
            return True
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr == "_band":
            return True
    return False


def _check_sched_materialize(tree, src: str, index: _ModuleIndex,
                             rel: str, diags: List[Diagnostic]):
    """S003(b): a `# thread:` scheduler method (or anything it reaches
    in-class — T005's closure) materializing device band state. Today
    the bands live on one chip; after the mesh PR the same line stalls
    the whole mesh, so the gate predates the sharding."""
    src_lines = src.splitlines()
    for cls_node in tree.body:
        if not isinstance(cls_node, ast.ClassDef):
            continue
        roots = _sched_roots(cls_node, src_lines)
        if not roots:
            continue
        methods = {
            item.name: item for item in cls_node.body
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        calls: Dict[str, Set[str]] = {}
        for name, node in methods.items():
            out: Set[str] = set()
            for sub in _own_stmt_nodes(node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id == "self"
                        and sub.func.attr in methods):
                    out.add(sub.func.attr)
            calls[name] = out
        reach: Set[str] = set()
        frontier = list(roots)
        while frontier:
            name = frontier.pop()
            if name in reach:
                continue
            reach.add(name)
            frontier.extend(calls.get(name, ()))
        for name in sorted(reach):
            node = methods[name]
            qual = "%s.%s" % (cls_node.name, name)
            tainted: Set[str] = set()
            for sub in _own_stmt_nodes(node):
                if isinstance(sub, ast.Assign) \
                        and _mentions_device_band(sub.value):
                    tainted.update(_names_in_targets(sub.targets))
            for sub in _own_stmt_nodes(node):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                dotted, known = _dotted(f, index.aliases)
                is_mat = (dotted in _MATERIALIZERS and known) \
                    or (isinstance(f, ast.Name) and f.id == "float"
                        and "float" not in index.aliases)
                is_item = isinstance(f, ast.Attribute) \
                    and f.attr == "item"
                if not (is_mat or is_item):
                    continue
                probe = sub.args[0] if (is_mat and sub.args) else \
                    (f.value if is_item else None)
                if probe is None:
                    continue
                dirty = _mentions_device_band(probe) or any(
                    isinstance(n, ast.Name) and n.id in tainted
                    for n in ast.walk(probe))
                if dirty:
                    diags.append(make(
                        "S003", rel, sub.lineno, qual, "_dev",
                        "scheduler-thread materialization of device "
                        "band state: a '# thread:' loop that blocks "
                        "on the mesh stalls every chip behind one "
                        "host round-trip"))


# --- S004 --------------------------------------------------------------

def _literal_rank(node, ranks: Dict[str, int],
                  index: _ModuleIndex) -> Optional[int]:
    """Statically-known rank of an expression: a tracked Name, a
    jnp.zeros/ones/full/empty literal-shape call, or .reshape(...)."""
    if isinstance(node, ast.Name):
        return ranks.get(node.id)
    if isinstance(node, ast.Call):
        dotted, known = _dotted(node.func, index.aliases)
        if dotted in _ARRAY_CTORS and known and node.args:
            shape = node.args[0]
            if isinstance(shape, ast.Tuple):
                if any(isinstance(e, ast.Starred) for e in shape.elts):
                    return None
                return len(shape.elts)
            if isinstance(shape, ast.Constant) \
                    and isinstance(shape.value, int):
                return 1
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "reshape":
            args = node.args
            if len(args) == 1 and isinstance(args[0], ast.Tuple):
                if any(isinstance(e, ast.Starred)
                       for e in args[0].elts):
                    return None
                return len(args[0].elts)
            if args and not any(isinstance(a, ast.Starred)
                                for a in args):
                return len(args)
    return None


def _spec_entry_count(node, index: _ModuleIndex) -> Optional[int]:
    """Number of dimension entries in a P(...)/PartitionSpec(...) call
    or a NamedSharding(mesh, P(...)) wrapper; None when not literal."""
    if not isinstance(node, ast.Call):
        return None
    dotted, known = _dotted(node.func, index.aliases)
    if dotted and dotted.split(".")[-1] == "NamedSharding" \
            and len(node.args) >= 2:
        return _spec_entry_count(node.args[1], index)
    if _is_partition_spec(dotted) and known:
        if any(isinstance(a, ast.Starred) for a in node.args):
            return None
        return len(node.args)
    return None


def _check_spec_rank(index: _ModuleIndex, rel: str,
                     diags: List[Diagnostic]):
    for fn in index.all_fns:
        if getattr(fn, "is_class", False):
            continue
        ranks: Dict[str, int] = {}
        for sub in _own_stmt_nodes(fn.node):
            if isinstance(sub, ast.Assign):
                r = _literal_rank(sub.value, ranks, index)
                if r is not None:
                    for name in _names_in_targets(sub.targets):
                        ranks[name] = r
        for sub in _own_stmt_nodes(fn.node):
            if not isinstance(sub, ast.Call):
                continue
            dotted, known = _dotted(sub.func, index.aliases)
            is_put = dotted in ("jax.device_put",) and known
            is_constraint = dotted is not None and known and \
                dotted.split(".")[-1] == "with_sharding_constraint"
            if not (is_put or is_constraint) or len(sub.args) < 2:
                continue
            rank = _literal_rank(sub.args[0], ranks, index)
            n_spec = _spec_entry_count(sub.args[1], index)
            if rank is None or n_spec is None or n_spec <= rank:
                continue
            diags.append(make(
                "S004", rel, sub.lineno, fn.qualname,
                "rank%d-spec%d" % (rank, n_spec),
                "PartitionSpec names %d dimensions but the array has "
                "statically-known rank %d — placement raises on real "
                "topology only" % (n_spec, rank)))


# --- entry points ------------------------------------------------------

def lint_file(path: str) -> List[Diagnostic]:
    with open(path) as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    index = _ModuleIndex(tree)
    _extend_assign_aliases(tree, index)
    rel = rel_path(path)
    vocab = _axis_vocab(tree, index)
    diags: List[Diagnostic] = []
    _check_axis_names(index, vocab, rel, diags)
    _check_shard_map_arity(index, rel, diags)
    _check_host_sync(index, rel, diags)
    _check_sched_materialize(tree, src, index, rel, diags)
    _check_spec_rank(index, rel, diags)
    diags.sort(key=lambda d: (d.path, d.line, d.code, d.detail))
    return diags


def lint_paths(paths=None) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for f in walk_python_files(paths, DEFAULT_PATHS):
        diags.extend(lint_file(f))
    return diags
