"""Deterministic schedule explorer for the serving fleet (CHESS-lite;
ISSUE 9 tentpole, engine 2 of 2).

Every protocol bug the PR 6-8 review passes found by hand was an
INTERLEAVING: a replica handshake racing a demotion racing a close.
Lexical linters cannot see interleavings; systematic concurrency
testing can (Musuvathi et al., "Finding and Reproducing Heisenbugs in
Concurrent Programs"). This module is that idea cut down to this
fleet's seam:

  * The fleet's `SchedulerHook` (serving/fleet.py) marks every
    thread-handoff point — replica handshake, engine step, monitor
    sweep, journal flush, submit commit — all OUTSIDE fleet locks.
    `ControlledScheduler` parks each fleet thread there and runs
    exactly ONE thread at a time; the driver picks who goes next.
  * Scenarios (`SCENARIOS`) build a small fleet over `ScriptEngine` —
    a host-only, deterministic fake engine (one token per step, a pure
    function of (prompt, seed, index), honest `resume_tokens`
    semantics) — so a whole run takes milliseconds and every branch
    the fleet takes is a function of the SCHEDULE alone: heartbeats
    are sized out, deadlines unset, demotion is operator-driven.
  * A schedule is the sequence of choices the driver made (one name
    per step). `run_schedule(scenario, decisions)` replays a decision
    prefix then falls back to the default policy; the same prefix
    always reproduces the same trace, so a violation PRINTS the exact
    schedule that breaks and `--replay` re-runs it.
  * `explore(scenario)` enumerates schedules with bounded preemptions
    (CHESS's insight: most heisenbugs need very few): run the default
    schedule, then branch every choice point where more than one
    thread was enabled, up to `max_preemptions` deviations.

Invariant probes checked after every run (the fleet's falsifiability
bar, machine-checked): every handle reaches a verdict and completed
outputs are token-identical to the scripted oracle; `stats()["lost"]
== 0`; no request is answered twice; the journal file passes the
protocol DFA (`protocol_lint.verify_journal`, close-invariant
included) and its mirror agrees with the file (`recover()` finds
nothing open).

CLI:  python -m paddle_tpu.analysis explore [--scenario NAME]
          [--preemptions K] [--max-schedules N] [--replay CSV]
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..serving.fleet import SchedulerHook, ServingFleet

__all__ = [
    "ControlledScheduler", "ScriptEngine", "Scenario", "SCENARIOS",
    "RunResult", "run_schedule", "explore", "format_schedule",
    "script_tokens",
]

# one released thread must reach its next yield point (or exit) within
# this budget; past it the run is reported as a WEDGE (the probe-wedge
# bug class), not silently stuck
_QUIESCE_TIMEOUT_S = 20.0


# ---------------------------------------------------------------------------
# scripted engine: the deterministic stand-in for ServingEngine
# ---------------------------------------------------------------------------

def script_tokens(prompt, seed: int, n: int) -> List[int]:
    """The scripted oracle: token i of a request is a pure function of
    (prompt, seed, i) — like the real engine's (seed, token index)
    sampling keys, the schedule/replica/resume split can never change
    WHICH tokens a request decodes to, only who emits them."""
    base = int(np.asarray(prompt, np.int64).sum()) % 1000
    return [(base * 7 + int(seed) * 13 + i * 3) % 97 for i in range(n)]


class _ScriptHandle(object):
    """Matches the real ServingHandle's resume contract: `tokens`
    holds only NEWLY generated tokens (the fleet prepends the resume
    prefix itself at completion), and generation continues at token
    index `len(resume)` of the per-request script."""

    def __init__(self, prompt, max_new, seed, resume):
        self.rid = -1  # assigned by ScriptEngine.submit
        self.tokens: List[int] = []
        self._script = script_tokens(prompt, seed, int(max_new))
        self._at = len(resume)
        if list(resume) != self._script[:self._at]:
            # an honest engine decodes the remainder AFTER the resume
            # prefix; a prefix that disagrees with the script would let
            # a protocol bug hide behind engine nondeterminism
            raise AssertionError(
                "resume prefix %r disagrees with the script %r"
                % (list(resume), self._script))
        self.done = self._at >= len(self._script)
        self.finish_reason = "done" if self.done else None

    def _step(self):
        if self.done:
            return
        self.tokens.append(self._script[self._at])
        self._at += 1
        if self._at >= len(self._script):
            self.done = True
            self.finish_reason = "done"


class _ScriptMetrics(object):
    """The metric surface `_Replica._stats` reads, scripted."""

    def __init__(self, step_ewma_s):
        self.tokens_out = 0
        self.decode_steps = 0
        self.prefills = 0
        self.prefill_tokens_computed = 0
        self.kv_blocks_in_use = 0
        self.kv_blocks_freed_at_retire = 0
        self.kv_tail_blocks_freed = 0
        self.cow_blocks = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.expired = 0
        self.resumed_requests = 0
        self.resume_tokens_reused = 0
        self.step_ewma_s = step_ewma_s
        self.adapter_pool = None


class ScriptEngine(object):
    """Host-only deterministic engine for schedule exploration: one
    token per `step()` per live request, tokens a pure function of
    (prompt, seed, index), honest `resume_tokens` (the remainder is
    decoded from the resume index, never re-decoded), `cancel()` claws
    work back. No jax, no wall-clock dependence — a fleet over this
    engine is a pure function of the schedule."""

    def __init__(self, params, cfg, replica_id=None, scheduler_hook=None,
                 step_ewma_s=0.001, **_kw):
        self.replica_id = replica_id
        self._hook = scheduler_hook
        self._serving: Dict[int, _ScriptHandle] = {}
        self._aborted: Optional[BaseException] = None
        self.metrics = _ScriptMetrics(step_ewma_s)
        self.prefix_cache = None

    def submit(self, prompt, max_new_tokens, temperature=0.0,
               eos_id=None, seed=0, publish_len=None, deadline_at=None,
               resume_tokens=None, handoff=None):
        # `handoff` (ISSUE 16): a block package the fleet ships at
        # re-route. A scripted engine has no KV pool to import into,
        # so the package is dropped on the floor and no outcome is
        # reported — exactly the surface-less engine the fleet's
        # _accept covers with the defaulted fallback outcome (the J011
        # fence the kv_handoff_race scenario explores)
        h = _ScriptHandle(prompt, max_new_tokens, seed,
                          resume_tokens or [])
        if resume_tokens:
            self.metrics.resumed_requests += 1
            self.metrics.resume_tokens_reused += len(resume_tokens)
        # fresh engine-local id (the fleet keeps its own rid map; ours
        # only needs cancel() to find the slot)
        h.rid = max(self._serving, default=-1) + 1
        self._serving[h.rid] = h
        return h

    def step(self):
        if self._hook is not None:
            self._hook.yield_point(
                "engine:%s:step" % (self.replica_id or ""))
        if self._aborted is not None:
            raise self._aborted
        for h in list(self._serving.values()):
            h._step()
            self.metrics.tokens_out += 1
            if h.done:
                self._serving.pop(h.rid)
        self.metrics.decode_steps += 1
        return bool(self._serving)

    def cancel(self, rid) -> bool:
        return self._serving.pop(rid, None) is not None

    def abort(self, exc: BaseException):
        self._aborted = exc
        self._serving.clear()

    @property
    def live_slots(self) -> int:
        return len(self._serving)

    @property
    def queue_depth(self) -> int:
        return 0

    @property
    def prefilling_slots(self) -> int:
        return 0


# ---------------------------------------------------------------------------
# the controlled scheduler
# ---------------------------------------------------------------------------

class SchedulerWedge(RuntimeError):
    """A released thread failed to reach its next yield point (or
    exit) within the quiescence budget — the wedge bug class."""


class ControlledScheduler(SchedulerHook):
    """One-thread-at-a-time cooperative scheduler over the fleet's
    `SchedulerHook` seam. Registered threads (the fleet's replicas and
    monitor, plus scenario threads spawned via `spawn()`) park at
    every yield point until `step(name)` releases them for exactly one
    hop; unregistered threads (the driver) pass through untouched.
    `release_all()` opens the gate permanently (teardown:
    `fleet.close()` joins threads, which must then free-run)."""

    def __init__(self):
        self._cv = threading.Condition()
        self._names: Dict[int, str] = {}      # guarded-by: _cv
        self._parked: Dict[str, str] = {}     # name -> point; guarded-by: _cv
        self._exited: set = set()             # guarded-by: _cv
        self._free = False                    # guarded-by: _cv
        self._threads: Dict[str, threading.Thread] = {}  # guarded-by: _cv
        # append-only registration log + announced-but-unregistered
        # spawns: step() uses both to wait for threads the RELEASED
        # hop itself spawned (an autoscaler scale-up, a rollout
        # refill) to reach their first park — the recorded enabled-set
        # must not race a fresh thread's startup, or replays of the
        # same schedule could diverge. The fleet announces each spawn
        # SYNCHRONOUSLY via thread_spawning(name) before start(), so
        # even a thread the OS has not scheduled yet (no
        # thread_started call) is accounted for.
        self._reg_log: List[str] = []         # guarded-by: _cv
        self._pending_spawn: set = set()      # guarded-by: _cv

    # -- SchedulerHook (called from fleet threads) ---------------------
    def thread_started(self, kind: str, name: str):
        with self._cv:
            self._names[threading.get_ident()] = name
            self._threads[name] = threading.current_thread()
            self._reg_log.append(name)
            self._pending_spawn.discard(name)
            self._cv.notify_all()

    def thread_spawning(self, name: str):
        # called on the SPAWNING thread (possibly under fleet locks):
        # record only, never block
        with self._cv:
            if not self._free:
                self._pending_spawn.add(name)
            self._cv.notify_all()

    def thread_exiting(self):
        with self._cv:
            name = self._names.pop(threading.get_ident(), None)
            if name is not None:
                self._exited.add(name)
                self._parked.pop(name, None)
                self._cv.notify_all()

    def yield_point(self, point: str):
        with self._cv:
            if self._free:
                return
            name = self._names.get(threading.get_ident())
            if name is None:
                return  # unregistered (driver) thread: pass through
            self._parked[name] = point
            self._cv.notify_all()
            while name in self._parked and not self._free:
                self._cv.wait(timeout=0.5)

    # -- driver surface ------------------------------------------------
    def spawn(self, name: str, fn: Callable[[], None]) -> threading.Thread:
        """Run `fn` on a REGISTERED scenario thread: it parks once at
        "scenario:<name>:start" before `fn` begins, then at every
        fleet yield point it hits, like any fleet thread. Blocks until
        that first park (or exit) — returning earlier would let the
        driver's next enabled() RACE the registration, making the
        recorded schedule timing-dependent and breaking replay."""
        def body():
            self.thread_started("scenario", name)
            try:
                self.yield_point("scenario:%s:start" % name)
                fn()
            finally:
                self.thread_exiting()
        t = threading.Thread(target=body, name="sched-%s" % name,
                             daemon=True)
        t.start()
        deadline = time.monotonic() + _QUIESCE_TIMEOUT_S
        with self._cv:
            while (name not in self._parked and name not in self._exited
                   and not self._free):
                if time.monotonic() > deadline:
                    raise SchedulerWedge(
                        "spawned thread %r failed to reach its start "
                        "park" % name)
                self._cv.wait(timeout=0.05)
        return t

    def await_quiescent(self, expected: Optional[int] = None,
                        timeout: float = _QUIESCE_TIMEOUT_S):
        """Block until every registered, live thread is parked (and,
        with `expected`, until at least that many threads exist)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                live = [n for n in self._names.values()]
                ok = all(n in self._parked for n in live)
                if ok and (expected is None
                           or len(live) + len(self._exited) >= expected):
                    return
                if time.monotonic() > deadline:
                    raise SchedulerWedge(
                        "threads failed to quiesce: live=%r parked=%r"
                        % (sorted(live), sorted(self._parked)))
                self._cv.wait(timeout=0.05)

    def enabled(self) -> List[str]:
        with self._cv:
            return sorted(self._parked)

    def parked_point(self, name: str) -> Optional[str]:
        with self._cv:
            return self._parked.get(name)

    def step(self, name: str, timeout: float = _QUIESCE_TIMEOUT_S):
        """Release thread `name` for one hop; block until it parks at
        its next yield point or exits — AND until any thread the hop
        spawned (scale-up, rollout refill) reaches its own first park,
        so the next enabled() snapshot is a pure function of the
        schedule, not of thread-startup timing."""
        with self._cv:
            if name not in self._parked:
                raise KeyError("thread %r is not parked" % name)
            reg0 = len(self._reg_log)
            self._parked.pop(name)
            self._cv.notify_all()
            deadline = time.monotonic() + timeout
            while (name in self._names.values()
                   and name not in self._parked
                   and name not in self._exited):
                if time.monotonic() > deadline:
                    raise SchedulerWedge(
                        "released thread %r failed to park or exit "
                        "within %.0fs (wedged between yield points)"
                        % (name, timeout))
                self._cv.wait(timeout=0.05)
            while not self._free:
                fresh = [n for n in self._reg_log[reg0:]
                         if n in self._names.values()
                         and n not in self._parked
                         and n not in self._exited]
                # announced spawns that have not even registered yet:
                # the synchronous thread_spawning() notice closes the
                # start()-to-registration window
                fresh += [n for n in self._pending_spawn
                          if n not in fresh]
                if not fresh:
                    break
                if time.monotonic() > deadline:
                    raise SchedulerWedge(
                        "thread(s) %r spawned by %r's hop failed to "
                        "reach their first park" % (fresh, name))
                self._cv.wait(timeout=0.05)

    def release_all(self):
        with self._cv:
            self._free = True
            self._parked.clear()
            self._pending_spawn.clear()
            self._cv.notify_all()


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------

class _Ctx(object):
    """Per-run scenario context handed to ops and invariant checks."""

    def __init__(self, fleet, sched, journal_path):
        self.fleet = fleet
        self.sched = sched
        self.journal_path = journal_path
        self.handles = []            # (handle, prompt, seed, max_new)
        self.submit_errors: List[BaseException] = []
        self.threads: List[threading.Thread] = []

    def submit(self, prompt, max_new, seed=0, tenant=None,
               stream=False, conn=None):
        h = self.fleet.submit(np.asarray(prompt, np.int32), max_new,
                              seed=seed, slo=None, tenant=tenant,
                              stream=stream, conn=conn)
        self.handles.append((h, list(prompt), seed, max_new))
        return h


class Scenario(object):
    """One explorable fleet scenario: `build()` constructs the fleet
    (ScriptEngine-backed), `ops` is the driver's scripted op list —
    each op is (label, when(ctx) -> bool, run(ctx)) and fires as a
    "main" schedule choice once its precondition holds — and
    `finished(ctx)` ends the controlled phase. Extra invariants beyond
    the common probes go in `check(ctx) -> [violation strings]`."""

    name = "scenario"
    n_replicas = 2
    expect_failures = False  # close-race: EngineFailed verdicts are ok
    expect_cancelled = False  # ISSUE 18: RequestCancelled verdicts ok

    def fleet_kw(self) -> dict:
        return {}

    def build(self, sched, journal_path) -> _Ctx:
        cfg = type("Cfg", (), {"max_len": 64})()
        params = {"pos": np.zeros((64, 4), np.float32)}
        kw = dict(
            n_replicas=self.n_replicas, journal_path=journal_path,
            heartbeat_timeout_s=3600.0, monitor_interval_s=0.001,
            affinity=False, auto_refill=False,
            engine_factory=ScriptEngine, scheduler_hook=sched,
        )
        kw.update(self.fleet_kw())
        fleet = ServingFleet(params, cfg, **kw)
        # idle replicas sleep this long per handshake with nothing to
        # do; under the controlled scheduler that wall time is pure
        # overhead (the driver serializes everything), so shrink it
        fleet._idle_wait_s = 0.0005
        return _Ctx(fleet, sched, journal_path)

    def ops(self) -> List[Tuple[str, Callable, Callable]]:
        return []

    def finished(self, ctx: _Ctx) -> bool:
        return all(h.done for h, _p, _s, _n in ctx.handles)

    def check(self, ctx: _Ctx) -> List[str]:
        return []


def _always(_ctx):
    return True


class SubmitKillScenario(Scenario):
    """The PR-6 drill as an explored schedule space: two requests, one
    replica killed while (potentially) holding both — journal-driven
    failover must land every request on the survivor with
    token-identical output, whatever the kill lands between."""

    name = "submit_kill"
    n_replicas = 2

    def ops(self):
        return [
            ("submit0", _always, lambda c: c.submit([3, 1, 4], 4, seed=1)),
            ("submit1", _always, lambda c: c.submit([2, 7], 3, seed=2)),
            ("kill_r0", _always, lambda c: c.fleet.kill_replica(0)),
        ]


class DemoteRouteBackScenario(Scenario):
    """The PR-8 fence-hole window: r0 finishes a request locally but
    has NOT yet reported it; the request is hedged away (demotion),
    the survivor dies, and the request routes BACK to demoted r0 —
    whose next handshake reports the completion of the SUPERSEDED
    submission. The fleet must refuse it (the in-flight fence); the
    `superseded_report` mutant accepts it and double-prepends the
    resume prefix — caught by the token-identity probe and the
    journal DFA's J005."""

    name = "demote_route_back"
    n_replicas = 2

    def _demote_ready(self, ctx):
        # r0 has journaled 2 of 3 tokens AND is parked at its sync
        # yield: token 3 is emitted and the completion is buffered but
        # UNREPORTED — the exact superseded-report window. A deviating
        # schedule can run r0 THROUGH the window (the request
        # completes); the op then fires as a harmless late demotion
        # instead of wedging the op queue
        if not ctx.handles:
            return False
        h = ctx.handles[0][0]
        if h.done:
            return True
        prog = ctx.fleet._journal.progress_of(h.rid)
        parked = ctx.sched.parked_point("r0.i1")
        return len(prog) >= 2 and parked == "replica:r0:sync"

    def _demote(self, ctx):
        with ctx.fleet._cond:
            ctx.fleet._demote_locked(0)

    def ops(self):
        return [
            ("submit0", _always, lambda c: c.submit([5, 9], 3, seed=3)),
            ("demote_r0", self._demote_ready, self._demote),
            ("kill_r1", _always, lambda c: c.fleet.kill_replica(1)),
        ]


class CloseRaceScenario(Scenario):
    """The PR-6 idempotent-reject window: a submit parks between its
    durable journal write and its routing critical section
    ("submit:commit") while a close() sweeps the open set — both sides
    reach the same rid's terminal bookkeeping, which must happen
    exactly once. The `double_reject` mutant counts it twice and
    drives stats()['lost'] negative."""

    name = "close_race"
    n_replicas = 1
    expect_failures = True

    def _spawn_submitter(self, ctx):
        def body():
            try:
                ctx.submit([1, 2, 3], 3, seed=4)
            except RuntimeError as exc:
                ctx.submit_errors.append(exc)
        ctx.threads.append(ctx.sched.spawn("submitter", body))

    def _spawn_closer(self, ctx):
        def body():
            # short join timeouts: every fleet thread is parked under
            # the controlled scheduler, so the joins MUST time out —
            # deterministically — and close() still finishes its sweep
            ctx.fleet.close(timeout=0.05)
        ctx.threads.append(ctx.sched.spawn("closer", body))

    def _submitter_committed(self, ctx):
        return (ctx.sched.parked_point("submitter") == "submit:commit"
                or "submitter" in ctx.sched._exited)

    def ops(self):
        return [
            ("spawn_submitter", _always, self._spawn_submitter),
            ("spawn_closer", self._submitter_committed,
             self._spawn_closer),
        ]

    def finished(self, ctx):
        return (len(ctx.threads) == 2
                and all(not t.is_alive() for t in ctx.threads))


class ScaleUpMidBurstScenario(Scenario):
    """ISSUE 11 elasticity: a burst of three requests hits a
    one-replica fleet whose autoscaler may spawn a second replica at
    any monitor sweep mid-burst. The explored space covers spawns
    landing between submits, between handshakes, and after the burst
    already drained — every request must still reach its oracle
    verdict exactly once, whatever the spawn interleaves with (a
    fresh replica joining routing must not double-route or strand
    inbox work)."""

    name = "scale_up_mid_burst"
    n_replicas = 1

    def fleet_kw(self):
        return {
            "min_replicas": 1, "max_replicas": 2,
            # every monitor sweep with open > live may spawn; no
            # cool-down so the schedule alone decides when
            "scale_up_open_per_replica": 1, "scale_cooldown_s": 0.0,
            "scale_down_idle_s": 1e9,
        }

    def ops(self):
        return [
            ("submit0", _always, lambda c: c.submit([4, 2], 3, seed=5)),
            ("submit1", _always, lambda c: c.submit([8, 1, 6], 4, seed=6)),
            ("submit2", _always, lambda c: c.submit([9], 3, seed=7)),
        ]


class DrainRetireRaceScenario(Scenario):
    """ISSUE 11 scale-down: replica r1 is gracefully retired
    (drain → journal-hedge → retire) while it may hold a request whose
    completion is decoded-but-unreported — the retire's clawback races
    the completion handshake. Exactly one verdict per rid must
    survive: the hedged copy resumes from the journaled prefix on r0,
    and r1's superseded report (if its handshake wins the race) must
    be refused by the in-flight fence, not double-answered."""

    name = "drain_retire_race"
    n_replicas = 2

    def _retire_ready(self, ctx):
        # the second submit routes to r1 (least-loaded tie-break);
        # retire once it journaled progress there — the
        # decoded-but-unreported window. A deviating schedule can run
        # the request to completion first; the op then fires as a
        # harmless no-work retirement instead of wedging the op queue
        if len(ctx.handles) < 2:
            return False
        h = ctx.handles[1][0]
        return h.done or len(ctx.fleet._journal.progress_of(h.rid)) >= 1

    def ops(self):
        return [
            ("submit0", _always, lambda c: c.submit([3, 3], 3, seed=8)),
            ("submit1", _always, lambda c: c.submit([7, 5], 3, seed=9)),
            ("retire_r1", self._retire_ready,
             lambda c: c.fleet.scale_down(1)),
        ]

    def check(self, ctx):
        st = ctx.fleet.stats()
        if st["replicas"][1]["state"] not in ("retired", "draining"):
            return ["scale_down(1) never retired r1 (state %r)"
                    % st["replicas"][1]["state"]]
        return []


class RolloutMigrationRaceScenario(Scenario):
    """ISSUE 11 live rollout racing a disaggregation migration: a
    tiered fleet (r0 prefill, r1 decode) serves one request — which
    migrates from r0 to r1 at first token — while a `roll_weights`
    (policy "migrate") swaps both replicas under it. The explored
    interleavings land the swap before, between, and after the
    migration's hedge; the probes pin token identity, exactly-once,
    and the journal DFA's J009 version fence (a done record must
    carry its final assignment's weights_version, whichever side of
    the swap completed it)."""

    name = "rollout_migration"
    n_replicas = 2

    def fleet_kw(self):
        return {"replica_tier": ["prefill", "decode"]}

    def _spawn_roller(self, ctx):
        def body():
            ctx.fleet.roll_weights(
                params={"pos": np.zeros((64, 4), np.float32)},
                version=7, policy="migrate")
        ctx.threads.append(ctx.sched.spawn("roller", body))

    def _submitted(self, ctx):
        return bool(ctx.handles)

    def ops(self):
        return [
            ("submit0", _always, lambda c: c.submit([6, 2, 8], 4,
                                                    seed=11)),
            ("spawn_roller", self._submitted, self._spawn_roller),
        ]

    def finished(self, ctx):
        return (all(h.done for h, _p, _s, _n in ctx.handles)
                and len(ctx.threads) == 1
                and not ctx.threads[0].is_alive())

    def check(self, ctx):
        out = []
        st = ctx.fleet.stats()
        if st["weights_version"] != 7:
            out.append("rollout never committed version 7 (%r)"
                       % st["weights_version"])
        if st["rollouts_completed"] != 1:
            out.append("rollouts_completed == %r, expected 1"
                       % st["rollouts_completed"])
        return out


class IntegrityTripScenario(Scenario):
    """ISSUE 15 quarantine + taint-aware resume: a tiered fleet (r0
    prefill, r1 decode) serves two requests — a 1-token request whose
    completion handshake the trip can race, and a longer one that
    MIGRATES from r0 to r1 at first token — while an integrity trip
    (the canary-mismatch path, scripted like DemoteRouteBack's
    demotion: canaries themselves are wall-clock-driven, which the
    explorer sizes out) quarantines r0 once it has journaled progress.
    The explored interleavings land the trip before, during, and after
    the migration's hedge and the completion's handshake; the probes
    pin token identity (the taint window re-decodes to the SAME
    tokens on the survivor — the scripted engine is honest), exactly-
    once verdicts, and the journal DFA — now including J010: the
    integrity record's taint windows must be well-formed, re-decoded
    tokens must lie inside them, and nothing may land from the
    quarantined incarnation after its integrity event."""

    name = "integrity_trip"
    n_replicas = 2

    def fleet_kw(self):
        return {"replica_tier": ["prefill", "decode"]}

    def _trip_ready(self, ctx):
        # fire once ANY journaled progress exists (the decoded-but-
        # unreported / mid-migration window); a deviating schedule may
        # have run a request to completion first — the trip then fires
        # as a harmless no-taint quarantine instead of wedging the ops
        if len(ctx.handles) < 2:
            return False
        return any(h.done
                   or len(ctx.fleet._journal.progress_of(h.rid)) >= 1
                   for h, _p, _s, _n in ctx.handles)

    def _trip(self, ctx):
        from ..serving.integrity import IntegrityError

        fleet = ctx.fleet
        with fleet._cond:
            fleet._integrity_trip_locked(
                0, fleet._replicas[0],
                IntegrityError("scripted canary mismatch on r0",
                               kind="canary", replica="r0"))
        fleet._flush_journal()

    def ops(self):
        return [
            ("submit0", _always, lambda c: c.submit([5, 3], 1, seed=31)),
            ("submit1", _always, lambda c: c.submit([2, 8, 4], 4,
                                                    seed=32)),
            ("trip_r0", self._trip_ready, self._trip),
        ]

    def check(self, ctx):
        out = []
        st = ctx.fleet.stats()
        if st["integrity_trips"] != 1:
            out.append("integrity_trips == %r, expected exactly 1 "
                       "(quarantine must be exactly-once)"
                       % st["integrity_trips"])
        if st["replicas"][0]["state"] != "dead":
            out.append("tripped replica r0 not quarantined (state %r)"
                       % st["replicas"][0]["state"])
        return out


class TenantFairnessScenario(Scenario):
    """ISSUE 12 multi-tenancy: a burst tenant's three requests race a
    higher-weight SLA tenant's request through the router's new WFQ
    dispatch hop (wfq_window=1 — at most one request is dispatched at
    a time, so the fair queue, not inbox order, decides who runs) on
    a two-replica fleet, with one replica killed mid-burst so the
    failover resubmission path (which BYPASSES the fair queue —
    survival beats fairness) interleaves with WFQ dispatch. The
    probes pin the multi-consumer contract under every explored
    schedule: each tenant's request reaches its oracle verdict
    exactly once (the burst cannot starve the SLA tenant into a lost
    or doubled verdict), per-tenant accounting balances
    (submitted == completed for both), nothing is quota-shed (the
    buckets are sized generously — fairness, not quota, is under
    test), and the journal's typed tenant side-band replays green
    through the DFA."""

    name = "tenant_fairness"
    n_replicas = 2

    def fleet_kw(self):
        from ..serving.tenancy import TenantRegistry

        reg = TenantRegistry()
        # generous buckets: quota never sheds here (determinism under
        # wall-clock-free exploration); the SLA tenant's 4x weight is
        # what the WFQ hop must honor
        reg.add("burst", rate=1000.0, burst=1000.0, weight=1.0,
                slo=None)
        reg.add("sla", rate=1000.0, burst=1000.0, weight=4.0,
                slo=None)
        return {"tenants": reg, "wfq_window": 1}

    def ops(self):
        return [
            ("burst0", _always,
             lambda c: c.submit([4, 4], 3, seed=21, tenant="burst")),
            ("burst1", _always,
             lambda c: c.submit([6, 1], 3, seed=22, tenant="burst")),
            ("sla0", _always,
             lambda c: c.submit([2, 9, 5], 4, seed=23, tenant="sla")),
            ("burst2", _always,
             lambda c: c.submit([8], 3, seed=24, tenant="burst")),
            ("kill_r0", _always, lambda c: c.fleet.kill_replica(0)),
        ]

    def check(self, ctx):
        out = []
        st = ctx.fleet.stats()
        if st["quota_shed"]:
            out.append("quota shed %d request(s) under generous "
                       "buckets" % st["quota_shed"])
        for name, want in (("burst", 3), ("sla", 1)):
            t = (st["tenants"] or {}).get(name, {})
            if t.get("submitted") != want or t.get("completed") != want:
                out.append(
                    "tenant %r accounting off: submitted %r / "
                    "completed %r, expected %d of each"
                    % (name, t.get("submitted"), t.get("completed"),
                       want))
        return out


class KVHandoffRaceScenario(Scenario):
    """ISSUE 16 durable-KV handoff under adversarial interleaving: a
    tiered fleet (r0 prefill, r1 decode) shares a pre-seeded
    `KVBlockStore`, so the request's migration at first token attaches
    a checksummed block package to the re-route — while (a) a store
    EVICTION races the package build on the source side (the chain the
    router credited may be gone by the time `chain_fetch` runs: before
    → no package, after → package shipped; both must serve), and (b)
    an integrity TRIP quarantines the decode target r1, so a shipped
    package's holder can die tainted before, during, or after
    accounting for it. The probes pin token identity and exactly-once
    verdicts as ever, plus the journal DFA's new J011 handoff fence:
    every assign that shipped a package must trace to a done carrying
    a verified-import or counted-fallback outcome (the ScriptEngine
    reports none, so every explored path exercises the fleet's
    defaulted-outcome cover), and no done may claim an import its
    assignment never shipped."""

    name = "kv_handoff_race"
    n_replicas = 2

    def fleet_kw(self):
        from ..serving.kv_store import KVBlockStore, make_block_record
        from ..serving.prefix_cache import fold_key

        # pre-seeded store: one fabricated record covering the
        # prompt's single closed block (2, 8). The payload bytes are
        # arbitrary — the ScriptEngine never uploads them — but the
        # crc is honest, so the store serves the record and the fleet
        # genuinely builds and ships a package
        store = KVBlockStore(block_tokens=2)
        self._block_key = fold_key(0, (2, 8))
        store.put(make_block_record(self._block_key, 0, (2, 8), 1.0,
                                    b"scripted-block--", []))
        return {
            "kv_store": store,
            "replica_tier": ["prefill", "decode"],
            "engine_kw": {"prefix_cache_tokens": 64,
                          "kv_block_tokens": 2},
        }

    def _progressed(self, ctx):
        # fire once ANY journaled progress exists — the window where
        # the migration's package build / the target's import race the
        # eviction and the trip; a deviating schedule may have
        # finished the request first, firing the op harmlessly late
        if not ctx.handles:
            return False
        h = ctx.handles[0][0]
        return (h.done
                or len(ctx.fleet._journal.progress_of(h.rid)) >= 1)

    def _evict(self, ctx):
        ctx.fleet.kv_store.evict(self._block_key)

    def _on_target(self, ctx):
        # fire once the migrated copy (package attached) is r1's — or
        # the request already finished: the trip then races r1's
        # accounting for the package it received, not the pre-
        # migration prefill (which the plain integrity_trip scenario
        # already covers)
        if not ctx.handles:
            return False
        h = ctx.handles[0][0]
        if h.done:
            return True
        a = ctx.fleet._journal.assigned_to(h.rid)
        return a is not None and a[0] == "r1"

    def _trip(self, ctx):
        from ..serving.integrity import IntegrityError

        fleet = ctx.fleet
        with fleet._cond:
            fleet._integrity_trip_locked(
                1, fleet._replicas[1],
                IntegrityError("scripted canary mismatch on r1",
                               kind="canary", replica="r1"))
        fleet._flush_journal()

    def ops(self):
        return [
            ("submit0", _always, lambda c: c.submit([2, 8, 4], 4,
                                                    seed=41)),
            ("evict_store", self._progressed, self._evict),
            ("trip_r1", self._on_target, self._trip),
        ]

    def check(self, ctx):
        out = []
        st = ctx.fleet.stats()
        if st["integrity_trips"] != 1:
            out.append("integrity_trips == %r, expected exactly 1"
                       % st["integrity_trips"])
        if st["replicas"][1]["state"] != "dead":
            out.append("tripped replica r1 not quarantined (state %r)"
                       % st["replicas"][1]["state"])
        # the package-accounting fence itself (every shipped package
        # traces to a verified import or a counted fallback) is J011,
        # already replayed by the harness's verify_journal probe —
        # including the superseded-assignment path where a later
        # package-less assign lawfully absorbs the account
        return out


class StreamDisconnectRaceScenario(Scenario):
    """The ISSUE 18 wire races: two streamed requests; one client
    cancels (a dropped connection's path) while its LAST token's
    completion handshake may already be in flight — the
    cancel-vs-accept race the `_cancelled_rids` fence decides (a late
    completion must count `cancel_late_refused`, never a duplicate or
    a resurrection) — and the OTHER request's holder is killed
    mid-stream, so failover must splice its stream token-exactly (no
    token re-pushed, none skipped: the `_stream_sent` cursor vs the
    resumed journal prefix). The streamed buffers are probed against
    the ScriptEngine oracle; the journal DFA replays the `cancelled`
    terminal and the conn/stream side-bands on every explored
    schedule."""

    name = "stream_disconnect_race"
    n_replicas = 2
    expect_cancelled = True

    def ops(self):
        return [
            ("submit0", _always,
             lambda c: c.submit([3, 1, 4], 4, seed=21, stream=True,
                                conn="c0")),
            ("submit1", _always,
             lambda c: c.submit([2, 7], 6, seed=22, stream=True,
                                conn="c1")),
            ("cancel0", self._near_done0, self._cancel0),
            ("kill_holder1", self._streaming1, self._kill_holder1),
        ]

    def _near_done0(self, ctx):
        # fire once rid0's penultimate token is journaled: the cancel
        # then races the final-token completion handshake. A deviating
        # schedule may complete rid0 first — the cancel fires
        # harmlessly late (fleet.cancel returns False on a done rid)
        if not ctx.handles:
            return False
        h = ctx.handles[0][0]
        return (h.done
                or len(ctx.fleet._journal.progress_of(h.rid)) >= 3)

    def _cancel0(self, ctx):
        ctx.fleet.cancel(ctx.handles[0][0].rid)

    def _streaming1(self, ctx):
        # rid1 is mid-stream: assigned, with at least one journaled
        # token but not all of them (or already done — late kill is a
        # no-op, the harmless-late rule every kill op follows)
        if len(ctx.handles) < 2:
            return False
        h = ctx.handles[1][0]
        return (h.done
                or len(ctx.fleet._journal.progress_of(h.rid)) >= 1)

    def _kill_holder1(self, ctx):
        h = ctx.handles[1][0]
        if h.done:
            return
        a = ctx.fleet._journal.assigned_to(h.rid)
        if a is None:
            return
        ctx.fleet.kill_replica(int(str(a[0])[1:]))

    def check(self, ctx):
        out = []
        for h, prompt, seed, max_new in ctx.handles:
            oracle = script_tokens(prompt, seed, max_new)
            with h._stream_cv:
                buf = list(h._stream_buf)
                closed = h._stream_closed
            if buf != oracle[:len(buf)]:
                out.append(
                    "rid %d streamed prefix diverges from the oracle: "
                    "buf %r vs %r (a failover re-pushed or skipped a "
                    "streamed token)" % (h.rid, buf, oracle))
            if not closed:
                out.append("rid %d stream never closed" % h.rid)
            if h.error is None and buf != oracle:
                out.append(
                    "rid %d completed but streamed only %d of %d "
                    "token(s) — stream != result"
                    % (h.rid, len(buf), len(oracle)))
        st = ctx.fleet.stats()
        if st["cancelled"] == 0 and st["completed"] != len(ctx.handles):
            out.append(
                "no cancel landed yet completed == %d of %d"
                % (st["completed"], len(ctx.handles)))
        if st["duplicate_refused"] != 0:
            out.append(
                "duplicate_refused == %d: a cancelled rid's late "
                "completion was misfiled (cancel_late_refused is the "
                "only lawful bucket)" % st["duplicate_refused"])
        return out


SCENARIOS: Dict[str, Callable[[], Scenario]] = {
    "submit_kill": SubmitKillScenario,
    "demote_route_back": DemoteRouteBackScenario,
    "close_race": CloseRaceScenario,
    "scale_up_mid_burst": ScaleUpMidBurstScenario,
    "drain_retire_race": DrainRetireRaceScenario,
    "rollout_migration": RolloutMigrationRaceScenario,
    "tenant_fairness": TenantFairnessScenario,
    "integrity_trip": IntegrityTripScenario,
    "kv_handoff_race": KVHandoffRaceScenario,
    "stream_disconnect_race": StreamDisconnectRaceScenario,
}


# ---------------------------------------------------------------------------
# driving one schedule
# ---------------------------------------------------------------------------

class RunResult(object):
    def __init__(self, scenario_name, journal_path=None):
        self.scenario = scenario_name
        self.journal_path = journal_path
        self.trace: List[Tuple[Tuple[str, ...], str]] = []
        self.violations: List[str] = []

    @property
    def schedule(self) -> List[str]:
        return [chosen for _enabled, chosen in self.trace]

    def __repr__(self):
        return ("RunResult(%s, %d steps, %s)"
                % (self.scenario, len(self.trace),
                   "OK" if not self.violations
                   else "%d violation(s)" % len(self.violations)))


def format_schedule(schedule: Sequence[str]) -> str:
    return ",".join(schedule)


# how many consecutive hops the default policy lets one thread run
# before rotating: long enough to cover a multi-yield window (a crash
# path is sync-raise -> journal-flush -> exit, three hops), short
# enough that every thread keeps making progress (liveness)
_STICKY_HOPS = 3


def _default_choice(enabled: List[str], last: Optional[str],
                    streak: int) -> str:
    """The deterministic baseline schedule deviations are counted
    against: 'main' first (scenario ops fire as soon as their
    preconditions hold), then STICKY round-robin — continue the thread
    that just ran for up to `_STICKY_HOPS` hops (the CHESS
    non-preemptive baseline, bounded for liveness), then rotate."""
    if "main" in enabled:
        return "main"
    if last in enabled and streak < _STICKY_HOPS:
        return last
    if last in enabled:
        i = enabled.index(last)
        return enabled[(i + 1) % len(enabled)]
    for name in enabled:
        if last is None or name > last:
            return name
    return enabled[0]


def run_schedule(scenario: Scenario, decisions: Sequence[str],
                 journal_path: str,
                 max_steps: int = 400) -> RunResult:
    """Run `scenario` under the controlled scheduler, following
    `decisions` (thread names / "main") while they last and the
    default policy after; record the full trace; check the invariant
    probes. Deterministic: the same decisions always produce the same
    trace and the same verdict."""
    from .diagnostics import format_diag
    from .protocol_lint import verify_journal

    sched = ControlledScheduler()
    result = RunResult(scenario.name, journal_path)
    ctx = scenario.build(sched, journal_path)
    fleet = ctx.fleet
    try:
        sched.await_quiescent(expected=scenario.n_replicas + 1)
        ops = list(scenario.ops())
        op_i = 0
        di = 0
        last = None
        streak = 0
        steps = 0
        while steps < max_steps:
            if op_i >= len(ops) and scenario.finished(ctx):
                break
            enabled = sched.enabled()
            if op_i < len(ops) and ops[op_i][1](ctx):
                enabled = ["main"] + enabled
            if not enabled:
                if op_i >= len(ops):
                    break  # every registered thread exited, nothing left
                result.violations.append(
                    "wedge: op %r blocked with no runnable thread"
                    % (ops[op_i][0],))
                break
            if di < len(decisions):
                choice = decisions[di]
                di += 1
                if choice not in enabled:
                    result.violations.append(
                        "schedule-divergence: decision %d chose %r but "
                        "enabled=%r (replay of a stale schedule?)"
                        % (di - 1, choice, enabled))
                    break
            else:
                choice = _default_choice(enabled, last, streak)
            result.trace.append((tuple(enabled), choice))
            streak = streak + 1 if choice == last else 1
            last = choice
            steps += 1
            if choice == "main":
                label, _when, run = ops[op_i]
                op_i += 1
                run(ctx)
            else:
                sched.step(choice)
        else:
            # the loop ran out of steps — but finishing ON the last
            # step is a finish, not a wedge
            if not (op_i >= len(ops) and scenario.finished(ctx)):
                result.violations.append(
                    "wedge: scenario did not finish within %d "
                    "schedule steps" % max_steps)
    except SchedulerWedge as exc:
        result.violations.append("wedge: %s" % exc)
    finally:
        sched.release_all()
        try:
            fleet.close()
        except Exception as exc:  # audit raises ride the violations
            result.violations.append("close: %r" % exc)
        for t in ctx.threads:
            t.join(timeout=_QUIESCE_TIMEOUT_S)

    # -- invariant probes ------------------------------------------------
    from ..serving.fleet import (EngineFailed, RequestCancelled,
                                 RequestJournal)
    for h, prompt, seed, max_new in ctx.handles:
        if not h.done:
            result.violations.append(
                "rid %d never reached a verdict" % h.rid)
            continue
        if h.error is not None:
            if isinstance(h.error, RequestCancelled):
                # a scripted client-cancel verdict (ISSUE 18): lawful
                # only where the scenario stages one; its journaled
                # prefix is still probed by the scenario's check()
                # and the DFA's J005 bar on the cancelled record
                if not scenario.expect_cancelled:
                    result.violations.append(
                        "rid %d cancelled but the scenario scripts no "
                        "cancel" % h.rid)
            elif not (scenario.expect_failures
                      and isinstance(h.error, EngineFailed)):
                result.violations.append(
                    "rid %d failed unexpectedly: %r" % (h.rid, h.error))
            continue
        expected = script_tokens(prompt, seed, max_new)
        if list(h.tokens or []) != expected:
            result.violations.append(
                "rid %d token identity violated: got %r, oracle %r "
                "(a stale-incarnation report was accepted?)"
                % (h.rid, h.tokens, expected))
    st = fleet.stats()
    if st["lost"] != 0:
        result.violations.append(
            "stats()['lost'] == %d (submitted %d, completed %d, "
            "rejected %d, expired %d, cancelled %d, open %d)"
            % (st["lost"], st["submitted"], st["completed"],
               st["rejected"], st["expired"], st["cancelled"],
               st["open"]))
    if st["completed"] > len(ctx.handles):
        result.violations.append(
            "completed %d > %d submitted: a request was answered twice"
            % (st["completed"], len(ctx.handles)))
    diags = verify_journal(journal_path, expect_closed=True)
    result.violations.extend(
        "journal: %s" % format_diag(d) for d in diags)
    if RequestJournal.recover(journal_path):
        result.violations.append(
            "journal mirror/file divergence: recover() found open "
            "rids after close()")
    result.violations.extend(scenario.check(ctx))
    return result


# ---------------------------------------------------------------------------
# bounded-preemption enumeration
# ---------------------------------------------------------------------------

class ExploreReport(object):
    def __init__(self, scenario_name):
        self.scenario = scenario_name
        self.runs = 0
        self.violation: Optional[RunResult] = None

    @property
    def ok(self) -> bool:
        return self.violation is None

    def __repr__(self):
        return ("ExploreReport(%s, %d schedules, %s)"
                % (self.scenario, self.runs,
                   "clean" if self.ok else "VIOLATION"))


def explore(scenario_factory: Callable[[], Scenario], tmp_dir: str,
            max_preemptions: int = 1, max_schedules: int = 64,
            max_steps: int = 400) -> ExploreReport:
    """Systematic bounded-preemption sweep: run the default schedule,
    then branch every choice point where another thread was enabled,
    spending at most `max_preemptions` deviations per schedule (the
    CHESS bound), capped at `max_schedules` runs. Stops at the first
    violating schedule — the result carries it, replayable."""
    import os

    scenario = scenario_factory()
    report = ExploreReport(scenario.name)
    seen = set()
    # iterative-deepening order (the CHESS bound made into a search
    # order): exhaust every 1-preemption schedule before any
    # 2-preemption one, and within a level branch LATE choice points
    # first — a heisenbug window sits near the end of the op script
    # far more often than the start
    queue: List[Tuple[Tuple[str, ...], int]] = [((), 0)]
    while queue and report.runs < max_schedules:
        best = min(range(len(queue)),
                   key=lambda i: (queue[i][1], -len(queue[i][0])))
        prefix, n_pre = queue.pop(best)
        jpath = os.path.join(
            tmp_dir, "explore_%s_%04d.jsonl"
            % (scenario.name, report.runs))
        result = run_schedule(scenario_factory(), list(prefix), jpath,
                              max_steps=max_steps)
        report.runs += 1
        if result.violations:
            report.violation = result
            return report
        schedule = result.schedule
        for i in range(len(prefix), len(result.trace)):
            enabled, chosen = result.trace[i]
            for alt in enabled:
                if alt == chosen:
                    continue
                # one deviation = one preemption. The STICKY default
                # policy continues the deviated-to thread afterwards,
                # so a multi-hop window (a crash path is sync-raise ->
                # journal-flush -> exit) is reachable with a single
                # deviation — the CHESS small-bound insight holds
                # without free-continuation bookkeeping.
                if n_pre + 1 > max_preemptions:
                    continue
                branch = tuple(schedule[:i]) + (alt,)
                if branch not in seen:
                    seen.add(branch)
                    queue.append((branch, n_pre + 1))
    return report
