"""CLI for paddle_tpu.analysis.

    python -m paddle_tpu.analysis --all
    python -m paddle_tpu.analysis program path/to/entry.py [--fetch NAME]
    python -m paddle_tpu.analysis trace [files...]
    python -m paddle_tpu.analysis locks [files-or-dirs...]
    python -m paddle_tpu.analysis bands [files...]
    python -m paddle_tpu.analysis shard [files-or-dirs...]
    python -m paddle_tpu.analysis journal <journal.jsonl> [--expect-closed]
    python -m paddle_tpu.analysis explore [--scenario NAME] [--preemptions K]
                                          [--max-schedules N] [--replay CSV]

Exit status: 0 when every finding is covered by the baseline
(`paddle_tpu/analysis/baseline.txt` unless --baseline overrides) and
no baseline entry is stale, 1 on a NEW finding or a stale entry
(the tier-1 self-check rejects both), 2 on usage errors.
`--write-baseline` rewrites the baseline to accept the current
findings (each entry still needs a hand-written justification —
the tool writes a TODO marker you must replace).

`program <entry.py>` executes the file (it is expected to build into
`fluid.default_main_program()` — the normal shape of a model script)
and verifies the resulting program; feeds are the program's `is_data`
vars, fetches default to the last op's outputs or --fetch names.
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys
from typing import List

from . import diagnostics
from .diagnostics import Diagnostic, format_diag, load_baseline, split_new


def _report(diags: List[Diagnostic], baseline_path, write_baseline,
            scope=None, out=sys.stdout, hygiene=True) -> int:
    """`scope` limits STALE detection to the given code prefixes
    ("P"/"T"/"L"): a partial run (one analyzer) must not read the other
    analyzers' baseline entries as stale. `hygiene=False` skips the
    TODO-justification audit of the baseline file — an ad-hoc target
    (a journal file) must answer for ITS findings only, not for repo
    baseline debt."""
    baseline = load_baseline(baseline_path)
    new, old, stale = split_new(diags, baseline)
    # a TODO/empty justification is a defect of the baseline FILE, not
    # of this run's findings — checked unscoped on every non-write run
    unjustified = [fp for fp, why in baseline.items()
                   if not why or "TODO" in why] if hygiene else []
    if scope is not None:
        stale = [fp for fp in stale if fp[:1] in scope]
    for d in old:
        out.write(format_diag(d, baselined=True) + "\n")
    for d in new:
        out.write(format_diag(d) + "\n")
    for fp in stale:
        out.write("stale baseline entry (fix landed? remove it): %s\n"
                  % fp)
    if not write_baseline:
        for fp in unjustified:
            out.write("unjustified baseline entry (replace the TODO "
                      "with a real reason): %s\n" % fp)
    out.write("%d finding%s (%d new, %d baselined, %d stale baseline "
              "entr%s)\n"
              % (len(diags), "" if len(diags) == 1 else "s", len(new),
                 len(old), len(stale), "y" if len(stale) == 1 else "ies"))
    if write_baseline:
        path = baseline_path or diagnostics.default_baseline_path()
        with open(path, "w") as f:
            f.write("# paddle_tpu.analysis baseline — accepted findings."
                    "\n# Every entry MUST carry a one-line justification"
                    " after '  #'.\n# Format: <CODE> <path>::<symbol>::"
                    "<detail>  # <why this is accepted>\n")
            written = set()
            for d in sorted(diags, key=lambda d: d.fingerprint):
                if d.fingerprint in written:
                    continue  # one entry per fingerprint, not per site
                written.add(d.fingerprint)
                why = baseline.get(d.fingerprint,
                                   "TODO: justify or fix")
                f.write("%s  # %s\n" % (d.fingerprint, why))
        out.write("baseline written: %s (%d entries)\n"
                  % (path, len(written)))
        return 0
    # stale and TODO-justified entries fail too: the tier-1 self-check
    # rejects both, so a green lint.sh must imply a green tier-1 gate
    return 1 if (new or stale or unjustified) else 0


def _cmd_program(args, baseline, write_baseline) -> int:
    # the entry script either builds into the default programs (bare
    # layer calls) or builds its own Program objects (the program_guard
    # idiom) — verify BOTH: the guarded default pair and every Program
    # left in the script's globals. An entry that built nothing is a
    # usage error, never a silent '0 findings'.
    sys.path.insert(0, os.path.dirname(os.path.abspath(args.entry)) or ".")
    import paddle_tpu.fluid as fluid

    from .program_lint import verify_program

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        mod = runpy.run_path(args.entry, run_name="__analysis__")
    base = os.path.basename(args.entry)
    programs = []
    if main.global_block().ops:
        programs.append(("<%s>" % base, main))
    if startup.global_block().ops:
        programs.append(("<%s:startup>" % base, startup))
    seen = {id(p) for _, p in programs}
    for name in sorted(mod):
        val = mod[name]
        if (isinstance(val, fluid.Program) and id(val) not in seen
                and val.global_block().ops):
            seen.add(id(val))
            programs.append(("<%s:%s>" % (base, name), val))
    if not programs:
        sys.stderr.write(
            "error: %s built no non-empty Program — build into the "
            "default programs or leave your Program objects in module "
            "globals\n" % args.entry)
        return 2
    diags = []
    for label, prog in programs:
        diags.extend(verify_program(prog, fetches=args.fetch or (),
                                    label=label))
    # an ad-hoc entry cannot assess baseline staleness at all
    return _report(diags, baseline, write_baseline, scope=())


def _lint_args_paths(lint_paths, paths):
    """Run an AST linter over CLI paths; a typo'd path is a usage
    error (exit 2), not a finding and not a traceback."""
    try:
        return lint_paths(paths or None)
    except (FileNotFoundError, SyntaxError, ValueError) as e:
        # SyntaxError: a non-parseable target file is equally a usage
        # error, not "a new finding" and not a traceback
        sys.stderr.write("error: %s\n" % e)
        return None


def _cmd_trace(args, baseline, write_baseline) -> int:
    from .trace_lint import lint_paths

    diags = _lint_args_paths(lint_paths, args.paths)
    if diags is None:
        return 2
    # explicit paths lint a SUBSET of files: entries for unlinted files
    # are out of scope, not stale — only the default full-scope run can
    # judge staleness for its analyzer
    return _report(diags, baseline, write_baseline,
                   scope=() if args.paths else ("T",))


def _cmd_locks(args, baseline, write_baseline) -> int:
    from .lock_lint import lint_paths

    diags = _lint_args_paths(lint_paths, args.paths)
    if diags is None:
        return 2
    return _report(diags, baseline, write_baseline,
                   scope=() if args.paths else ("L",))


def _cmd_bands(args, baseline, write_baseline) -> int:
    from .band_lint import lint_paths

    diags = _lint_args_paths(lint_paths, args.paths)
    if diags is None:
        return 2
    return _report(diags, baseline, write_baseline,
                   scope=() if args.paths else ("B",))


def _cmd_shard(args, baseline, write_baseline) -> int:
    from .shard_lint import lint_paths

    diags = _lint_args_paths(lint_paths, args.paths)
    if diags is None:
        return 2
    return _report(diags, baseline, write_baseline,
                   scope=() if args.paths else ("S",))


def _cmd_all(args, baseline, write_baseline) -> int:
    from . import collect_diagnostics
    from .diagnostics import REPO_SCOPE_CODES

    # --all runs the repo-scope analyzers; J-code entries (journal
    # files are runtime artifacts) are out of scope, never stale here
    return _report(collect_diagnostics(), baseline, write_baseline,
                   scope=REPO_SCOPE_CODES)


def _cmd_journal(args, baseline, write_baseline) -> int:
    from .protocol_lint import verify_journal

    try:
        diags = verify_journal(args.path,
                               expect_closed=args.expect_closed)
    except FileNotFoundError as e:
        sys.stderr.write("error: %s\n" % e)
        return 2
    # a journal is an ad-hoc target like `program`: no staleness scope,
    # and repo-baseline hygiene (TODO entries) is not ITS failure
    return _report(diags, baseline, write_baseline, scope=(),
                   hygiene=False)


def _cmd_explore(args, baseline, write_baseline) -> int:
    import tempfile

    from .sched_explore import SCENARIOS

    names = sorted(SCENARIOS) if args.scenario == "all" \
        else [args.scenario]
    for name in names:
        if name not in SCENARIOS:
            sys.stderr.write("error: unknown scenario %r (have: %s)\n"
                             % (name, ", ".join(sorted(SCENARIOS))))
            return 2
    if args.journal_dir:
        # keep the run's journals where the caller (tools/lint.sh's
        # protocol gate) can re-verify each with `analysis journal`
        tmp = args.journal_dir
        os.makedirs(tmp, exist_ok=True)
        cleanup = None
    else:
        tmp = tempfile.mkdtemp(prefix="paddle_tpu_explore_")
        cleanup = tmp
    try:
        return _run_explore(args, names, tmp)
    finally:
        if cleanup is not None:
            import shutil

            shutil.rmtree(cleanup, ignore_errors=True)


def _run_explore(args, names, tmp) -> int:
    from .sched_explore import (SCENARIOS, explore, format_schedule,
                                run_schedule)

    rc = 0
    if args.replay is not None:
        decisions = [c for c in args.replay.split(",") if c]
        name = names[0]
        result = run_schedule(SCENARIOS[name](), decisions,
                              os.path.join(tmp, "replay.jsonl"),
                              max_steps=args.max_steps)
        sys.stdout.write("replay %s: %d steps, %d violation(s)\n"
                         % (name, len(result.trace),
                            len(result.violations)))
        for v in result.violations:
            sys.stdout.write("  violation: %s\n" % v)
        return 1 if result.violations else 0
    for name in names:
        report = explore(SCENARIOS[name], tmp,
                         max_preemptions=args.preemptions,
                         max_schedules=args.max_schedules,
                         max_steps=args.max_steps)
        if report.ok:
            sys.stdout.write(
                "%s: %d schedule(s) explored, no violation\n"
                % (name, report.runs))
        else:
            rc = 1
            sys.stdout.write(
                "%s: VIOLATION after %d schedule(s)\n"
                % (name, report.runs))
            for v in report.violation.violations:
                sys.stdout.write("  violation: %s\n" % v)
            sys.stdout.write(
                "  replay with: python -m paddle_tpu.analysis explore "
                "--scenario %s --replay '%s'\n"
                % (name, format_schedule(report.violation.schedule)))
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m paddle_tpu.analysis")
    p.add_argument("--all", action="store_true",
                   help="run every analyzer over the repo")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: packaged baseline.txt)")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept current findings into the baseline")
    sub = p.add_subparsers(dest="cmd")
    sp = sub.add_parser("program", help="verify a program-building script")
    sp.add_argument("entry")
    sp.add_argument("--fetch", action="append", default=[])
    st = sub.add_parser("trace", help="trace-hazard lint")
    st.add_argument("paths", nargs="*")
    sl = sub.add_parser("locks", help="lock-discipline lint")
    sl.add_argument("paths", nargs="*")
    sb = sub.add_parser("bands", help="band-lifecycle verify (B-codes)")
    sb.add_argument("paths", nargs="*")
    ss = sub.add_parser("shard", help="mesh sharding-spec lint (S-codes)")
    ss.add_argument("paths", nargs="*")
    sj = sub.add_parser("journal",
                        help="verify a RequestJournal file (J-codes)")
    sj.add_argument("path")
    sj.add_argument("--expect-closed", action="store_true",
                    help="also require every rid to have a terminal "
                         "record (the post-close() invariant)")
    se = sub.add_parser("explore",
                        help="deterministic fleet schedule exploration")
    se.add_argument("--scenario", default="all",
                    help="scenario name, or 'all' (default)")
    se.add_argument("--preemptions", type=int, default=1)
    se.add_argument("--max-schedules", type=int, default=200)
    se.add_argument("--max-steps", type=int, default=400)
    se.add_argument("--replay", default=None,
                    help="comma-separated schedule to replay verbatim "
                         "(requires a single --scenario)")
    se.add_argument("--journal-dir", default=None,
                    help="write per-schedule journals here (kept) "
                         "instead of a throwaway temp dir")
    args = p.parse_args(argv)

    if args.write_baseline and not args.all and args.baseline is None:
        # a partial run sees only its own analyzer's findings; writing
        # the SHARED baseline from it would silently delete every other
        # analyzer's justified entries
        p.error("--write-baseline without --all would clobber the "
                "shared baseline with a partial view; pass --all or an "
                "explicit --baseline path")
    # NO blanket try/except here: an entry script failing under
    # `program` must surface its full traceback, not masquerade as a
    # usage error (path typos are handled inside _cmd_trace/_cmd_locks)
    if args.all:
        return _cmd_all(args, args.baseline, args.write_baseline)
    if args.cmd == "program":
        return _cmd_program(args, args.baseline, args.write_baseline)
    if args.cmd == "trace":
        return _cmd_trace(args, args.baseline, args.write_baseline)
    if args.cmd == "locks":
        return _cmd_locks(args, args.baseline, args.write_baseline)
    if args.cmd == "bands":
        return _cmd_bands(args, args.baseline, args.write_baseline)
    if args.cmd == "shard":
        return _cmd_shard(args, args.baseline, args.write_baseline)
    if args.cmd == "journal":
        return _cmd_journal(args, args.baseline, args.write_baseline)
    if args.cmd == "explore":
        if args.replay is not None and args.scenario == "all":
            p.error("--replay needs a single --scenario (a schedule "
                    "only means anything against the scenario that "
                    "recorded it)")
        return _cmd_explore(args, args.baseline, args.write_baseline)
    p.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
