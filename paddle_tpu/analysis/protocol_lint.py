"""Journal state-machine verifier: a per-rid DFA over `RequestJournal`
files (ISSUE 9 tentpole, engine 1 of 2).

The serving fleet's correctness story is its request journal: every
submit/assign/progress/terminal transition is appended before the fleet
acts on it, and failover/restart recover FROM the file. Five post-merge
review passes of PRs 6-8 each found a protocol bug by hand (idempotent-
reject double counting, superseded-assignment acceptance, probe
wedges) — bugs that all leave a FINGERPRINT in the journal. This module
machine-checks that fingerprint: it replays a journal file through the
protocol DFA the fleet promises

    submit -> assign -> progress* ->
        exactly one of done|rejected|expired|cancelled

(`cancelled`, ISSUE 18, is the client's terminal: a dropped wire
connection or cancel frame — closed like any verdict, and held to the
same accumulated-progress bar) and reports violations as stable
J-codes:

  J001 orphan-record      assign/progress/terminal for a rid this file
                          never saw submitted
  J002 duplicate-terminal a second terminal record for one rid
  J003 record-after-terminal  assign/progress after the rid's verdict
  J004 stale-fence        progress/done carrying a (replica,
                          incarnation, generation) that is not the
                          rid's LATEST assignment — the zombie-holder
                          acceptance the fleet's lease fence must refuse
  J005 progress-terminal-mismatch  a done/expired/cancelled record
                          whose tokens differ from the rid's
                          accumulated journaled progress (a re-decoded
                          or double-prepended token: the
                          superseded-report bug class)
  J006 unassigned-progress  progress from a named replica with no
                          assignment in effect (the restart-resume
                          record `__restart__` and compaction's
                          consolidated `replica: null` form are the two
                          sanctioned exceptions)
  J007 open-at-close      with `expect_closed=True`: a rid left open —
                          `ServingFleet.close()` promises every
                          journaled rid ends in a verdict
  J008 malformed-journal  unreadable mid-file record, unknown kind,
                          missing fields, or a compaction meta record
                          anywhere but the file head (compaction
                          REWRITES the file; meta mid-file means two
                          histories were glued together)
  J009 version-fence      a done record whose `weights_version` differs
                          from its latest assignment's (ISSUE 11 live
                          weight rollout): a mixed-version output is a
                          PROTOCOL violation, not just a test failure —
                          the fleet promises every response's verdict
                          version matches the assignment that produced
                          it. Checked only when both sides carry the
                          optional side-band; journals from an
                          unversioned fleet stay clean.
  J010 taint-fence        the ISSUE 15 integrity contract. An
                          `integrity` record quarantines a (replica,
                          incarnation) and TAINTS per-rid progress
                          windows [from, upto): the rid's accumulated
                          progress truncates to `from`, and ONLY the
                          tainted indices may ever be journaled twice
                          (the one sanctioned exception to PR 8's
                          zero-re-decode rule). J010 fires when (a)
                          progress re-covers an already-journaled
                          token index OUTSIDE any taint window — a
                          re-decode the protocol never sanctioned; (b)
                          an assign/progress/done names a quarantined
                          (replica, incarnation) AFTER its integrity
                          event — "a done whose assignment predates
                          the replica's integrity event"; (c) an
                          integrity record's taint window is
                          ill-formed (from > upto, from past the
                          journaled progress, an unknown or already-
                          terminal rid).
  J011 handoff-fence      the ISSUE 16 durable-KV contract. An assign
                          may carry a `handoff` side-band (`len` +
                          fingerprint `digest`: the checksummed block
                          package shipped at re-route) and a done the
                          matching outcome (`imported` tokens +
                          `fallback` flag). J011 fires when (a) a
                          FIRST assign (no prior assign, no journaled
                          history — compaction's consolidated progress
                          counts as history) carries handoff: packages
                          only attach at re-route, an admission-time
                          one is fabricated; (b) the package claims more
                          tokens than the prompt plus journaled
                          progress at assign time could have closed;
                          (c) a done carries an outcome but its latest
                          assignment shipped no package; (d) a done
                          whose holder received a package and actually
                          ran (tokens beyond the progress at assign)
                          reports NO outcome — every shipped package
                          must trace to a verified import or a counted
                          fallback, never silence; (e) an outcome
                          claims more imported tokens than its
                          assignment's package carried.

Optional side-band fields (ISSUEs 11 + 12 + 16 + 18): assign records
may carry `tier` (prefill/decode disaggregation placement),
`weights_version` (the assignee's weight version), `tenant` (the
consumer whose quota admitted the request — the multi-tenant
exactly-once audit groups the journal by it), and `handoff` (the
ISSUE 16 block-package side-band); done records may carry
`weights_version`, `tenant`, and `handoff`. ISSUE 18's front door
adds `conn` (the wire connection id that submitted the request) on
submit/progress/cancelled records and `stream` on submit (bool: the
client asked for token streaming) and progress (int: the journal's
cumulative generated-token count AFTER the record's tokens — the
stream cursor; it must equal the accumulated progress length, else
J008, because the streamed prefix is derived from it and a drifted
cursor means streamed tokens and the journal disagree).
Present-but-ill-typed side-band fields are J008 like any other field,
including the inner shape of `handoff` ({"len": int, "digest": str}
on assign, {"imported": int, "fallback": bool} on done).

A torn FINAL line is tolerated exactly like `RequestJournal._read`
(the crash the journal exists to survive must not fail its own audit);
torn-then-more-records is real corruption and reports J008.

Compaction invariant: a compacted file replays to the same open set and
the same concatenated progress prefixes — checked by running the same
DFA over the rewritten file (`verify_journal` after `compact()`); a
compaction that drops an open rid shows up as J001 (its later records
orphaned) or as a J005 prefix mismatch at its terminal.

Entry points: `verify_journal(path, expect_closed=False)` (library),
`python -m paddle_tpu.analysis journal <path> [--expect-closed]` (CLI),
and the opt-in `PADDLE_TPU_AUDIT_JOURNAL=1` hook in
`ServingFleet.close()` which audits the live journal so every fleet
test and bench run double-checks itself for free.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from .diagnostics import Diagnostic, make, rel_path

__all__ = ["verify_journal", "verify_records", "JournalViolation"]

_TERMINAL = ("done", "rejected", "expired", "cancelled")
_KINDS = ("meta", "submit", "assign", "progress", "integrity") + _TERMINAL

# the front-door-restart resume prefix: journaled by submit() before any
# assignment exists, under this sentinel holder (fleet.py submit())
_RESTART = "__restart__"

_REQUIRED = {
    "meta": ("max_rid",),
    "submit": ("rid", "spec"),
    "assign": ("rid", "replica", "incarnation", "gen"),
    "progress": ("rid", "replica", "incarnation", "gen", "tokens"),
    "done": ("rid", "replica", "incarnation", "gen", "tokens"),
    "rejected": ("rid", "reason"),
    "expired": ("rid", "tokens"),
    # ISSUE 18 client-cancel terminal: the submitter walked away (a
    # dropped wire connection or cancel frame); `tokens` is the
    # journaled prefix emitted before the cancel — the DFA accepts it
    # as CLOSED (J007) and holds it to the same accumulated-progress
    # bar as done/expired (J005)
    "cancelled": ("rid", "tokens"),
    # ISSUE 15 quarantine record: no rid of its own — `taint` maps
    # rid -> [from, upto) windows over that rid's journaled progress
    "integrity": ("replica", "incarnation", "taint"),
}

# field -> accepted types: a JSON-parseable record with an ill-typed
# field is J008, never a TypeError out of the DFA (the never-crash
# contract). replica/incarnation/gen are nullable — compaction's
# consolidated progress form writes all three as null.
_FIELD_TYPES = {
    "rid": (int,),
    "max_rid": (int,),
    "spec": (dict,),
    "reason": (str,),
    "tokens": (list,),
    "replica": (str, type(None)),
    "incarnation": (int, type(None)),
    "gen": (int, type(None)),
    # ISSUE 11 side-band (optional on assign/done): nullable, because
    # an untiered/unversioned fleet writes them as null
    "tier": (str, type(None)),
    "weights_version": (int, type(None)),
    # ISSUE 12 side-band: the tenant whose quota admitted the request
    # (null on a single-tenant fleet) — a per-tenant exactly-once
    # audit groups the journal by this field, so an ill-typed value
    # silently breaks the grouping and must be J008 like any other
    "tenant": (str, type(None)),
    # ISSUE 15: the integrity record's rid -> [from, upto] window map
    "taint": (dict,),
    # ISSUE 16: the durable-KV handoff side-band — a package
    # description on assign, an import outcome on done (nullable: the
    # fleet writes null when no package rode the assignment)
    "handoff": (dict, type(None)),
    # ISSUE 18 wire side-band: the front-door connection id that owns
    # the request (submit/progress/cancelled). A restarted front door
    # groups orphaned streams by this field, so an ill-typed value is
    # J008 like tenant.
    "conn": (str, type(None)),
    # ISSUE 18: `stream` is a BOOL on submit (incremental delivery
    # requested) and an INT CURSOR on progress (accumulated journaled
    # length after the delta — what a restarted front door may have
    # already delivered). bool is accepted where int is only because
    # the per-kind check below pins the exact shape: a bool cursor on
    # progress is J008 despite Python's bool-is-int subtyping.
    "stream": (bool, int, type(None)),
}

# optional per-kind side-band fields: absent is fine (old journals),
# present-but-ill-typed is J008 like any required field
_OPTIONAL = {
    "submit": ("conn", "stream"),
    "assign": ("tier", "weights_version", "tenant", "handoff"),
    "progress": ("conn", "stream"),
    "done": ("weights_version", "tenant", "handoff"),
    "cancelled": ("conn",),
    "integrity": ("reason",),
}


def _bad_stream(rec, kind):
    """Pin the per-kind shape of a present `stream` side-band: BOOL on
    submit, non-negative INT (not bool) on progress — `isinstance(True,
    int)` is True in Python, so the generic type table alone would
    wave a bool cursor through."""
    s = rec.get("stream")
    if s is None:
        return None
    if kind == "submit":
        if not isinstance(s, bool):
            return "stream"
    elif kind == "progress":
        if isinstance(s, bool) or not isinstance(s, int) or s < 0:
            return "stream"
    return None


def _bad_handoff(rec, kind):
    """Inner-shape check for a present, non-null `handoff` side-band:
    returns a short defect label or None. The outer dict/None check is
    `_FIELD_TYPES`; this pins the inner schema so a fabricated or
    bit-rotted side-band is J008, not a KeyError in the J011 fence."""
    ho = rec.get("handoff")
    if ho is None:
        return None
    if kind == "assign":
        if not isinstance(ho.get("len"), int) or ho["len"] < 0:
            return "len"
        if not isinstance(ho.get("digest"), str):
            return "digest"
    else:  # done
        if not isinstance(ho.get("imported"), int) or ho["imported"] < 0:
            return "imported"
        if not isinstance(ho.get("fallback"), bool):
            return "fallback"
    return None


def _ill_typed(rec, kind):
    """Name of the first ill-typed required (or present optional)
    field, or None."""
    for field in _REQUIRED[kind]:
        if not isinstance(rec[field], _FIELD_TYPES[field]):
            return field
    for field in _OPTIONAL.get(kind, ()):
        if field in rec and not isinstance(rec[field],
                                           _FIELD_TYPES[field]):
            return field
    bad = _bad_stream(rec, kind)
    if bad is not None:
        return bad
    return None


class JournalViolation(RuntimeError):
    """Raised by the `PADDLE_TPU_AUDIT_JOURNAL=1` close() audit when
    the live journal fails the protocol DFA. Carries the diagnostics."""

    def __init__(self, path: str, diagnostics: List[Diagnostic]):
        from .diagnostics import format_diag

        self.diagnostics = list(diagnostics)
        super().__init__(
            "journal %s violates the request protocol (%d finding%s):"
            "\n  %s" % (path, len(self.diagnostics),
                        "" if len(self.diagnostics) == 1 else "s",
                        "\n  ".join(format_diag(d)
                                    for d in self.diagnostics)))


class _Rid(object):
    """DFA state for one request id."""

    __slots__ = ("state", "assign", "assign_version", "progress",
                 "terminal_line", "hwm", "taint", "n_assigns",
                 "assign_handoff", "progress_at_assign", "prompt_len")

    def __init__(self):
        self.state = "open"          # open -> terminal
        self.assign: Optional[Tuple[str, int, int]] = None
        # weights_version side-band of the latest assignment (None =
        # unversioned): the J009 version fence's reference value
        self.assign_version: Optional[int] = None
        self.progress: List[int] = []
        self.terminal_line = 0
        # ISSUE 16 handoff fence (J011): how many assigns this rid has
        # seen (a package on the FIRST one is fabricated), the latest
        # assignment's handoff side-band, the journaled-progress length
        # when that assignment landed (a done beyond it means the
        # holder actually ran), and the submit spec's prompt length
        # (bounds what a package could legally cover)
        self.n_assigns = 0
        self.assign_handoff: Optional[dict] = None
        self.progress_at_assign = 0
        self.prompt_len = 0
        # ISSUE 15 taint fence: the high-water mark of journaled
        # progress (never lowered — an integrity truncation lowers the
        # ACCUMULATION, not the mark) and the active taint window
        # [from, upto). Progress below the mark is a re-decode, legal
        # ONLY inside the window (J010).
        self.hwm = 0
        self.taint: Optional[Tuple[int, int]] = None


def _iter_records(path: str):
    """(lineno, record-or-None, raw) — a None record is a parse
    failure; final-line failures are torn tails (tolerated), earlier
    ones are J008 (the caller decides, mirroring RequestJournal._read's
    torn-tail rule without raising)."""
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                yield lineno, None, line
                continue
            if not isinstance(rec, dict):
                yield lineno, None, line
                continue
            yield lineno, rec, line


def verify_records(records, path_label: str = "<journal>",
                   expect_closed: bool = False) -> List[Diagnostic]:
    """Run the protocol DFA over an iterable of (lineno, record) pairs
    (already-parsed journal records). The library half of
    `verify_journal`, reusable over in-memory record lists (tests, the
    explorer's invariant probes)."""
    diags: List[Diagnostic] = []
    rids: Dict[int, _Rid] = {}
    # quarantined (replica, incarnation) -> integrity-record line: any
    # later record naming the pair is J010 (the fleet kills the
    # incarnation at the trip; nothing legitimate can follow)
    quarantined: Dict[Tuple[str, int], int] = {}

    def diag(code, lineno, rid, detail, msg):
        # a malformed record's rid may be any JSON value — the symbol
        # must describe it, never crash the describer
        sym = "rid%d" % rid if isinstance(rid, int) else "journal"
        diags.append(make(code, path_label, lineno, sym, detail, msg))

    first_record = True
    for lineno, rec in records:
        kind = rec.get("kind")
        if kind not in _KINDS:
            diag("J008", lineno, rec.get("rid"), "kind:%r" % (kind,),
                 "unknown record kind %r" % (kind,))
            first_record = False
            continue
        missing = [k for k in _REQUIRED[kind] if k not in rec]
        if missing:
            diag("J008", lineno, rec.get("rid"),
                 "%s:missing:%s" % (kind, ",".join(missing)),
                 "%s record missing field(s) %s" % (kind,
                                                    ", ".join(missing)))
            first_record = False
            continue
        bad = _ill_typed(rec, kind)
        if bad is not None:
            rid = rec["rid"] if isinstance(rec.get("rid"), int) else None
            diag("J008", lineno, rid, "%s:ill-typed:%s" % (kind, bad),
                 "%s record field %r has type %s, expected %s"
                 % (kind, bad, type(rec[bad]).__name__,
                    "/".join(t.__name__ for t in _FIELD_TYPES[bad])))
            first_record = False
            continue
        if kind == "meta":
            if not first_record:
                diag("J008", lineno, None, "meta-mid-file",
                     "compaction meta record at line %d is not at the "
                     "file head: compaction rewrites the WHOLE file, a "
                     "mid-file meta means two histories were glued "
                     "together" % lineno)
            first_record = False
            continue
        first_record = False
        if kind == "integrity":
            # the ISSUE 15 quarantine record: no rid of its own
            if not isinstance(rec["replica"], str) \
                    or not isinstance(rec["incarnation"], int):
                diag("J008", lineno, None, "integrity:ill-typed:holder",
                     "integrity record needs a concrete (replica, "
                     "incarnation) — got (%r, %r)"
                     % (rec["replica"], rec["incarnation"]))
                continue
            holder2 = (rec["replica"], rec["incarnation"])
            for rid_s in sorted(rec["taint"]):
                window = rec["taint"][rid_s]
                try:
                    trid = int(rid_s)
                except (TypeError, ValueError):
                    trid = None
                if (trid is None or not isinstance(window, list)
                        or len(window) != 2
                        or not all(isinstance(w, int) for w in window)):
                    diag("J008", lineno, None,
                         "integrity:ill-typed:taint",
                         "integrity taint entry %r -> %r is not "
                         "rid -> [from, upto]" % (rid_s, window))
                    continue
                frm, upto = window
                st = rids.get(trid)
                if st is None:
                    diag("J010", lineno, trid, "taint:unknown-rid",
                         "integrity record taints rid %d that was "
                         "never submitted in this file" % trid)
                    continue
                if st.state == "terminal":
                    diag("J010", lineno, trid, "taint:terminal",
                         "integrity record taints rid %d after its "
                         "terminal record (line %d) — a verdict's "
                         "tokens cannot be retroactively tainted"
                         % (trid, st.terminal_line))
                    continue
                if frm < 0 or frm > upto:
                    diag("J010", lineno, trid, "taint:ill-formed",
                         "integrity taint window [%d, %d) for rid %d "
                         "is ill-formed" % (frm, upto, trid))
                    continue
                if frm > len(st.progress):
                    diag("J010", lineno, trid, "taint:past-progress",
                         "integrity taint window for rid %d opens at "
                         "token %d but only %d progress token(s) are "
                         "journaled — the verified prefix cannot "
                         "exceed what was journaled"
                         % (trid, frm, len(st.progress)))
                    continue
                # truncate the ACCUMULATION to the verified prefix;
                # the high-water mark keeps the pre-taint length so a
                # later progress below it is recognized as re-decode
                st.hwm = max(st.hwm, len(st.progress), upto)
                st.progress = st.progress[:frm]
                st.taint = (frm, upto)
            quarantined[holder2] = lineno
            continue
        rid = rec["rid"]
        st = rids.get(rid)
        if kind == "submit":
            if st is not None:
                code = ("J003" if st.state == "terminal" else "J001")
                diag(code, lineno, rid, "resubmit",
                     "duplicate submit for rid %d (already %s)"
                     % (rid, st.state))
                continue
            st = rids[rid] = _Rid()
            prompt = rec["spec"].get("prompt")
            if isinstance(prompt, list):
                st.prompt_len = len(prompt)
            continue
        if st is None:
            diag("J001", lineno, rid, "orphan:%s" % kind,
                 "%s record for rid %d that was never submitted in "
                 "this file" % (kind, rid))
            # keep tracking, applying this record's state effects
            # WITHOUT further checks: one orphan is one finding, not a
            # cascade of secondary fence/terminal violations
            st = rids[rid] = _Rid()
            if kind == "assign":
                st.assign = (rec["replica"], rec["incarnation"],
                             rec["gen"])
                st.assign_version = rec.get("weights_version")
                st.n_assigns = 1
                if _bad_handoff(rec, "assign") is None:
                    st.assign_handoff = rec.get("handoff")
            elif kind == "progress":
                st.progress.extend(rec["tokens"])
                st.hwm = len(st.progress)
            else:
                st.state = "terminal"
                st.terminal_line = lineno
            continue
        if st.state == "terminal":
            code = "J002" if kind in _TERMINAL else "J003"
            diag(code, lineno, rid, "%s-after-terminal" % kind,
                 "%s record for rid %d after its terminal record "
                 "(line %d): the DFA allows exactly one verdict"
                 % (kind, rid, st.terminal_line))
            continue
        if kind == "assign":
            if (rec["replica"], rec["incarnation"]) in quarantined:
                diag("J010", lineno, rid,
                     "assign:quarantined:%s" % (rec["replica"],),
                     "assign of rid %d to (%r, incarnation %r) AFTER "
                     "that incarnation's integrity event (line %d) — "
                     "the fleet kills a tripped incarnation; nothing "
                     "may be assigned to it again"
                     % (rid, rec["replica"], rec["incarnation"],
                        quarantined[(rec["replica"],
                                     rec["incarnation"])]))
            ho = rec.get("handoff")
            bad_ho = _bad_handoff(rec, "assign")
            if bad_ho is not None:
                diag("J008", lineno, rid, "assign:handoff:%s" % bad_ho,
                     "assign handoff side-band for rid %d has an "
                     "ill-formed %r field (%r) — expected "
                     '{"len": int >= 0, "digest": str}'
                     % (rid, bad_ho, ho.get(bad_ho)))
                ho = None
            elif ho is not None:
                # the J011 handoff fence, assign half (ISSUE 16).
                # journaled progress with no assign seen yet is the
                # compacted/restart consolidated form — a prior holder
                # existed, so its re-emitted package has a source
                if st.n_assigns == 0 and not st.progress:
                    diag("J011", lineno, rid, "handoff:first-assign",
                         "assign of rid %d carries a handoff package "
                         "on its FIRST assignment — packages only "
                         "attach at re-route (migration/failover); an "
                         "admission-time package has no source" % rid)
                cap = st.prompt_len + len(st.progress)
                if ho["len"] > cap:
                    diag("J011", lineno, rid, "handoff:overrun",
                         "assign handoff for rid %d claims %d "
                         "package token(s) but only %d (prompt + "
                         "journaled progress) existed to close — the "
                         "package describes blocks the source never "
                         "had" % (rid, ho["len"], cap))
            st.assign = (rec["replica"], rec["incarnation"], rec["gen"])
            st.assign_version = rec.get("weights_version")
            st.assign_handoff = ho
            st.progress_at_assign = len(st.progress)
            st.n_assigns += 1
            continue
        if kind == "progress":
            holder = (rec["replica"], rec["incarnation"], rec["gen"])
            if rec["replica"] is None or rec["replica"] == _RESTART:
                # compaction's consolidated form / the restart resume
                # prefix: both precede (or replace) any assignment
                pass
            elif st.assign is None:
                diag("J006", lineno, rid, "progress:%s" % rec["replica"],
                     "progress for rid %d from %r with no assignment "
                     "in effect" % (rid, rec["replica"]))
            elif holder != st.assign:
                diag("J004", lineno, rid,
                     "progress:%s" % (rec["replica"],),
                     "progress for rid %d from %r (incarnation %r, gen "
                     "%r) but the latest assignment is %r — a stale "
                     "holder's tokens were accepted past the lease "
                     "fence" % (rid, rec["replica"], rec["incarnation"],
                                rec["gen"], (st.assign,)))
            if rec["replica"] is not None and rec["replica"] != _RESTART \
                    and (rec["replica"], rec["incarnation"]) in quarantined:
                diag("J010", lineno, rid,
                     "progress:quarantined:%s" % (rec["replica"],),
                     "progress for rid %d from (%r, incarnation %r) "
                     "AFTER that incarnation's integrity event (line "
                     "%d) — a quarantined holder's tokens were "
                     "accepted" % (rid, rec["replica"],
                                   rec["incarnation"],
                                   quarantined[(rec["replica"],
                                                rec["incarnation"])]))
            # the taint-fence re-decode audit (ISSUE 15): progress
            # below the high-water mark journals token indices a
            # PREVIOUS holder already journaled. That is legal only
            # for indices INSIDE a journaled taint window — PR 8's
            # zero-re-decode rule everywhere else (both ends checked:
            # a resume below `from` re-decodes VERIFIED tokens, a span
            # past `upto` re-decodes untainted ones)
            L = len(st.progress)
            hi = min(L + len(rec["tokens"]), st.hwm)
            if hi > L and (st.taint is None or L < st.taint[0]
                           or hi > st.taint[1]):
                diag("J010", lineno, rid, "redecode-outside-taint",
                     "progress for rid %d re-decodes token indices "
                     "[%d, %d) (high-water mark %d) outside the "
                     "journaled taint window (%r) — only tainted "
                     "tokens may ever re-decode"
                     % (rid, L, hi, st.hwm, st.taint))
            st.progress.extend(rec["tokens"])
            st.hwm = max(st.hwm, len(st.progress))
            cur = rec.get("stream")
            if isinstance(cur, int) and not isinstance(cur, bool) \
                    and cur != len(st.progress):
                # the wire side-band's one semantic promise (ISSUE
                # 18): the cursor IS the accumulation after this
                # delta — what a restarted front door may already
                # have delivered. A drifting cursor is an ill-shaped
                # side-band (J008), and acting on it would re-send
                # or skip streamed tokens.
                diag("J008", lineno, rid, "stream-cursor",
                     "progress for rid %d carries stream cursor %d "
                     "but the accumulated journaled progress is %d "
                     "token(s) — a resumed stream would re-deliver "
                     "or skip tokens" % (rid, cur, len(st.progress)))
            continue
        # terminal kinds
        st.state = "terminal"
        st.terminal_line = lineno
        if kind == "done" and rec["replica"] != _RESTART \
                and (rec["replica"], rec["incarnation"]) in quarantined:
            # "a done whose assignment predates the replica's
            # integrity event": the quarantined incarnation's verdict
            # landed past the fence (ISSUE 15)
            diag("J010", lineno, rid,
                 "done:quarantined:%s" % (rec["replica"],),
                 "done for rid %d from (%r, incarnation %r) AFTER "
                 "that incarnation's integrity event (line %d) — its "
                 "assignment predates the quarantine, the verdict "
                 "must be refused"
                 % (rid, rec["replica"], rec["incarnation"],
                    quarantined[(rec["replica"], rec["incarnation"])]))
        if kind == "done":
            holder = (rec["replica"], rec["incarnation"], rec["gen"])
            if rec["replica"] == _RESTART and st.assign is None:
                pass  # completed straight from the restart prefix
            elif st.assign is None:
                diag("J006", lineno, rid, "done:%s" % (rec["replica"],),
                     "done for rid %d from %r with no assignment in "
                     "effect" % (rid, rec["replica"]))
            elif holder != st.assign:
                diag("J004", lineno, rid, "done:%s" % (rec["replica"],),
                     "done for rid %d from %r (incarnation %r, gen %r) "
                     "but the latest assignment is %r — a zombie "
                     "holder's completion was accepted"
                     % (rid, rec["replica"], rec["incarnation"],
                        rec["gen"], (st.assign,)))
            dv = rec.get("weights_version")
            if dv is not None and st.assign is not None \
                    and st.assign_version is not None \
                    and dv != st.assign_version:
                # the live-rollout version fence (ISSUE 11): the
                # verdict must come from the weights the latest
                # assignment promised — a mismatch means tokens from
                # two weight versions were mixed into one response
                diag("J009", lineno, rid, "done-version",
                     "done for rid %d records weights_version %d but "
                     "its latest assignment carries version %d — a "
                     "mixed-version output crossed the rollout fence"
                     % (rid, dv, st.assign_version))
            # the J011 handoff fence, done half (ISSUE 16): every
            # shipped package traces to a verified import or a counted
            # fallback — silence is a protocol violation
            out = rec.get("handoff")
            bad_ho = _bad_handoff(rec, "done")
            if bad_ho is not None:
                diag("J008", lineno, rid, "done:handoff:%s" % bad_ho,
                     "done handoff outcome for rid %d has an "
                     "ill-formed %r field (%r) — expected "
                     '{"imported": int >= 0, "fallback": bool}'
                     % (rid, bad_ho, out.get(bad_ho)))
            elif out is not None and st.assign_handoff is None:
                diag("J011", lineno, rid, "handoff:unshipped",
                     "done for rid %d reports a handoff outcome but "
                     "its latest assignment shipped no package — an "
                     "import was claimed for a transfer that never "
                     "happened" % rid)
            elif out is not None \
                    and out["imported"] > st.assign_handoff["len"]:
                diag("J011", lineno, rid, "handoff:over-import",
                     "done for rid %d claims %d imported token(s) but "
                     "its assignment's package carried only %d"
                     % (rid, out["imported"],
                        st.assign_handoff["len"]))
            elif out is None and st.assign_handoff is not None \
                    and st.assign is not None and holder == st.assign \
                    and len(rec["tokens"]) > st.progress_at_assign:
                diag("J011", lineno, rid, "handoff:unaccounted",
                     "done for rid %d from the holder that received a "
                     "%d-token handoff package reports no outcome — "
                     "the package must be accounted as a verified "
                     "import or a counted fallback, never silence"
                     % (rid, st.assign_handoff["len"]))
        if kind in ("done", "expired", "cancelled"):
            # no empty-progress exemption: the fleet journals EVERY
            # emitted token as a progress delta before the terminal
            # (the PR-8 re-decode-zero audit depends on it), so a done
            # with tokens but no journaled progress is exactly the
            # never-journaled defect this code names. `cancelled`
            # (ISSUE 18) is held to the same bar: its tokens are the
            # journaled prefix at cancel time, taken under the same
            # lock the progress mirror updates under
            if list(rec["tokens"]) != st.progress:
                diag("J005", lineno, rid, "%s-tokens" % kind,
                     "%s tokens for rid %d (%d token(s)) differ from "
                     "the accumulated journaled progress (%d token(s)) "
                     "— a token was re-decoded, double-prepended, or "
                     "never journaled" % (kind, rid, len(rec["tokens"]),
                                          len(st.progress)))
    if expect_closed:
        for rid in sorted(rids):
            st = rids[rid]
            if st.state != "terminal":
                diags.append(make(
                    "J007", path_label, 0, "rid%d" % rid, "open",
                    "rid %d is still open at end of journal — close() "
                    "promises every journaled rid a terminal verdict"
                    % rid))
    diags.sort(key=lambda d: (d.line, d.code, d.symbol))
    return diags


def verify_journal(path: str,
                   expect_closed: bool = False) -> List[Diagnostic]:
    """Verify a `RequestJournal` file against the protocol DFA.
    Returns the J-coded findings (empty = the journal is a valid
    history). Tolerates a torn final line; anything unparseable
    earlier is J008, not an exception — an auditor must be able to
    describe a corrupt journal, not crash on it."""
    if not os.path.exists(path):
        raise FileNotFoundError("no such journal: %r" % path)
    label = rel_path(path)
    parsed: List[Tuple[int, dict]] = []
    torn: Optional[Tuple[int, str]] = None
    diags: List[Diagnostic] = []
    for lineno, rec, raw in _iter_records(path):
        if torn is not None:
            # an unparseable line FOLLOWED by more content is not a
            # torn tail — it is mid-file corruption
            diags.append(make(
                "J008", label, torn[0], "journal", "corrupt-line",
                "unparseable record at line %d is not a torn tail "
                "(records follow it)" % torn[0]))
            torn = None
        if rec is None:
            torn = (lineno, raw)
            continue
        parsed.append((lineno, rec))
    diags.extend(verify_records(parsed, path_label=label,
                                expect_closed=expect_closed))
    diags.sort(key=lambda d: (d.line, d.code, d.symbol))
    return diags
