"""Trace-hazard linter: AST pass over the jitted hot paths.

Retrace and host-sync hazards are the bug class every review pass of
PR 1-4 hunted by hand: a `float()`/`.item()`/`np.asarray` on a traced
value forces a device sync (or a tracer error) inside a compiled step,
a `time.time()`/`random.*` call bakes one trace-time value into the
compiled artifact forever, and a Python `if` on a tracer-typed argument
either crashes or silently recompiles per branch. This pass finds the
*traced* functions of a module and flags those patterns inside them:

  T001 host-sync-in-trace   float()/int()/bool() on non-literals,
                            .item()/.tolist()/.block_until_ready(),
                            np.asarray/np.array on traced values
  T002 impure-call-in-trace time.*/random.*/np.random.*/os.environ —
                            evaluated once at trace time, frozen into
                            the compiled step
  T003 tracer-branch        `if`/`while` on a parameter of a traced
                            function (static accessors like `.ndim`,
                            `.shape`, `len()`, `isinstance()`,
                            `is None` are exempt — they are shape-level
                            and legitimately branch at trace time)
  T004 unhashable-static-arg jit static_argnums/static_argnames naming
                            a parameter whose default is a mutable
                            (unhashable) literal — every call misses
                            the jit cache
  T005 device-dispatch-in-scheduler  a `jnp.`/`jax.*` call reachable
                            from a host-side scheduler loop — a method
                            annotated `# thread: <domain>` (the fleet's
                            replica/monitor control threads) or any
                            same-class method those reach. A control
                            thread that dispatches device work per
                            step serializes the fleet behind one
                            accelerator queue; device math belongs in
                            the engine's traced bodies (nested traced
                            defs are exempt — they are the fix, not
                            the hazard)

A function is *traced* when it is (a) passed to / decorated with a jit
or lax control-flow marker (`jax.jit`, `jax.vmap`, `jax.pmap`,
`lax.scan`, `lax.while_loop`, `lax.fori_loop`, `lax.cond`,
`lax.map`, `jax.checkpoint`), (b) defined inside a traced function, or
(c) called by name from a traced function in the same module (local
call-graph propagation — `decode_step` is traced because `generate`'s
scan body calls it). Cross-module calls are not resolved; each hot-path
file is linted on its own.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .diagnostics import Diagnostic, make, rel_path, walk_python_files

__all__ = ["lint_file", "lint_paths", "HOT_PATHS"]

# the jitted hot paths; `--all` lints exactly these. executor.py's jit
# sites wrap functions BUILT in core/lowering.py (cross-module), so the
# lowering module — where the traced step bodies actually live — is a
# hot path in its own right.
HOT_PATHS = [
    "paddle_tpu/models/transformer.py",
    # the fused paged-attention kernels (ISSUE 13): everything in the
    # module body runs at trace time inside the compiled serving steps
    "paddle_tpu/parallel/paged_attention.py",
    "paddle_tpu/serving/engine.py",
    "paddle_tpu/serving/fleet.py",
    # multi-tenant front door + adapter paging (ISSUE 12): host-side
    # admission/residency today, but both sit ON the scheduler hot
    # path next to the compiled steps — linted from day one
    "paddle_tpu/serving/tenancy.py",
    "paddle_tpu/serving/adapters.py",
    # serving integrity (ISSUE 15): the trap/fingerprint/sentinel
    # helpers run inside (or right next to) the compiled serving steps
    "paddle_tpu/serving/integrity.py",
    # durable KV (ISSUE 16): serialization/import/spill run on the
    # admission and retire paths right next to the compiled steps
    "paddle_tpu/serving/kv_store.py",
    # KV/weight quantization (ISSUE 14): the quant/dequant helpers are
    # traced inside the compiled serving steps — a host sync here runs
    # per block per step
    "paddle_tpu/serving/quantization.py",
    # wire front door + load harness (ISSUE 18): pure host-side
    # threading, but the pump/stream paths feed the compiled steps'
    # journal flushes — a stray trace-time construct here would stall
    # every stream, so they're linted with the rest of the hot set
    "paddle_tpu/serving/frontdoor.py",
    "paddle_tpu/serving/loadgen.py",
    "paddle_tpu/fluid/executor.py",
    "paddle_tpu/fluid/core/lowering.py",
    # the training sentinel sits ON the step loop next to the jitted
    # step — registered so any traced helper that grows inside it is
    # linted from day one (today it is pure host control flow)
    "paddle_tpu/distributed/sentinel.py",
]

_TRACE_MARKERS = {
    "jax.jit", "jax.vmap", "jax.pmap", "jax.checkpoint", "jax.remat",
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.fori_loop",
    "jax.lax.cond", "jax.lax.map", "jax.lax.associative_scan",
}
_JIT_MARKERS = {"jax.jit"}

_HOST_CAST_BUILTINS = {"float", "int", "bool", "complex"}
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_HOST_SYNC_CALLS = {"numpy.asarray", "numpy.array", "numpy.copy"}
_IMPURE_PREFIXES = ("time.", "random.", "numpy.random.", "secrets.")
_IMPURE_EXACT = {"os.environ", "os.urandom", "os.getenv"}
_STATIC_ATTRS = {"ndim", "shape", "dtype", "size", "axis_names",
                 "sharding", "weak_type"}
_SAFE_TEST_CALLS = {"len", "isinstance", "issubclass", "getattr",
                    "hasattr", "callable", "type", "jax.numpy.ndim",
                    "numpy.ndim"}


class _Fn(object):
    """One function/lambda scope."""

    def __init__(self, node, qualname: str, parent: Optional["_Fn"]):
        self.node = node
        self.qualname = qualname
        self.parent = parent
        self.children: Dict[str, "_Fn"] = {}  # name -> direct child def
        self.child_list: List["_Fn"] = []
        args = node.args
        self.params: Set[str] = {
            a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)
        }
        if args.vararg:
            self.params.add(args.vararg.arg)
        if args.kwarg:
            self.params.add(args.kwarg.arg)
        self.arg_order: List[str] = [
            a.arg for a in (args.posonlyargs + args.args)
        ]
        self.defaults = args.defaults  # align to tail of arg_order
        self.kw_defaults: Dict[str, ast.AST] = {
            a.arg: d for a, d in zip(args.kwonlyargs, args.kw_defaults)
            if d is not None
        }


class _ModuleIndex(ast.NodeVisitor):
    """Collect function scopes, the import alias table, and every call
    site paired with the scope it occurs in."""

    def __init__(self, tree):
        self.aliases: Dict[str, str] = {}
        self.module_fns: Dict[str, _Fn] = {}
        self.all_fns: List[_Fn] = []
        self.calls: List[Tuple[ast.Call, Optional[_Fn]]] = []
        self.decorated: List[_Fn] = []
        self._stack: List[Optional[_Fn]] = [None]
        self.visit(tree)

    # imports ----------------------------------------------------------
    def visit_Import(self, node):
        for a in node.names:
            if a.asname:
                self.aliases[a.asname] = a.name
            else:
                self.aliases[a.name.split(".")[0]] = a.name.split(".")[0]

    def visit_ImportFrom(self, node):
        mod = node.module or ""
        for a in node.names:
            self.aliases[a.asname or a.name] = (
                "%s.%s" % (mod, a.name) if mod else a.name
            )

    # scopes -----------------------------------------------------------
    def _enter(self, node, name):
        parent = self._stack[-1]
        qual = name if parent is None else "%s.%s" % (parent.qualname, name)
        fn = _Fn(node, qual, parent)
        if parent is None:
            self.module_fns.setdefault(name, fn)
        else:
            parent.children.setdefault(name, fn)
            parent.child_list.append(fn)
        self.all_fns.append(fn)
        self._stack.append(fn)
        return fn

    def visit_FunctionDef(self, node):
        fn = self._enter(node, node.name)
        if node.decorator_list:
            self.decorated.append(fn)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._enter(node, "<lambda>")
        self.generic_visit(node)
        self._stack.pop()

    def visit_ClassDef(self, node):
        # class bodies do not create a call-resolution scope for our
        # purposes; methods register under the enclosing scope chain
        # with the class name folded into the qualname
        parent = self._stack[-1]
        shim = _Fn(ast.Lambda(args=ast.arguments(
            posonlyargs=[], args=[], kwonlyargs=[], kw_defaults=[],
            defaults=[]), body=ast.Constant(value=None)),
            node.name if parent is None
            else "%s.%s" % (parent.qualname, node.name), parent)
        shim.is_class = True  # class bodies are NOT enclosing scopes
        self._stack.append(shim)
        self.generic_visit(node)
        self._stack.pop()
        # methods are reachable for marker calls via self.* only, which
        # we do not resolve; jit(_local) INSIDE a method resolves
        # through the shim's scope chain

    def visit_Call(self, node):
        self.calls.append((node, self._stack[-1]))
        self.generic_visit(node)


def _dotted(node, aliases) -> Tuple[Optional[str], bool]:
    """Resolve an expression to a dotted name with import aliases
    expanded. Returns (dotted, base_is_import): base_is_import is True
    only when the leftmost name is a known import alias — checks that
    must not fire on same-named locals require it."""
    parts = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None, False
    base = cur.id
    known = base in aliases
    parts.append(aliases.get(base, base))
    return ".".join(reversed(parts)), known


def _resolve(name: str, scope: Optional[_Fn], index: _ModuleIndex):
    """Find the function def `name` visible from `scope` under real
    Python scoping: class bodies (shim scopes) are NOT enclosing
    scopes — a bare name inside a method never resolves to a sibling
    method, it skips straight to the outer function/module scope."""
    s = scope
    while s is not None:
        if not getattr(s, "is_class", False) and name in s.children:
            return s.children[name]
        s = s.parent
    return index.module_fns.get(name)


def _marker_name(call_func, aliases) -> Optional[str]:
    dotted, _ = _dotted(call_func, aliases)
    if dotted in _TRACE_MARKERS:
        return dotted
    return None


def _traced_set(index: _ModuleIndex) -> Set[_Fn]:
    traced: Set[_Fn] = set()
    roots: List[_Fn] = []

    def add(fn):
        if fn is not None and fn not in traced:
            traced.add(fn)
            roots.append(fn)

    # (a) marker call sites: jit(f), lax.scan(body, ...), vmap(lambda ..)
    for call, scope in index.calls:
        if _marker_name(call.func, index.aliases) is None:
            continue
        # positional AND keyword forms: lax.while_loop(cond_fun=c,
        # body_fun=b) traces its operands just the same
        operands = list(call.args) + [kw.value for kw in call.keywords]
        for arg in operands:
            if isinstance(arg, ast.Name):
                add(_resolve(arg.id, scope, index))
            elif isinstance(arg, ast.Lambda):
                for fn in index.all_fns:
                    if fn.node is arg:
                        add(fn)

    # (a') decorators: @jax.jit / @partial(jax.jit, ...)
    for fn in index.decorated:
        for dec in fn.node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            dotted, _ = _dotted(target, index.aliases)
            if dotted in _TRACE_MARKERS:
                add(fn)
            elif (isinstance(dec, ast.Call)
                  and dotted in ("functools.partial", "partial")
                  and dec.args
                  and _marker_name(dec.args[0], index.aliases)):
                add(fn)

    # (b) nested defs + (c) local call-graph propagation, to fixpoint.
    # Only fn's OWN body is walked: calls inside nested defs resolve
    # from the nested def's scope when IT is processed — resolving them
    # from here would misattribute same-named outer functions.
    while roots:
        fn = roots.pop()
        for child in fn.child_list:
            add(child)
        for node in _own_nodes(fn):
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                         ast.Name):
                callee = _resolve(node.func.id, fn, index)
                if callee is not None and not _is_marker_alias(
                        node.func.id, index):
                    add(callee)
    return traced


def _is_marker_alias(name, index):
    return index.aliases.get(name, name) in _TRACE_MARKERS


# --- per-function checks ----------------------------------------------

def _own_nodes(fn: _Fn):
    """Walk fn's body, NOT descending into nested function/lambda
    bodies (they are linted as their own traced functions)."""
    stack = list(ast.iter_child_nodes(fn.node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _parent_map(root):
    parents = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _test_param_hazard(test, fn: _Fn, aliases) -> Optional[str]:
    """Name of a traced-fn parameter branched on unsafely, or None."""
    parents = _parent_map(test)
    for node in ast.walk(test):
        if not (isinstance(node, ast.Name) and node.id in fn.params):
            continue
        if _safe_usage(node, parents, aliases):
            continue
        return node.id
    return None


def _is_static_expr(node, aliases) -> bool:
    """True when `node` is shape-level data that is concrete at trace
    time — `x.shape[1]`, `q.ndim`, `len(xs)` — so `int()`/`float()`
    over it is a legitimate idiom, not a host sync."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_ATTRS
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value, aliases)
    if isinstance(node, ast.Call):
        dotted, _ = _dotted(node.func, aliases)
        return dotted in _SAFE_TEST_CALLS
    if isinstance(node, ast.BinOp):
        return (_is_static_expr(node.left, aliases)
                and _is_static_expr(node.right, aliases))
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand, aliases)
    return False


def _safe_usage(name_node, parents, aliases) -> bool:
    cur = name_node
    while cur in parents:
        parent = parents[cur]
        if isinstance(parent, ast.Attribute) and parent.value is cur:
            if parent.attr in _STATIC_ATTRS:
                return True
        if isinstance(parent, ast.Call):
            dotted, _ = _dotted(parent.func, aliases)
            if dotted in _SAFE_TEST_CALLS:
                return True
        if isinstance(parent, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in parent.ops):
                return True
        cur = parent
    return False


def _check_traced_fn(fn: _Fn, index: _ModuleIndex, path: str,
                     diags: List[Diagnostic]):
    aliases = index.aliases
    for node in _own_nodes(fn):
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Name):
                name = func.id
                if (name in _HOST_CAST_BUILTINS
                        and name not in aliases
                        and node.args
                        and not all(_is_static_expr(a, aliases)
                                    for a in node.args)):
                    diags.append(make(
                        "T001", path, node.lineno, fn.qualname, name,
                        "%s() on a traced value forces a host sync "
                        "(or a ConcretizationTypeError)" % name))
            dotted, known = _dotted(func, aliases)
            if isinstance(func, ast.Attribute):
                if (func.attr in _HOST_SYNC_METHODS
                        and (dotted is None or not _is_module_ref(
                            dotted, known))):
                    diags.append(make(
                        "T001", path, node.lineno, fn.qualname,
                        ".%s" % func.attr,
                        ".%s() inside a traced function blocks on the "
                        "device" % func.attr))
            if dotted and known:
                if dotted in _HOST_SYNC_CALLS:
                    diags.append(make(
                        "T001", path, node.lineno, fn.qualname, dotted,
                        "%s materializes a traced value on the host"
                        % dotted))
                elif (dotted in _IMPURE_EXACT
                      or dotted.startswith(_IMPURE_PREFIXES)):
                    diags.append(make(
                        "T002", path, node.lineno, fn.qualname, dotted,
                        "%s evaluates ONCE at trace time; the compiled "
                        "step replays that frozen value" % dotted))
        elif isinstance(node, (ast.If, ast.While)):
            hazard = _test_param_hazard(node.test, fn, aliases)
            if hazard is not None:
                diags.append(make(
                    "T003", path, node.lineno, fn.qualname, hazard,
                    "branching on parameter %r of a traced function: "
                    "a tracer here raises, a python value recompiles "
                    "per branch" % hazard))
        elif isinstance(node, ast.Attribute):
            # os.environ reads (subscript or .get): the inner Attribute
            # node itself reports, exactly once
            dotted, known = _dotted(node, aliases)
            if dotted == "os.environ" and known:
                diags.append(make(
                    "T002", path, node.lineno, fn.qualname, dotted,
                    "%s read inside a traced function is frozen at "
                    "trace time" % dotted))


def _is_module_ref(dotted: str, known: bool) -> bool:
    # `np.copy` style module calls are handled by _HOST_SYNC_CALLS;
    # without this, `time.sleep` would double-report as a method call
    return known and "." in dotted


# --- T004 -------------------------------------------------------------

def _static_arg_sites(index: _ModuleIndex):
    """(jit-call node, target _Fn) pairs for BOTH forms: the call form
    `jax.jit(f, static_argnums=...)` and the decorator form
    `@partial(jax.jit, static_argnames=...)`."""
    for call, scope in index.calls:
        dotted, _ = _dotted(call.func, index.aliases)
        if dotted not in _JIT_MARKERS:
            continue
        if call.args and isinstance(call.args[0], ast.Name):
            target = _resolve(call.args[0].id, scope, index)
            if target is not None:
                yield call, target
    for fn in index.decorated:
        for dec in fn.node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            dotted, _ = _dotted(dec.func, index.aliases)
            if dotted in _JIT_MARKERS:
                yield dec, fn
            elif (dotted in ("functools.partial", "partial") and dec.args
                  and _marker_name(dec.args[0], index.aliases)
                  in _JIT_MARKERS):
                yield dec, fn


def _check_static_args(index: _ModuleIndex, path: str,
                       diags: List[Diagnostic]):
    for call, target in _static_arg_sites(index):
        static_params: List[str] = []
        for kw in call.keywords:
            vals = _literal_seq(kw.value)
            if kw.arg == "static_argnums":
                for v in vals:
                    if isinstance(v, int) and 0 <= v < len(
                            target.arg_order):
                        static_params.append(target.arg_order[v])
            elif kw.arg == "static_argnames":
                for v in vals:
                    if isinstance(v, str) and v in target.params:
                        static_params.append(v)
        if not static_params:
            continue
        n_def = len(target.defaults)
        defaulted = dict(zip(target.arg_order[-n_def:], target.defaults)) \
            if n_def else {}
        defaulted.update(target.kw_defaults)  # keyword-only defaults
        for p in static_params:
            d = defaulted.get(p)
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                diags.append(make(
                    "T004", path, call.lineno,
                    target.qualname, p,
                    "static arg %r defaults to an unhashable %s — "
                    "every call with the default misses the jit cache "
                    "(TypeError at best, retrace storm at worst)"
                    % (p, type(d).__name__.lower())))


def _literal_seq(node) -> list:
    if isinstance(node, ast.Constant):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts if isinstance(e, ast.Constant)]
    return []


# --- T005 -------------------------------------------------------------

# the same annotation lock_lint's thread-domain check learns from:
# `def _loop(self):  # thread: replica`
_THREAD_ANNOT_RE = re.compile(r"#\s*thread\s*:\s*(\w[\w\-]*)")


def _sched_roots(cls_node: ast.ClassDef, src_lines) -> Dict[str, str]:
    """method name -> thread domain, from `# thread:` annotations on
    the def line(s) — the declared host-side scheduler loops."""
    roots: Dict[str, str] = {}
    for item in cls_node.body:
        if not isinstance(item, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        body_start = item.body[0].lineno if item.body else item.lineno
        for ln in range(item.lineno, body_start + 1):
            if ln - 1 < len(src_lines):
                m = _THREAD_ANNOT_RE.search(src_lines[ln - 1])
                if m:
                    roots[item.name] = m.group(1)
                    break
    return roots


def _own_stmt_nodes(fn_node):
    """Walk a def body without descending into nested defs/lambdas:
    a nested def on a scheduler path is either a traced body (the
    sanctioned home for device math) or deferred work — neither runs
    on the scheduler thread at this call site."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _check_sched_dispatch(tree, src: str, index: _ModuleIndex,
                          traced: Set[_Fn], path: str,
                          diags: List[Diagnostic]):
    """T005: `jax.*` (so `jnp.*`) calls reachable from a `# thread:`
    annotated method through the same-class call graph. Traced
    functions are exempt wherever they appear — the check hunts
    dispatch FROM the control thread, not inside compiled steps."""
    src_lines = src.splitlines()
    traced_nodes = {id(fn.node) for fn in traced}
    for cls_node in tree.body:
        if not isinstance(cls_node, ast.ClassDef):
            continue
        roots = _sched_roots(cls_node, src_lines)
        if not roots:
            continue
        methods = {
            item.name: item for item in cls_node.body
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # same-class reachability: self.m() closure from the roots
        calls: Dict[str, Set[str]] = {}
        for name, node in methods.items():
            out: Set[str] = set()
            for sub in _own_stmt_nodes(node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id == "self"
                        and sub.func.attr in methods):
                    out.add(sub.func.attr)
            calls[name] = out
        via: Dict[str, Tuple[str, str]] = {}  # name -> (root, domain)
        frontier = [(name, name, dom) for name, dom in roots.items()]
        while frontier:
            name, root, dom = frontier.pop()
            if name in via:
                continue
            via[name] = (root, dom)
            for callee in sorted(calls.get(name, ())):
                if callee not in via:
                    frontier.append((callee, root, dom))
        for name in sorted(via):
            node = methods[name]
            if id(node) in traced_nodes:
                continue  # a traced method body is compiled, not host
            root, dom = via[name]
            qual = "%s.%s" % (cls_node.name, name)
            for sub in _own_stmt_nodes(node):
                if not isinstance(sub, ast.Call):
                    continue
                dotted, known = _dotted(sub.func, index.aliases)
                if not (known and dotted
                        and (dotted == "jax"
                             or dotted.startswith("jax."))):
                    continue
                if dotted in _TRACE_MARKERS:
                    # building a compiled step (jax.jit(body)) from
                    # the control thread is the sanctioned pattern —
                    # the hazard is dispatching work, not wrapping it
                    continue
                reach = ("a '# thread: %s' scheduler loop" % dom
                         if name == root else
                         "'%s.%s' (# thread: %s)"
                         % (cls_node.name, root, dom))
                diags.append(make(
                    "T005", path, sub.lineno, qual, dotted,
                    "%s dispatches device work from %s: a control "
                    "thread that calls into jax per step serializes "
                    "the fleet behind one accelerator queue — move it "
                    "into the engine's traced body or precompute on "
                    "the host" % (dotted, reach)))


# --- entry points ------------------------------------------------------

def lint_file(path: str) -> List[Diagnostic]:
    with open(path) as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    index = _ModuleIndex(tree)
    rel = rel_path(path)
    diags: List[Diagnostic] = []
    traced = _traced_set(index)
    for fn in sorted(traced, key=lambda f: f.node.lineno):
        _check_traced_fn(fn, index, rel, diags)
    _check_static_args(index, rel, diags)
    _check_sched_dispatch(tree, src, index, traced, rel, diags)
    diags.sort(key=lambda d: (d.path, d.line, d.code))
    return diags


def lint_paths(paths=None) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for f in walk_python_files(paths, HOT_PATHS):
        diags.extend(lint_file(f))
    return diags
