"""Band-lifecycle verifier: static propagation guarantees for the
engine's side-bands before decode goes multi-chip (ISSUE 20).

The serving engine's state is a set of named BANDS: per-slot host
mirrors (`_BANDS` in serving/engine.py — tok/pos/alive/…) with device
copies managed by a dirty-set protocol (`_mark_dirty` / `_band`), and
per-block CACHE bands in the KV pytree (k/v payloads plus the ISSUE 14
k_scale/v_scale side-bands). Every band must survive every lifecycle
verb — alias, COW, serialize, import, resume, retire, sync — and the
change history shows this exact defect class (a side-band missed at
COW/serialize, a dirty-flag set drifting from `_BANDS`) escaping to
manual review in PRs 14, 15, 16 and 19. This pass makes the registry
declarative and the propagation checkable:

  B001 band-not-propagated  a function annotated `# band-verb: <verb>`
                            does not reference every band the registry
                            requires for that verb (a COW that copies
                            payload but not k_scale), or a lifecycle
                            file is missing a required verb annotation
                            entirely (the check silently dying is
                            itself a finding)
  B002 dirty-flag-gap       a method of a `_mark_dirty`-bearing class
                            mutates a host band mirror (`self._tok[s] =
                            …`) without marking it dirty, adopting the
                            device copy, or every caller doing so; and
                            `_mark_dirty("name")` names outside the
                            band registry (a typo silently dirties
                            nothing)
  B003 wire-schema-asymmetry the kv_store record schema written by the
                            serialize side (`make_block_record` /
                            `_encode`) drifted from what the import
                            side (`_decode`) reads back — a field
                            serialized but never imported is lost at
                            every handoff
  B004 device-adoption-drift a band adopted as device truth
                            (`self._dev[x] = …` / `_dirty.
                            difference_update((…))`) that is not in
                            `_DEVICE_ADVANCED`, a chain gate comparing
                            `_dirty` against a literal set != the
                            registry, or `_DEVICE_ADVANCED` naming a
                            band outside `_BANDS` — each one desyncs
                            `_can_chain` from what the compiled window
                            actually advances

The registry is DERIVED, not duplicated: `_BANDS`/`_DEVICE_ADVANCED`
are parsed from serving/engine.py's module literals and the cache band
set from the paged-cache dict literal in models/transformer.py, so the
lint cannot drift from the engine (a file under lint may also declare
its own `_BANDS`/`_DEVICE_ADVANCED`/`_CACHE_BANDS` literals — the test
corpora do). A function covers a cache-band requirement either by
naming every band or by iterating the band dict GENERICALLY (a dict
comprehension keyed by its own loop variable, or subscripting with a
loop-bound name) — generic iteration is the idiom that stays correct
when a future pool adds bands, which is exactly why the mutation drill
(tests) replaces it with explicit keys and expects B001.

Annotation grammar (on the `def` line or the lines down to the first
body statement, the `# thread:` placement rule):

    def _make_cow(self):  # band-verb: cow
    def _admit(self, h, s):  # band-verb: alias, import

Pure AST — no jax import, the package's import-light rule.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .diagnostics import (Diagnostic, make, rel_path, repo_root,
                          walk_python_files)

__all__ = ["lint_file", "lint_paths", "load_registry", "BandRegistry",
           "DEFAULT_PATHS", "VERBS"]

# the band-lifecycle files; `--all` lints exactly these
DEFAULT_PATHS = [
    "paddle_tpu/serving/engine.py",
    "paddle_tpu/serving/kv_blocks.py",
    "paddle_tpu/serving/kv_store.py",
    "paddle_tpu/serving/prefix_cache.py",
    "paddle_tpu/serving/fleet.py",
]

VERBS = ("alias", "cow", "serialize", "import", "resume", "retire",
         "sync")

_ANNOT_RE = re.compile(r"#\s*band-verb\s*:\s*([\w\-, ]+)")

# requirement sentinels, resolved against the parsed registry
_CACHE = "<cache-bands>"
_DEVICE = "<device-advanced>"

# verb -> band names a function carrying that verb must propagate.
# The engine's own names are the default; host-bookkeeping files that
# track different state override per (repo-relative path, verb) below.
DEFAULT_VERB_BANDS: Dict[str, Tuple[str, ...]] = {
    "alias": ("tables", "limits", "aidx"),
    "cow": (_CACHE,),
    "serialize": (_CACHE,),
    "import": (_CACHE,),
    "resume": ("tok", "pos", "alive", "temps", "counts", "base_keys",
               "eos"),
    "retire": ("alive", "aidx", "tables", "limits"),
    "sync": (_DEVICE,),
}

FILE_VERB_BANDS: Dict[Tuple[str, str], Tuple[str, ...]] = {
    # kv_store's bands are the wire-record fields (B003 audits the
    # full schema; B001 pins the geometry-critical trio)
    ("paddle_tpu/serving/kv_store.py", "serialize"):
        ("tokens", "meta", "payload"),
    ("paddle_tpu/serving/kv_store.py", "import"):
        ("tokens", "meta", "payload"),
    ("paddle_tpu/serving/kv_store.py", "alias"): ("tokens",),
    ("paddle_tpu/serving/kv_store.py", "retire"): ("parent", "nbytes"),
    # allocator / trie: ref-counts ARE the band being propagated
    ("paddle_tpu/serving/kv_blocks.py", "alias"): ("refs",),
    ("paddle_tpu/serving/kv_blocks.py", "retire"): ("refs", "free"),
    ("paddle_tpu/serving/prefix_cache.py", "alias"): ("refs",),
    ("paddle_tpu/serving/prefix_cache.py", "retire"):
        ("refs", "payload"),
    # fleet: token-level resume + durable-KV handoff side-bands
    ("paddle_tpu/serving/fleet.py", "resume"):
        ("resume", "generation"),
    ("paddle_tpu/serving/fleet.py", "import"):
        ("handoff_package", "handoff_meta"),
}

# verbs each lifecycle file MUST annotate somewhere: a deleted
# annotation silently disables its checks, so absence is a finding
REQUIRED_SITES: Dict[str, Tuple[str, ...]] = {
    "paddle_tpu/serving/engine.py": VERBS,
    "paddle_tpu/serving/kv_store.py": ("serialize", "import"),
    "paddle_tpu/serving/kv_blocks.py": ("alias", "retire"),
    "paddle_tpu/serving/prefix_cache.py": ("alias", "retire"),
    "paddle_tpu/serving/fleet.py": ("resume", "import"),
}

_ENGINE_FILE = "paddle_tpu/serving/engine.py"
_CACHE_FILE = "paddle_tpu/models/transformer.py"

_FALLBACK_CACHE_BANDS = ("k", "v", "k_scale", "v_scale")


class BandRegistry(object):
    """The declarative band registry one lint run checks against."""

    def __init__(self, slot_bands: Tuple[str, ...],
                 device_advanced: Tuple[str, ...],
                 cache_bands: Tuple[str, ...]):
        self.slot_bands = tuple(slot_bands)
        self.device_advanced = frozenset(device_advanced)
        self.cache_bands = tuple(cache_bands)

    def resolve(self, names: Tuple[str, ...]) -> List[str]:
        out: List[str] = []
        for n in names:
            if n == _CACHE:
                out.extend(self.cache_bands)
            elif n == _DEVICE:
                out.extend(sorted(self.device_advanced))
            else:
                out.append(n)
        return out


def _str_tuple(node) -> Optional[Tuple[str, ...]]:
    """The string elements of a tuple/list/set literal (possibly
    wrapped in frozenset(...)/set(...)/tuple(...)), else None."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("frozenset", "set", "tuple") \
            and len(node.args) == 1:
        node = node.args[0]
    if not isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return None
    out = []
    for e in node.elts:
        if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
            return None
        out.append(e.value)
    return tuple(out)


def _module_literals(tree) -> Dict[str, Tuple[str, ...]]:
    """Module-level `NAME = (tuple of str)` assignments (frozenset
    wrapping accepted) — how a linted file declares its own registry."""
    out: Dict[str, Tuple[str, ...]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            vals = _str_tuple(node.value)
            if vals is not None:
                out[node.targets[0].id] = vals
    return out


def _parse_cache_bands(tree) -> Optional[Tuple[str, ...]]:
    """Cache band names from the paged-cache layer dict literal: any
    dict literal whose string keys include both a payload band and a
    `*_scale` side-band (init_paged_cache's quantized branch)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        keys = []
        for k in node.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.append(k.value)
        if keys and "k" in keys and any(k.endswith("_scale")
                                        for k in keys):
            return tuple(keys)
    return None


_REGISTRY_CACHE: Dict[str, BandRegistry] = {}


def load_registry(engine_path: Optional[str] = None,
                  cache_path: Optional[str] = None) -> BandRegistry:
    """Parse the repo's registry ground truth (engine `_BANDS` /
    `_DEVICE_ADVANCED`, transformer cache dict). Cached per path pair."""
    root = repo_root()
    engine_path = engine_path or os.path.join(root, _ENGINE_FILE)
    cache_path = cache_path or os.path.join(root, _CACHE_FILE)
    ck = "%s|%s" % (engine_path, cache_path)
    if ck in _REGISTRY_CACHE:
        return _REGISTRY_CACHE[ck]
    with open(engine_path) as f:
        etree = ast.parse(f.read(), filename=engine_path)
    lits = _module_literals(etree)
    if "_BANDS" not in lits or "_DEVICE_ADVANCED" not in lits:
        raise ValueError(
            "band registry parse failed: %s defines no _BANDS/"
            "_DEVICE_ADVANCED string-tuple literals" % engine_path)
    cache_bands = _FALLBACK_CACHE_BANDS
    if os.path.exists(cache_path):
        with open(cache_path) as f:
            parsed = _parse_cache_bands(
                ast.parse(f.read(), filename=cache_path))
        if parsed is not None:
            cache_bands = parsed
    reg = BandRegistry(lits["_BANDS"], lits["_DEVICE_ADVANCED"],
                       cache_bands)
    _REGISTRY_CACHE[ck] = reg
    return reg


def _file_registry(tree, path: str) -> BandRegistry:
    """Registry for one linted file: its own `_BANDS` /
    `_DEVICE_ADVANCED` / `_CACHE_BANDS` literals when declared (the
    engine itself, test corpora), the repo registry otherwise."""
    lits = _module_literals(tree)
    if "_BANDS" in lits:
        return BandRegistry(
            lits["_BANDS"],
            lits.get("_DEVICE_ADVANCED", ()),
            lits.get("_CACHE_BANDS", _FALLBACK_CACHE_BANDS))
    repo = load_registry()
    if "_CACHE_BANDS" in lits:
        return BandRegistry(repo.slot_bands, tuple(repo.device_advanced),
                            lits["_CACHE_BANDS"])
    return repo


# --- function harvest --------------------------------------------------

class _FnInfo(object):
    """Everything B001/B002 need about one def: referenced band-ish
    names, generic-iteration evidence, local dirty coverage, calls."""

    def __init__(self, node, qualname, cls_name):
        self.node = node
        self.qualname = qualname
        self.cls_name = cls_name  # enclosing class, or None
        self.verbs: List[str] = []
        self.refs: Set[str] = set()
        self.generic = False
        self.self_calls: Set[str] = set()   # self.m() targets
        self.local_calls: Set[str] = set()  # bare-name call targets
        self.dirty_cov: Set[str] = set()    # bands covered locally
        self.dirty_all = False              # bare _mark_dirty()
        self.mutations: List[Tuple[str, int]] = []  # (band, lineno)
        self.schema: Optional[Set[str]] = None
        self.schema_partial = False


def _annotated_verbs(item, src_lines) -> List[str]:
    body_start = item.body[0].lineno if item.body else item.lineno
    for ln in range(item.lineno, body_start + 1):
        if ln - 1 < len(src_lines):
            m = _ANNOT_RE.search(src_lines[ln - 1])
            if m:
                return [v.strip() for v in m.group(1).split(",")
                        if v.strip()]
    return []


def _walk_fn(fn_node):
    """Walk a def's FULL body including nested defs/lambdas (a COW
    maker's compiled body is a nested def) but not the def node
    itself."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _loop_targets(fn_node) -> Set[str]:
    """Names bound as for-loop or comprehension targets anywhere in
    the function — the generic-iteration variables."""
    out: Set[str] = set()

    def names_of(t):
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                out.add(n.id)

    for node in _walk_fn(fn_node):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            names_of(node.target)
        elif isinstance(node, ast.comprehension):
            names_of(node.target)
    return out


def _dev_store_keys(stmt_targets) -> Set[str]:
    """String keys of `self._dev["x"]` subscript assignment targets
    (tuple targets included)."""
    out: Set[str] = set()
    stack = list(stmt_targets)
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Subscript) \
                and isinstance(t.value, ast.Attribute) \
                and t.value.attr == "_dev" \
                and isinstance(t.slice, ast.Constant) \
                and isinstance(t.slice.value, str):
            out.add(t.slice.value)
    return out


def _band_of_target(t, slot_bands) -> Optional[str]:
    """The slot band a store target mutates: `self._tok` or
    `self._tok[...]` (any attribute base named `_<band>`)."""
    if isinstance(t, ast.Subscript):
        t = t.value
    if isinstance(t, ast.Attribute) and t.attr.startswith("_") \
            and t.attr[1:] in slot_bands:
        return t.attr[1:]
    return None


def _harvest_schema(info: _FnInfo):
    """Record schema of a serialize/import function: keys of a
    returned dict literal (full), or the keys subscript-assigned onto
    a returned `dict(...)` copy (partial — `_encode`'s shape)."""
    node = info.node
    dict_keys: Dict[str, Tuple[Set[str], bool]] = {}  # var -> (keys, partial)
    assigns = [sub for sub in _walk_fn(node)
               if isinstance(sub, ast.Assign) and len(sub.targets) == 1]
    # two passes: the tree walk is not source-ordered, so register the
    # dict copies before attributing subscript stores to them
    for sub in assigns:
        t = sub.targets[0]
        if isinstance(t, ast.Name):
            if isinstance(sub.value, ast.Dict):
                keys = {k.value for k in sub.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
                dict_keys[t.id] = (keys, False)
            elif isinstance(sub.value, ast.Call) \
                    and isinstance(sub.value.func, ast.Name) \
                    and sub.value.func.id == "dict" \
                    and sub.value.args:
                dict_keys[t.id] = (set(), True)
    for sub in assigns:
        t = sub.targets[0]
        if isinstance(t, ast.Subscript) \
                and isinstance(t.value, ast.Name) \
                and t.value.id in dict_keys \
                and isinstance(t.slice, ast.Constant) \
                and isinstance(t.slice.value, str):
            dict_keys[t.value.id][0].add(t.slice.value)
    for sub in _walk_fn(node):
        if not isinstance(sub, ast.Return) or sub.value is None:
            continue
        if isinstance(sub.value, ast.Dict):
            keys = {k.value for k in sub.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
            if keys:
                info.schema = keys
                info.schema_partial = False
                return
        elif isinstance(sub.value, ast.Name) \
                and sub.value.id in dict_keys:
            keys, partial = dict_keys[sub.value.id]
            info.schema = keys
            info.schema_partial = partial
            return


def _harvest(tree, src: str, registry: BandRegistry
             ) -> Tuple[List[_FnInfo], Dict[str, Dict[str, _FnInfo]]]:
    """All defs with their band facts, plus per-class method tables."""
    src_lines = src.splitlines()
    infos: List[_FnInfo] = []
    classes: Dict[str, Dict[str, _FnInfo]] = {}

    def visit_fn(item, qual, cls_name):
        info = _FnInfo(item, qual, cls_name)
        info.verbs = _annotated_verbs(item, src_lines)
        loop_names = _loop_targets(item)
        for sub in _walk_fn(item):
            if isinstance(sub, ast.Constant) \
                    and isinstance(sub.value, str):
                info.refs.add(sub.value)
            elif isinstance(sub, ast.Attribute):
                info.refs.add(sub.attr)
                if sub.attr.startswith("_"):
                    info.refs.add(sub.attr[1:])
            elif isinstance(sub, ast.Name):
                info.refs.add(sub.id)
            elif isinstance(sub, ast.Subscript) \
                    and isinstance(sub.slice, ast.Name) \
                    and sub.slice.id in loop_names:
                # kv[band] with band loop-bound: generic band iteration
                info.generic = True
            elif isinstance(sub, ast.DictComp) \
                    and isinstance(sub.key, ast.Name):
                for gen in sub.generators:
                    for n in ast.walk(gen.target):
                        if isinstance(n, ast.Name) \
                                and n.id == sub.key.id:
                            info.generic = True
            elif isinstance(sub, ast.Call):
                f = sub.func
                if isinstance(f, ast.Attribute):
                    if isinstance(f.value, ast.Name) \
                            and f.value.id == "self":
                        info.self_calls.add(f.attr)
                    if f.attr == "_mark_dirty":
                        names = [a.value for a in sub.args
                                 if isinstance(a, ast.Constant)
                                 and isinstance(a.value, str)]
                        if not sub.args:
                            info.dirty_all = True
                        info.dirty_cov.update(names)
                    elif f.attr == "difference_update" \
                            and isinstance(f.value, ast.Attribute) \
                            and f.value.attr == "_dirty":
                        for a in sub.args:
                            vals = _str_tuple(a)
                            if vals is not None:
                                info.dirty_cov.update(vals)
                            elif isinstance(a, ast.Name):
                                # e.g. _DEVICE_ADVANCED by name
                                info.dirty_cov.update(
                                    registry.device_advanced)
                elif isinstance(f, ast.Name):
                    info.local_calls.add(f.id)
            if isinstance(sub, (ast.Assign, ast.AugAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                info.dirty_cov.update(_dev_store_keys(targets))
                flat = []
                stack = list(targets)
                while stack:
                    t = stack.pop()
                    if isinstance(t, (ast.Tuple, ast.List)):
                        stack.extend(t.elts)
                    else:
                        flat.append(t)
                for t in flat:
                    band = _band_of_target(t, registry.slot_bands)
                    if band is not None:
                        info.mutations.append((band, sub.lineno))
        if "serialize" in info.verbs or "import" in info.verbs:
            _harvest_schema(info)
        infos.append(info)
        return info

    def walk_body(body, prefix, cls_name, methods):
        for item in body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = "%s.%s" % (prefix, item.name) if prefix \
                    else item.name
                info = visit_fn(item, qual, cls_name)
                if methods is not None:
                    methods[item.name] = info
            elif isinstance(item, ast.ClassDef):
                cm: Dict[str, _FnInfo] = {}
                classes[item.name] = cm
                walk_body(item.body, item.name, item.name, cm)

    walk_body(tree.body, "", None, None)
    return infos, classes


# --- closures ----------------------------------------------------------

def _closure_refs(info: _FnInfo, by_name: Dict[str, _FnInfo],
                  cls_methods: Dict[str, _FnInfo]
                  ) -> Tuple[Set[str], bool]:
    """Referenced names + generic flag, transitively through same-class
    `self.m()` calls and module-level bare calls (a retire that frees
    through `_free_slot_blocks` propagates tables/limits there)."""
    seen: Set[int] = set()
    refs: Set[str] = set()
    generic = False
    stack = [info]
    while stack:
        cur = stack.pop()
        if id(cur) in seen:
            continue
        seen.add(id(cur))
        refs |= cur.refs
        generic = generic or cur.generic
        for name in cur.self_calls:
            nxt = cls_methods.get(name)
            if nxt is not None:
                stack.append(nxt)
        for name in cur.local_calls:
            nxt = by_name.get(name)
            if nxt is not None:
                stack.append(nxt)
    return refs, generic


def _dirty_covered(band: str, info: _FnInfo,
                   cls_methods: Dict[str, _FnInfo],
                   callers: Dict[str, Set[str]],
                   _seen: Optional[Set[str]] = None) -> bool:
    """B002 coverage: the method covers the band locally, or EVERY
    same-class caller (transitively) does — `_emit` bumping counts is
    fine because every path into it marked counts dirty or adopted the
    device copy."""
    if info.dirty_all or band in info.dirty_cov:
        return True
    name = info.node.name
    seen = _seen or set()
    if name in seen:
        return True  # cycle: judged by the other members
    seen.add(name)
    ins = callers.get(name, set())
    if not ins:
        return False
    return all(_dirty_covered(band, cls_methods[c], cls_methods,
                              callers, seen)
               for c in ins if c in cls_methods)


# --- checks ------------------------------------------------------------

def _check_b001(infos, classes, registry, rel, diags):
    by_name = {i.node.name: i for i in infos if i.cls_name is None}
    seen_verbs: Set[str] = set()
    for info in infos:
        if not info.verbs:
            continue
        cls_methods = classes.get(info.cls_name, {}) \
            if info.cls_name else {}
        refs, generic = _closure_refs(info, by_name, cls_methods)
        for verb in info.verbs:
            if verb not in VERBS:
                diags.append(make(
                    "B001", rel, info.node.lineno, info.qualname,
                    "unknown-verb:%s" % verb,
                    "unknown lifecycle verb %r (have: %s)"
                    % (verb, ", ".join(VERBS))))
                continue
            seen_verbs.add(verb)
            req = FILE_VERB_BANDS.get((rel, verb))
            from_default = req is None
            if req is None:
                req = DEFAULT_VERB_BANDS[verb]
            for name in req:
                is_cache = name == _CACHE
                if from_default and name not in (_CACHE, _DEVICE) \
                        and name not in registry.slot_bands:
                    # default requirements follow the file's registry:
                    # a band the registry does not declare cannot be
                    # required (per-file overrides stay unconditional)
                    continue
                for band in registry.resolve((name,)):
                    if band in refs or (is_cache and generic):
                        continue
                    diags.append(make(
                        "B001", rel, info.node.lineno, info.qualname,
                        "%s:%s" % (verb, band),
                        "lifecycle verb %r does not propagate band "
                        "%r: every registered band/side-band must "
                        "survive this operation (reference it, or "
                        "iterate the band dict generically)"
                        % (verb, band)))
    for verb in REQUIRED_SITES.get(rel, ()):
        if verb not in seen_verbs:
            diags.append(make(
                "B001", rel, 1, "<module>", "missing-verb:%s" % verb,
                "lifecycle file carries no '# band-verb: %s' "
                "annotation — the %s propagation check is silently "
                "disabled" % (verb, verb)))


def _check_b002(infos, classes, registry, rel, diags):
    bands = set(registry.slot_bands)
    for info in infos:
        # _mark_dirty with a name outside the registry dirties nothing
        for sub in _walk_fn(info.node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "_mark_dirty":
                for a in sub.args:
                    if isinstance(a, ast.Constant) \
                            and isinstance(a.value, str) \
                            and a.value not in bands:
                        diags.append(make(
                            "B002", rel, sub.lineno, info.qualname,
                            "unknown-band:%s" % a.value,
                            "_mark_dirty(%r) names no registered band "
                            "— the upload this meant to force never "
                            "happens" % a.value))
    for cls_name, methods in classes.items():
        if "_mark_dirty" not in methods:
            continue  # not a dirty-protocol class
        callers: Dict[str, Set[str]] = {}
        for name, info in methods.items():
            for callee in info.self_calls:
                callers.setdefault(callee, set()).add(name)
        for name, info in methods.items():
            if name == "__init__":
                continue  # construction writes every band by design
            for band, lineno in info.mutations:
                if _dirty_covered(band, info, methods, callers):
                    continue
                diags.append(make(
                    "B002", rel, lineno, info.qualname, band,
                    "host band mirror %r mutated without _mark_dirty/"
                    "device adoption on this path (or on every caller) "
                    "— the device copy silently keeps stale truth"
                    % band))


def _check_b003(infos, rel, diags):
    ser = [i for i in infos if "serialize" in i.verbs
           and i.schema is not None]
    imp = [i for i in infos if "import" in i.verbs
           and i.schema is not None]
    ser_full = set().union(*[i.schema for i in ser
                             if not i.schema_partial]) \
        if any(not i.schema_partial for i in ser) else set()
    imp_full = set().union(*[i.schema for i in imp
                             if not i.schema_partial]) \
        if any(not i.schema_partial for i in imp) else set()
    if ser_full and imp_full:
        for i in ser:
            if i.schema_partial:
                continue
            for key in sorted(i.schema - imp_full):
                diags.append(make(
                    "B003", rel, i.node.lineno, i.qualname,
                    "unread:%s" % key,
                    "record field %r is serialized but the import "
                    "side never reads it back — lost at every "
                    "handoff/restart" % key))
        for i in imp:
            if i.schema_partial:
                continue
            for key in sorted(i.schema - ser_full):
                diags.append(make(
                    "B003", rel, i.node.lineno, i.qualname,
                    "unwritten:%s" % key,
                    "import side reads record field %r that no "
                    "serialize side writes — KeyError (or a silent "
                    "default) on every real record" % key))
    if imp_full:
        for i in ser:
            if not i.schema_partial:
                continue
            for key in sorted(i.schema - imp_full):
                diags.append(make(
                    "B003", rel, i.node.lineno, i.qualname,
                    "unread:%s" % key,
                    "encoder rewrites field %r that the decoder "
                    "never reads back" % key))


def _check_b004(infos, registry, rel, diags):
    dev = registry.device_advanced
    bands = set(registry.slot_bands)
    if registry.slot_bands:
        for band in sorted(dev - bands):
            diags.append(make(
                "B004", rel, 1, "<module>",
                "device-advanced-drift:%s" % band,
                "_DEVICE_ADVANCED names %r which is not in _BANDS — "
                "the chain gate consults a band that cannot exist"
                % band))
    for info in infos:
        for sub in _walk_fn(info.node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "difference_update" \
                    and isinstance(sub.func.value, ast.Attribute) \
                    and sub.func.value.attr == "_dirty":
                for a in sub.args:
                    vals = _str_tuple(a)
                    if vals is None:
                        continue
                    for v in vals:
                        if v not in dev:
                            diags.append(make(
                                "B004", rel, sub.lineno, info.qualname,
                                "adopt:%s" % v,
                                "dirty bit cleared for %r which the "
                                "compiled window does not advance — "
                                "a host change to it would never "
                                "re-upload" % v))
            elif isinstance(sub, (ast.Assign, ast.AugAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                for key in sorted(_dev_store_keys(targets)):
                    if info.node.name != "_band" and key not in dev:
                        diags.append(make(
                            "B004", rel, sub.lineno, info.qualname,
                            "adopt:%s" % key,
                            "device copy of %r adopted outside the "
                            "_band upload but it is not in "
                            "_DEVICE_ADVANCED — _can_chain cannot "
                            "see it go stale" % key))
            elif isinstance(sub, ast.BinOp) \
                    and isinstance(sub.op, ast.BitAnd):
                for side in (sub.left, sub.right):
                    vals = _str_tuple(side)
                    if vals is not None and set(vals) != set(dev) \
                            and _mentions_dirty(sub):
                        diags.append(make(
                            "B004", rel, sub.lineno, info.qualname,
                            "chain-gate:%s" % ",".join(sorted(vals)),
                            "chain gate intersects _dirty with a "
                            "literal band set != _DEVICE_ADVANCED — "
                            "the gate and the scan have drifted"))


def _mentions_dirty(binop) -> bool:
    for side in (binop.left, binop.right):
        if isinstance(side, ast.Attribute) and side.attr == "_dirty":
            return True
    return False


# --- entry points ------------------------------------------------------

def lint_file(path: str) -> List[Diagnostic]:
    with open(path) as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    rel = rel_path(path)
    registry = _file_registry(tree, path)
    infos, classes = _harvest(tree, src, registry)
    diags: List[Diagnostic] = []
    _check_b001(infos, classes, registry, rel, diags)
    _check_b002(infos, classes, registry, rel, diags)
    _check_b003(infos, rel, diags)
    _check_b004(infos, registry, rel, diags)
    diags.sort(key=lambda d: (d.path, d.line, d.code, d.detail))
    return diags


def lint_paths(paths=None) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for f in walk_python_files(paths, DEFAULT_PATHS):
        diags.extend(lint_file(f))
    return diags
