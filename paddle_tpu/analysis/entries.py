"""Built-in program entries for `python -m paddle_tpu.analysis --all`.

The reference validated every ProgramDesc a trainer submitted; our
equivalent of "the programs the repo ships" is a small set of
representative graphs built through the real layer stack — a regression
net and a classification net, each with a full backward + optimizer
region. `--all` (and the tier-1 self-check) verifies these end to end,
so a regression in the layer helpers, `append_backward`, or an
optimizer's op emission that produces malformed IR fails the lint gate
even if no runtime test happens to execute that path.

Each entry builds fresh `Program`s under `program_guard` (no global
default-program pollution) and returns (main, startup, feeds, fetches).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

__all__ = ["ENTRIES", "build_entry", "verify_entries"]


def _fit_a_line():
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[13], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return main, startup, [], [loss.name]


def _recognize_digits_mlp():
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[784], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        hidden = fluid.layers.fc(input=img, size=32, act="relu")
        logits = fluid.layers.fc(input=hidden, size=10, act="softmax")
        loss = fluid.layers.mean(
            x=fluid.layers.cross_entropy(input=logits, label=label))
        acc = fluid.layers.accuracy(input=logits, label=label)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main, startup, [], [loss.name, acc.name]


ENTRIES: Dict[str, Callable] = {
    "fit_a_line": _fit_a_line,
    "recognize_digits_mlp": _recognize_digits_mlp,
}


def build_entry(name: str):
    return ENTRIES[name]()


def verify_entries(names=None) -> List:
    """Verify every built-in entry's main AND startup program."""
    from .program_lint import verify_program

    diags = []
    for name in names or sorted(ENTRIES):
        main, startup, feeds, fetches = build_entry(name)
        diags.extend(verify_program(
            main, feeds=feeds, fetches=fetches, label="<%s>" % name))
        diags.extend(verify_program(
            startup, label="<%s:startup>" % name))
    return diags
