"""Fault injection as a first-class fixture (SURVEY §5.3: the reference
had no injection framework — its elasticity was only provable on a live
cluster; here preemptions/crashes/stalls are injectable into any worker
or the trainer CLI itself, so recovery paths are CI-testable).

A fault spec is a comma-separated string, e.g.::

    PADDLE_FAULT="kill@12"          SIGKILL self at step 12 (preemption)
    PADDLE_FAULT="exc@7"            raise FaultInjected at step 7
    PADDLE_FAULT="delay@3:0.5"      sleep 0.5s at step 3 (straggler)
    PADDLE_FAULT="corrupt@5:/path"  flip bytes of a file at step 5
    PADDLE_FAULT="hang@4"           spin forever at step 4 (livelock: the
                                    process stays up but makes no
                                    progress — only a heartbeat timeout
                                    can detect it)
    PADDLE_FAULT="netsplit@3:2.0"   drop coordinator connections for 2 s
                                    starting at step 3 (partition: RPCs
                                    fail and must ride it out on backoff)
    PADDLE_FAULT="nanloss@5"        SILENT failure (ISSUE 10): the loss
                                    the training loop observes at step 5
                                    becomes NaN — the process neither
                                    crashes nor hangs; only the training
                                    sentinel's divergence detection can
                                    see it. The loop opts in by passing
                                    its loss through
                                    `injector.poison_loss(loss)`.
    PADDLE_FAULT="spike@5:50"       soft SILENT failure: the observed
                                    loss at step 5 is multiplied by 50 —
                                    a one-step spike the sentinel's
                                    EWMA + hysteresis must classify
                                    (transient: tolerated; sustained:
                                    tripped). Arg is the factor,
                                    default 10, must be > 1.
    PADDLE_FAULT="garble@5"         SILENT serving integrity fault
                                    (ISSUE 15): from step 5 ON, every
                                    token this engine emits is
                                    wrong-but-FINITE (the engine
                                    consumes `injector.garbled` and
                                    perturbs each emitted token to a
                                    different valid vocab id). STICKY
                                    by design — a faulty core keeps
                                    computing wrong until the
                                    incarnation is replaced — so the
                                    in-step numeric traps never fire
                                    (nothing is NaN) and only a
                                    known-answer canary mismatch can
                                    catch it. Models the SDC failure
                                    class TPU-scale fleets see.
    PADDLE_FAULT="flip@5"           SILENT serving integrity fault
                                    (ISSUE 15): at step 5 the engine
                                    corrupts ONE resident KV block's
                                    payload in place (finite garbage,
                                    lowest in-use physical id —
                                    deterministic on a fixed-seed
                                    trace; consumed via
                                    `injector.take_flip()`, re-armed
                                    each tick until a block is
                                    resident). Requests attending
                                    through the block decode wrong
                                    tokens; only a block FINGERPRINT
                                    spot-check (at aliased re-open /
                                    failover resume) can catch it.
    PADDLE_FAULT="store_corrupt@2"  SILENT durable-KV fault (ISSUE 16):
                                    the 2nd record put into the
                                    KVBlockStore is garbled AT REST
                                    (one payload byte flipped in RAM
                                    and in store.jsonl; the recorded
                                    crc stays honest, so only the read
                                    path's crc check can catch it).
                                    N counts STORE RECORDS, not steps
                                    — the store consumes these via
                                    `injector.store_tick()` per put.
                                    Import/warm paths must skip +
                                    quarantine the record and fall
                                    back to re-prefill, counted,
                                    token-identical.
    PADDLE_FAULT="store_trunc@2"    as store_corrupt@N but the record's
                                    payload is TRUNCATED (the torn-
                                    write shape: nbytes disagrees with
                                    the bytes present).
    PADDLE_FAULT="slow@3:2.0/0.1"   GRAY failure (ISSUE 8): starting at
                                    step 3, every tick sleeps 0.1 s until
                                    2.0 s of wall time have passed — the
                                    process keeps heartbeating (each step
                                    completes!) but is too slow to meet
                                    latency targets. Unlike delay@ (one
                                    pause) or hang@ (no progress at all),
                                    slow@ is invisible to liveness checks
                                    and only detectable by step-latency /
                                    progress-watermark health scoring
                                    (the fleet's slow_replica_factor
                                    demotion). Arg is dur[/per]; per
                                    defaults to 0.05 s.

The trainer CLI ticks its injector once per batch when PADDLE_FAULT is
set; worker scripts call `default_injector().tick()` wherever their
step boundary is.

Serving semantics (ISSUE 6): `ServingEngine.step()` is a step boundary
too — when PADDLE_FAULT is set, every scheduler step (one admission +
prefill-chunk + batched-decode round) ticks the default injector, so
`kill@N` SIGKILLs a serving replica mid-decode, `delay@N:dur` turns it
into a straggler that misses its fleet heartbeat deadline (zombie
drill), and `exc@N` crashes the replica thread in-process. Fleet kill
drills count on this: N is a deterministic engine-step index on a
fixed-seed trace, so the fault lands with requests in flight and the
journal-resubmit/failover path is exercised, not the happy path. An
engine can also be handed its OWN `FaultInjector` (the in-process fleet
drills do, one per replica) — the env-driven default stays process-wide
on purpose, like a host-level fault.
"""

from __future__ import annotations

import os
import signal
import time
from typing import List, Optional

__all__ = [
    "FaultInjected", "FaultInjector", "default_injector", "corrupt_file",
    "netsplit_active",
]

ENV_VAR = "PADDLE_FAULT"

# wall-clock end of the current injected partition window (0 = none).
# Process-wide on purpose: every RemoteCoordinator in the process loses
# its "network" at once, like a real NIC/switch failure would look from
# one host.
_netsplit_until = 0.0


def netsplit_active() -> bool:
    """True while an injected netsplit window is open. Transport clients
    (RemoteCoordinator) consult this and drop/refuse connections so the
    partition is exercised end-to-end without real firewalling."""
    return time.time() < _netsplit_until


class FaultInjected(RuntimeError):
    """Raised by exc@N faults."""


def corrupt_file(path: str, offset: int = -4, flip: bytes = b"\x5a"):
    """Flip byte(s) in `path` (checkpoint-corruption fixture: the CRC
    check must reject the file afterwards)."""
    with open(path, "r+b") as f:
        f.seek(offset, os.SEEK_END if offset < 0 else os.SEEK_SET)
        pos = f.tell()
        raw = f.read(len(flip))
        if len(raw) != len(flip):
            raise ValueError(
                "corrupt_file: offset %d leaves only %d byte(s) to flip "
                "in %s" % (offset, len(raw), path)
            )
        f.seek(pos)
        f.write(bytes(b ^ f2 for b, f2 in zip(raw, flip)))


class _Fault(object):
    def __init__(self, kind: str, step: int, arg: Optional[str]):
        self.kind = kind
        self.step = step
        self.arg = arg

    def fire(self):
        if self.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        elif self.kind == "exc":
            raise FaultInjected("injected fault at step %d" % self.step)
        elif self.kind == "delay":
            time.sleep(float(self.arg or "1.0"))
        elif self.kind == "corrupt":
            corrupt_file(self.arg)
        elif self.kind == "hang":
            # livelock, NOT a crash: the process keeps its sockets and
            # pid, stops heartbeating, and never returns — detectable
            # only by the supervisor's heartbeat deadline. sleep in
            # small slices so an external SIGKILL reaps promptly.
            while True:
                time.sleep(0.05)
        elif self.kind == "netsplit":
            global _netsplit_until
            _netsplit_until = time.time() + float(self.arg or "1.0")
        else:
            raise ValueError("unknown fault kind %r" % self.kind)


_KINDS = ("kill", "exc", "delay", "corrupt", "hang", "netsplit", "slow",
          "nanloss", "spike", "garble", "flip", "store_corrupt",
          "store_trunc")

# fault kinds whose @N indexes the Nth KV-STORE record, not the Nth
# step boundary: tick() never fires them, store_tick() consumes them,
# and arm(relative=True) must NOT shift their index by the step count
_STORE_KINDS = ("store_corrupt", "store_trunc")


def _parse_slow_arg(arg: str):
    """slow@N:dur[/per] -> (window_s, per_tick_sleep_s), validated —
    a bad window or a negative stall must fail at PARSE time, not as
    a time.sleep(-x) crash loop N serving steps later."""
    dur_s, _, per_s = (arg or "1.0").partition("/")
    dur, per = float(dur_s), float(per_s or "0.05")
    if dur <= 0.0:
        raise ValueError("slow@N:dur needs a positive window, got %r" % dur)
    if per < 0.0:
        raise ValueError("slow@N:dur/per needs per >= 0, got %r" % per)
    return dur, per


def _parse(spec: str) -> List[_Fault]:
    faults = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        kind, _, rest = part.partition("@")
        kind = kind.strip()
        step_s, _, arg = rest.partition(":")
        # a bad spec must fail HERE, not N training steps later
        if kind not in _KINDS:
            raise ValueError(
                "unknown fault kind %r (want one of %s)" % (kind, _KINDS)
            )
        if kind == "corrupt" and not arg:
            raise ValueError("corrupt@N:<path> needs the file path")
        if kind in ("delay", "netsplit"):
            arg = str(float(arg or "1.0"))  # fail fast on a bad duration
        if kind == "slow":
            _parse_slow_arg(arg)  # fail fast on a bad dur[/per]
        if kind == "spike":
            mag = float(arg or "10")
            if mag <= 1.0:
                raise ValueError(
                    "spike@N:mag needs a factor > 1, got %r" % mag)
            arg = str(mag)
        faults.append(_Fault(kind, int(step_s), arg or None))
    return faults


class FaultInjector(object):
    """Counts step boundaries via tick(); fires matching faults."""

    def __init__(self, spec: Optional[str] = None):
        self.faults = _parse(
            spec if spec is not None else os.environ.get(ENV_VAR, "")
        )
        self.step = 0
        # open slow@ window: (wall end, per-tick sleep). Injector state,
        # not _Fault state: the window outlives the step that opened it
        self._slow_until = 0.0
        self._slow_per = 0.0
        # armed loss fault for the CURRENT step, consumed (one-shot) by
        # poison_loss(): ("nanloss", None) or ("spike", factor)
        self._loss_fault = None
        # serving integrity faults (ISSUE 15): garble is STICKY from
        # its step on (a faulty core keeps computing wrong); flip is
        # armed at its step and stays pending until the engine finds a
        # resident block to corrupt (take_flip consumes it)
        self._garbled = False
        self._flip_pending = False
        # durable-KV faults (ISSUE 16): store_corrupt@N/store_trunc@N
        # count STORE RECORDS — the KVBlockStore ticks this counter
        # once per put and the matching fault fires one-shot
        self._store_puts = 0

    @property
    def active(self) -> bool:
        return bool(self.faults)

    @property
    def slowed(self) -> bool:
        """True while an injected slow@ (gray) window is open."""
        return time.monotonic() < self._slow_until

    @property
    def garbled(self) -> bool:
        """True from a garble@ step on (sticky): the consuming engine
        perturbs every emitted token to a wrong-but-finite vocab id."""
        return self._garbled

    def rearm_flip(self):
        """Put a consumed flip@ back (the engine found nothing resident
        to corrupt this step — retry at the next step boundary)."""
        self._flip_pending = True

    def take_flip(self) -> bool:
        """Consume a pending flip@ fault. The engine calls this every
        step; the first call with a resident KV block to corrupt wins
        (the fault stays pending across ticks where nothing is
        resident, so flip@1 on an idle engine still lands on the first
        real block)."""
        if self._flip_pending:
            self._flip_pending = False
            return True
        return False

    def arm(self, spec: str, relative: bool = True):
        """Add faults mid-run. With `relative=True` (default) the @N
        indices count from the CURRENT step — `arm("delay@3:1.0")`
        fires three ticks from now. Drills use this to warm a system up
        (compile, prime caches) under no faults and then schedule the
        fault at a deterministic step of the measured phase, without
        hand-counting the warm-up's ticks. Store faults shift by the
        STORE-RECORD counter instead — their @N never counted steps."""
        new = _parse(spec)
        if relative:
            for f in new:
                f.step += (self._store_puts if f.kind in _STORE_KINDS
                           else self.step)
        self.faults.extend(new)

    def store_tick(self):
        """Advance the KV-store record counter (the KVBlockStore calls
        this once per `put`); returns "corrupt" / "trunc" when the Nth
        record has a store fault armed (one-shot), else None."""
        self._store_puts += 1
        for f in self.faults:
            if (f.kind in _STORE_KINDS and f.step == self._store_puts
                    and not getattr(f, "spent", False)):
                f.spent = True  # one-shot: the Nth record, exactly once
                return f.kind[len("store_"):]
        return None

    def tick(self):
        """Advance one step; fire any fault scheduled for it. While a
        slow@ window is open every tick sleeps the window's per-step
        stall — the step COMPLETES (heartbeats keep flowing), it is
        just late: the gray-failure shape delay@/hang@ cannot model."""
        self.step += 1
        for f in self.faults:
            if f.step == self.step:
                if f.kind == "slow":
                    dur, per = _parse_slow_arg(f.arg)
                    self._slow_until = time.monotonic() + dur
                    self._slow_per = per
                elif f.kind in ("nanloss", "spike"):
                    # silent fault: nothing fires HERE — the training
                    # loop's poison_loss() call this step observes it
                    self._loss_fault = (f.kind, f.arg)
                elif f.kind == "garble":
                    # silent + sticky: the serving engine consumes the
                    # `garbled` property on every emission from now on
                    self._garbled = True
                elif f.kind == "flip":
                    # silent one-shot: pending until take_flip() finds
                    # a resident block to corrupt
                    self._flip_pending = True
                elif f.kind in _STORE_KINDS:
                    # counted in STORE RECORDS, not steps: only
                    # store_tick() may consume these (a step index
                    # colliding with @N must not fire them)
                    pass
                else:
                    f.fire()
        if self.slowed:
            time.sleep(self._slow_per)
        return self.step

    def poison_loss(self, loss):
        """Pass the step's observed loss through any armed silent loss
        fault (nanloss@/spike@) and disarm it. Training loops that
        integrate the sentinel call this right after computing their
        loss; loops that don't are simply immune to these fault kinds
        (the spec parses, nothing fires)."""
        lf = self._loss_fault
        self._loss_fault = None
        if lf is None:
            return loss
        kind, arg = lf
        if kind == "nanloss":
            return float("nan")
        return float(loss) * float(arg or "10")


_default: Optional[FaultInjector] = None


def default_injector() -> FaultInjector:
    """Process-wide injector built from PADDLE_FAULT (parsed once)."""
    global _default
    if _default is None:
        _default = FaultInjector()
    return _default
