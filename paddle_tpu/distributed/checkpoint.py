"""Durable training checkpoints: CRC-checked, atomic, shard-aware.

Go pserver parity (go/pserver/service.go:120-226,346): state is written
with CRC32 sidecars and the metadata commit is one atomic rename, so a
half-written checkpoint is never visible and a corrupt shard is rejected
at load. Serves the Fluid save/load_persistables job (fluid/io.py) with
optimizer state included — resume is exact.

Multi-host/sharded (round 2): partially-addressable jax.Arrays (tensor-
parallel weights, FSDP-sharded optimizer state spanning processes) are
saved shard-by-shard — each process writes only the shards it owns
(replica 0 of each), with the global index of every shard recorded in its
per-process meta. Loading merges ALL process metas found in the
directory and reassembles each entry's global value, so a checkpoint
taken on N processes restores on ANY process count — the elastic
resize-on-resume the reference's Go stack gets from etcd-coordinated
pserver shards (go/pserver/etcd_client.go:70-150).
"""

from __future__ import annotations

import glob
import json
import logging
import os
import re
import shutil
import sys
import zlib

import jax
import numpy as np

__all__ = ["save_checkpoint", "load_checkpoint", "latest_step", "retain",
           "resume_or_init", "verify_checkpoint", "verify_step"]

_LOG = logging.getLogger(__name__)

_STEP_DIR_RE = re.compile(r"^step_(\d+)$")


def _step_dir(dirname: str, step: int) -> str:
    return os.path.join(dirname, "step_%010d" % int(step))


def _list_step_dirs(dirname: str):
    """[(step, path)] of step-keyed subdirectories, newest first."""
    out = []
    try:
        names = os.listdir(dirname)
    except OSError:
        return out
    for n in names:
        m = _STEP_DIR_RE.match(n)
        if m:
            out.append((int(m.group(1)), os.path.join(dirname, n)))
    out.sort(reverse=True)
    return out


def _metas_complete(metas) -> bool:
    if not metas:
        return False
    expected = max(m.get("process_count", 1) for m in metas)
    return len(metas) >= expected


def _meta_name(pidx=None) -> str:
    return "checkpoint.meta.p%d.json" % (
        jax.process_index() if pidx is None else pidx
    )


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def _fname(name: str, pidx: int, shard: int = None) -> str:
    base = name.replace("/", "__")
    if shard is None:
        return "%s.p%d.npy" % (base, pidx)
    return "%s.p%d.s%d.npy" % (base, pidx, shard)


def _atomic_save(dirname: str, fname: str, arr: np.ndarray):
    tmp = os.path.join(dirname, fname + ".tmp")
    with open(tmp, "wb") as fh:  # np.save(path) would append ".npy"
        np.save(fh, np.ascontiguousarray(arr))
    os.replace(tmp, os.path.join(dirname, fname))


def _index_to_json(index, shape):
    """A shard's global index (tuple of slices) -> [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_checkpoint(scope, dirname: str, step: int = 0, extra: dict = None,
                    keep_last: int = 1, stateful: dict = None,
                    protect=None):
    """Write every scope entry (params + optimizer state + BN stats) under
    `dirname/step_<N>/`. Safe against interruption: data files land first,
    then the meta file commits the checkpoint with one atomic rename — and
    because every step gets its own subdirectory, a crash mid-save never
    touches the last committed step (Go pserver keeps its last good
    checkpoint the same way, service.go:346). Older steps are pruned only
    after the new step's metas are complete. Sharded arrays: this process
    saves only its owned (replica-0) shards.

    `stateful` maps names to objects with a JSON-serializable
    `state_dict()` (a data.DataLoader cursor, an LR schedule, ...);
    their states commit atomically with the tensors and are restored by
    load_checkpoint/resume_or_init(stateful=...) — so a supervisor
    restart resumes the input pipeline at the exact record the model
    state was checkpointed at."""
    extra = dict(extra or {})
    if stateful:
        extra["stateful"] = {
            name: obj.state_dict() for name, obj in stateful.items()
        }
    root = dirname
    dirname = _step_dir(dirname, step)
    os.makedirs(dirname, exist_ok=True)
    pidx = jax.process_index()
    entries = {}
    for name in sorted(scope.keys()):
        val = scope.get(name)
        if val is None:
            continue
        if (
            isinstance(val, (jax.Array, _HostShardedArray))
            and not val.is_fully_replicated
        ):
            # genuinely sharded (TP / FSDP): write shard-by-shard — the
            # same path whether the shards span processes or not, and no
            # full-array materialisation for big weights
            shards_meta = []
            for k, shard in enumerate(val.addressable_shards):
                if shard.replica_id != 0:
                    continue  # another device holds this same shard
                arr = np.asarray(shard.data)
                fname = _fname(name, pidx, k)
                _atomic_save(dirname, fname, arr)
                shards_meta.append(
                    {
                        "file": fname,
                        "crc32": _crc(arr),
                        "index": _index_to_json(shard.index, val.shape),
                    }
                )
            if shards_meta:
                entries[name] = {
                    "sharded": True,
                    "global_shape": list(val.shape),
                    "dtype": str(val.dtype),
                    "shards": shards_meta,
                }
        elif isinstance(val, jax.Array) and not val.is_fully_addressable:
            # fully replicated across processes: process 0 writes it once
            if pidx == 0:
                arr = np.asarray(val)
                fname = _fname(name, pidx)
                _atomic_save(dirname, fname, arr)
                entries[name] = {
                    "file": fname,
                    "crc32": _crc(arr),
                    "shape": list(arr.shape),
                    "dtype": str(arr.dtype),
                }
        else:
            arr = np.asarray(val)
            fname = _fname(name, pidx)
            _atomic_save(dirname, fname, arr)
            entries[name] = {
                "file": fname,
                "crc32": _crc(arr),
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
            }
    meta = {
        "step": int(step),
        "process": pidx,
        "process_count": jax.process_count(),
        "entries": entries,
        "extra": extra,
    }
    tmp = os.path.join(dirname, _meta_name() + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(dirname, _meta_name()))
    meta["dir"] = dirname
    _prune_old_steps(root, keep=keep_last, protect=protect)
    return meta


def _protect_set(protect):
    if protect is None:
        return frozenset()
    if isinstance(protect, (list, tuple, set, frozenset)):
        return frozenset(int(p) for p in protect if p is not None)
    return frozenset([int(protect)])


def _prune_old_steps(root: str, keep: int = 1, protect=None):
    """Remove step directories older than the newest COMPLETE step (all
    expected process metas committed), keeping `keep` complete steps.
    Steps in `protect` (the sentinel's known-good step) are never
    removed and never consume the keep budget. Racing deleters (every
    process prunes after its own save) are harmless: rmtree errors are
    ignored."""
    protect = _protect_set(protect)
    steps = _list_step_dirs(root)
    complete_seen = 0
    for s, path in steps:  # newest first
        if s in protect:
            continue  # known-good: the rollback target outlives GC
        if _metas_complete(_dir_metas(path)):
            complete_seen += 1
            if complete_seen > keep:
                shutil.rmtree(path, ignore_errors=True)
        elif complete_seen >= keep:
            # an older incomplete step can never become complete again
            shutil.rmtree(path, ignore_errors=True)


def retain(dirname: str, keep_last: int = 1, protect=None):
    """Garbage-collect old checkpoint steps under `dirname`, keeping the
    newest `keep_last` COMPLETE steps (plus any newer still-incomplete
    save in flight). A crash-looping worker checkpoints every restart
    cycle; without GC its disk fills exactly when the job is least
    healthy — the supervisor calls this after every restart. `protect`
    (a step or list of steps — the sentinel's last known-good) is
    exempt from collection, so a divergence rollback always finds its
    target on disk. Returns the steps still on disk, newest first."""
    if keep_last < 1:
        raise ValueError("retain(keep_last=%d): must keep >= 1" % keep_last)
    _prune_old_steps(dirname, keep=keep_last, protect=protect)
    return [s for s, _ in _list_step_dirs(dirname)]


def _verify_step_dir(path: str):
    """Re-check one step directory offline: metas complete, every
    referenced shard file present, readable, and matching its recorded
    CRC32. Returns (ok, problems) where `problems` names each failure
    (which entry, which file, which CRC) — the evidence trail a resume
    fallback logs and the `verify` CLI prints."""
    problems = []
    metas = _dir_metas(path)
    if not metas:
        return False, ["no committed meta files"]
    if not _metas_complete(metas):
        expected = max(m.get("process_count", 1) for m in metas)
        return False, [
            "incomplete: %d of %d process meta file(s) present"
            % (len(metas), expected)]
    latest = max(m["step"] for m in metas)
    for m in metas:
        if m["step"] != latest:
            continue
        for name in sorted(m["entries"]):
            ent = m["entries"][name]
            shards = ent["shards"] if ent.get("sharded") else [ent]
            for sh in shards:
                fp = os.path.join(path, sh["file"])
                if not os.path.exists(fp):
                    problems.append(
                        "entry %r: missing file %s" % (name, sh["file"]))
                    continue
                try:
                    arr = np.load(fp)
                except Exception as e:  # torn header, truncation, ...
                    problems.append(
                        "entry %r: unreadable %s (%s: %s)"
                        % (name, sh["file"], type(e).__name__, e))
                    continue
                got = _crc(arr)
                if got != sh["crc32"]:
                    problems.append(
                        "entry %r: CRC mismatch in %s (recorded %d, "
                        "file has %d)" % (name, sh["file"],
                                          sh["crc32"], got))
    return not problems, problems


def verify_step(dirname: str, step: int):
    """Verify ONE step directory under `dirname` — metas complete,
    every shard file present, readable, CRC-matching. This is exactly
    the per-candidate check `resume_or_init`'s walk-back runs before
    trusting a checkpoint; exposed so other consumers (the serving
    fleet's `roll_weights` — no replica may touch a candidate weight
    set before its CRC walk passes) share the same verification
    instead of re-deriving it. Returns (ok, problems)."""
    path = _step_dir(dirname, int(step))
    if not os.path.isdir(path):
        return False, ["no such checkpoint step dir: %s" % path]
    return _verify_step_dir(path)


def verify_checkpoint(dirname: str):
    """Offline integrity scan of every step directory under `dirname`
    (or of `dirname` itself for the legacy flat layout): re-checks all
    shard CRCs and metas-completeness WITHOUT loading anything into a
    scope. Returns [{"step", "dir", "ok", "problems"}, ...] oldest
    first — run it in CI or before committing to a long resume:

        python -m paddle_tpu.distributed.checkpoint verify <dir>
    """
    steps = _list_step_dirs(dirname)
    if not steps:
        if _dir_metas(dirname):
            ok, problems = _verify_step_dir(dirname)
            return [{"step": None, "dir": dirname, "ok": ok,
                     "problems": problems}]
        return []
    out = []
    for s, path in sorted(steps):
        ok, problems = _verify_step_dir(path)
        out.append({"step": s, "dir": path, "ok": ok,
                    "problems": problems})
    return out


def _quarantine_step_dir(path: str):
    """Set a failed step dir aside as `<dir>.corrupt` — NEVER deleted
    (it is the forensic evidence of what tore), never seen by resume
    again (the step-dir regex no longer matches it). Returns the new
    path, or None when a racing resume already moved it."""
    target = path + ".corrupt"
    n = 1
    while os.path.exists(target):
        target = path + ".corrupt.%d" % n
        n += 1
    try:
        os.replace(path, target)
    except OSError:
        return None
    return target


def resume_or_init(scope, dirname: str, init_fn=None, strict: bool = True,
                   stateful: dict = None, step: int = None):
    """One-call crash-recovery glue for supervised workers: restore the
    newest VERIFIABLE checkpoint under `dirname` into `scope` and return
    its merged meta, or — when nothing restorable is committed (first
    launch, or a crash before the first save) — run `init_fn()` and
    return None. The caller branches on the return value for its start
    step:

        meta = resume_or_init(scope, ckpt_dir, init_fn=run_startup)
        start = meta["step"] + 1 if meta else 0

    Hardened against torn/corrupted checkpoints (zero manual
    intervention): each candidate step dir is verified (metas complete +
    every shard CRC) BEFORE loading; a failing dir is renamed
    `<dir>.corrupt` (kept, never deleted), the failure logged with the
    exact CRC that mismatched, and the walk continues to the next older
    step. Exception: on a MULTI-process job a metas-incomplete dir is
    skipped without renaming — it may be a peer's save still in flight,
    and destroying it would crash healthy writers. Fallbacks taken are
    recorded in the returned meta under `"fallbacks"`. The verification
    pass reads every array once more than a blind load would — the
    price of never resuming from a dir a later CRC failure would have
    killed anyway.

    `step` pins the restore target (the sentinel's known-good step):
    newer step dirs are ignored outright — they are not corrupt, just
    distrusted — and the walk starts at `step`, still falling back past
    corruption below it.

    `stateful` objects (see save_checkpoint) get `load_state_dict()`
    called with their checkpointed state on the restore path; on the
    init path they are left at their constructed state.
    """
    fallbacks = []
    if dirname:
        for s, path in _list_step_dirs(dirname):  # newest first
            if step is not None and s > int(step):
                continue
            ok, problems = _verify_step_dir(path)
            if not ok:
                incomplete = any(p.startswith("incomplete")
                                 for p in problems)
                if incomplete and jax.process_count() > 1:
                    # multi-process job: an incomplete newest step may
                    # be a PEER's save still in flight — renaming it
                    # would destroy a checkpoint about to commit. Skip
                    # non-destructively (the pre-hardening behavior);
                    # only a single-process resume, where no peer can
                    # be writing, quarantines incomplete dirs.
                    _LOG.warning(
                        "resume: skipping incomplete checkpoint step "
                        "%d at %s (%s) — possibly a peer's in-flight "
                        "save", s, path, "; ".join(problems))
                    fallbacks.append({"step": s, "dir": path,
                                      "renamed_to": None,
                                      "problems": problems})
                    continue
                renamed = _quarantine_step_dir(path)
                _LOG.warning(
                    "resume: checkpoint step %d at %s failed "
                    "verification (%s)%s — falling back to the next "
                    "older step", s, path, "; ".join(problems),
                    (", quarantined as %s" % renamed) if renamed else "")
                fallbacks.append({"step": s, "dir": path,
                                  "renamed_to": renamed,
                                  "problems": problems})
                continue
            meta = load_checkpoint(scope, dirname, strict=strict,
                                   stateful=stateful, step=s)
            if fallbacks:
                meta["fallbacks"] = fallbacks
            return meta
        if _dir_metas(dirname):  # legacy flat layout
            meta = load_checkpoint(scope, dirname, strict=strict,
                                   stateful=stateful)
            if fallbacks:
                meta["fallbacks"] = fallbacks
            return meta
        if fallbacks:
            # nothing restorable: the operator must still learn WHICH
            # checkpoints were quarantined before training restarts
            # from scratch
            _LOG.error(
                "resume: no verifiable checkpoint under %s — %d step "
                "dir(s) failed verification (%s); initializing fresh",
                dirname, len(fallbacks),
                "; ".join(f["problems"][0] for f in fallbacks))
    if init_fn is not None:
        init_fn()
    return None


def _dir_metas(dirname: str):
    metas = []
    for path in sorted(glob.glob(os.path.join(dirname, "checkpoint.meta.p*.json"))):
        m = re.search(r"checkpoint\.meta\.p(\d+)\.json$", path)
        if not m:
            continue
        with open(path) as f:
            metas.append(json.load(f))
    return metas


def _resolve_dir(dirname: str, strict: bool = True, step: int = None):
    """Pick the directory holding the checkpoint to load: the newest
    step_<N>/ subdir whose metas are complete (falling back to older
    complete steps), or `dirname` itself for the legacy flat layout.
    With `step`, exactly that step's dir — incomplete is an error (the
    caller asked for a specific rollback target)."""
    if step is not None:
        path = _step_dir(dirname, step)
        metas = _dir_metas(path)
        if not _metas_complete(metas):
            raise IOError(
                "checkpoint step %d under %s is missing or incomplete"
                % (int(step), dirname))
        return path, metas
    newest_partial = None
    for s, path in _list_step_dirs(dirname):
        metas = _dir_metas(path)
        if _metas_complete(metas):
            return path, metas
        if metas and newest_partial is None:
            newest_partial = (path, metas)
    if newest_partial is not None and not strict:
        return newest_partial
    if newest_partial is not None and strict:
        path, metas = newest_partial
        expected = max(m.get("process_count", 1) for m in metas)
        raise IOError(
            "newest checkpoint step under %s was written by %d processes "
            "but only %d meta file(s) are present (and no older complete "
            "step exists)" % (dirname, expected, len(metas))
        )
    return dirname, _dir_metas(dirname)  # legacy flat layout


def latest_step(dirname: str):
    """Highest COMMITTED step — the one load_checkpoint would restore
    (complete metas only; a partially-written newer step is ignored)."""
    try:
        _, metas = _resolve_dir(dirname, strict=True)
    except IOError:
        return None  # only a partial step exists: nothing committed
    return max((m["step"] for m in metas), default=None)


def _load_entry(dirname: str, name: str, ent: dict, strict: bool):
    if ent.get("sharded"):
        out = np.zeros(ent["global_shape"], ent["dtype"])
        covered = np.zeros(ent["global_shape"], bool)
        for sh in ent["shards"]:
            path = os.path.join(dirname, sh["file"])
            if not os.path.exists(path):
                if strict:
                    raise FileNotFoundError(path)
                return None
            arr = np.load(path)
            if _crc(arr) != sh["crc32"]:
                raise IOError(
                    "checkpoint shard %r failed its CRC check (%s)"
                    % (name, path)
                )
            idx = tuple(slice(a, b) for a, b in sh["index"])
            out[idx] = arr
            covered[idx] = True
        if not covered.all():
            # a writer's meta is missing (non-shared filesystem, lost
            # file): silent zero-filled regions would be the worst kind
            # of corruption
            raise IOError(
                "checkpoint entry %r is only partially covered by the "
                "shards on disk (%d of %d elements); a process's shard "
                "files/meta are missing from %s"
                % (name, int(covered.sum()), covered.size, dirname)
            )
        return out
    path = os.path.join(dirname, ent["file"])
    if not os.path.exists(path):
        if strict:
            raise FileNotFoundError(path)
        return None
    arr = np.load(path)
    if _crc(arr) != ent["crc32"]:
        raise IOError(
            "checkpoint entry %r failed its CRC check (corrupt file %s)"
            % (name, path)
        )
    return arr


def load_checkpoint(scope, dirname: str, strict: bool = True,
                    stateful: dict = None, step: int = None) -> dict:
    """Restore a checkpoint into `scope`, verifying every CRC (reference
    LoadCheckpoint rejects corrupt shards).

    Merges ALL per-process metas of the newest complete step directory
    (falling back to older complete steps when the newest save was
    interrupted; legacy flat-layout directories still load): a sharded
    entry is reassembled from every process's shard files (requires a
    shared or gathered filesystem, as the reference's save_dir does).
    Entries are restored as host numpy values; the executor re-places
    them onto the current mesh/shardings at the next run — so a
    checkpoint written on N processes restores on any process count.
    Returns the merged meta (step = max across processes; entries =
    union). `step` pins the load to one step dir (rollback to
    known-good) instead of the newest complete one."""
    dirname, metas = _resolve_dir(dirname, strict=strict, step=step)
    if not metas:
        raise FileNotFoundError(
            "no checkpoint meta found under %s" % dirname
        )
    # only metas from the LATEST committed step participate: a resume on
    # fewer processes overwrites only its own meta files, and mixing a
    # stale process's older-step meta in would restore stale shard data
    latest = max(m["step"] for m in metas)
    metas = [m for m in metas if m["step"] == latest]
    expected = max(m.get("process_count", 1) for m in metas)
    if strict and len(metas) < expected:
        raise IOError(
            "checkpoint at step %d was written by %d processes but only "
            "%d meta file(s) are present under %s (incomplete copy?)"
            % (latest, expected, len(metas), dirname)
        )
    merged = {
        "step": latest,
        "dir": dirname,
        "extra": {},
        "entries": {},
    }
    partial = {}  # sharded entries may span processes: merge shard lists
    for m in metas:
        merged["extra"].update(m.get("extra") or {})
        for name, ent in m["entries"].items():
            if ent.get("sharded"):
                agg = partial.setdefault(
                    name,
                    {
                        "sharded": True,
                        "global_shape": ent["global_shape"],
                        "dtype": ent["dtype"],
                        "shards": [],
                    },
                )
                if not agg.get("sharded"):
                    raise IOError(
                        "checkpoint entry %r is sharded in one process "
                        "meta and whole in another — corrupt checkpoint "
                        "directory" % name
                    )
                agg["shards"].extend(ent["shards"])
            else:
                prev = partial.get(name)
                if prev is not None and prev.get("sharded"):
                    raise IOError(
                        "checkpoint entry %r is sharded in one process "
                        "meta and whole in another — corrupt checkpoint "
                        "directory" % name
                    )
                partial[name] = ent
    for name, ent in partial.items():
        val = _load_entry(dirname, name, ent, strict)
        if val is not None:
            scope.set(name, val)
            merged["entries"][name] = ent
    if stateful:
        states = merged["extra"].get("stateful") or {}
        for name, obj in stateful.items():
            if name in states:
                obj.load_state_dict(states[name])
            elif strict:
                raise KeyError(
                    "stateful object %r has no state in the checkpoint "
                    "under %s" % (name, dirname))
    return merged


# ---------------------------------------------------------------------
# async save: snapshot now, write in the background. Preemption-aware
# training wants the step loop paused only for the device->host pull,
# not for CRC + disk + rename (the reference's Go pserver likewise
# checkpoints off the serving path, service.go:120).
# ---------------------------------------------------------------------


class _HostScope(object):
    """Scope-shaped view over host numpy snapshots."""

    def __init__(self, arrays):
        self._arrays = arrays

    def keys(self):
        return self._arrays.keys()

    def get(self, name):
        return self._arrays[name]


class _HostShard(object):
    """One addressable shard pulled to host (mirrors jax.Array shard)."""

    __slots__ = ("replica_id", "data", "index")

    def __init__(self, replica_id, data, index):
        self.replica_id = replica_id
        self.data = data
        self.index = index


class _HostShardedArray(object):
    """Host-side snapshot of a sharded jax.Array that PRESERVES the shard
    layout, so the async writer emits the same shard-by-shard files as
    the synchronous saver — a big TP weight is pulled one owned shard at
    a time, never materialised whole on host."""

    is_fully_replicated = False
    is_fully_addressable = True

    def __init__(self, shards, shape, dtype):
        self.addressable_shards = shards
        self.shape = shape
        self.dtype = dtype


class AsyncCheckpoint(object):
    """Handle for an in-flight save: result() joins and re-raises any
    writer error; done() polls. thread=None marks an already-committed
    save (the synchronous fallback)."""

    def __init__(self, thread, box):
        self._thread = thread
        self._box = box

    def done(self) -> bool:
        return self._thread is None or not self._thread.is_alive()

    def result(self, timeout=None):
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError("checkpoint writer still running")
        if self._box.get("error") is not None:
            raise self._box["error"]
        return self._box.get("value")


def save_checkpoint_async(scope, dirname: str, step: int = 0,
                          extra: dict = None,
                          keep_last: int = 1,
                          stateful: dict = None,
                          protect=None) -> AsyncCheckpoint:
    """Snapshot the scope to host memory NOW (so later training steps —
    including donated-buffer updates — cannot touch the saved values),
    then run the normal atomic save on a background thread. Returns an
    AsyncCheckpoint; call result() before relying on the checkpoint.

    `stateful` objects have their state_dict() taken NOW too, so a
    loader that keeps delivering batches while the writer runs cannot
    leak post-snapshot positions into the checkpoint. `protect` (the
    sentinel's known-good step) is honored by the background prune
    exactly as in the synchronous saver.

    Process-spanning (multi-host) arrays need cross-process save
    coordination, so they fall back to a synchronous save_checkpoint —
    the handle is already done when returned.
    """
    import threading

    extra = dict(extra or {})
    if stateful:
        extra["stateful"] = {
            name: obj.state_dict() for name, obj in stateful.items()
        }

    # multi-host fallback decided BEFORE any device->host pulls
    if any(
        isinstance(scope.get(n), jax.Array)
        and not scope.get(n).is_fully_addressable
        for n in scope.keys()
    ):
        save_checkpoint(scope, dirname, step=step, extra=extra,
                        keep_last=keep_last, protect=protect)
        return AsyncCheckpoint(
            None, {"value": _step_dir(dirname, step), "error": None}
        )

    arrays = {}
    for name in sorted(scope.keys()):
        val = scope.get(name)
        if val is None:
            continue
        # device->host pull happens here, synchronously. np.array(copy)
        # so in-place mutation of numpy scope values after the call can
        # never reach the writer. Sharded (TP) values snapshot per owned
        # shard, keeping the sync saver's shard-file layout and the
        # 'no full-array materialisation' property
        if isinstance(val, jax.Array) and not val.is_fully_replicated:
            shards = [
                _HostShard(s.replica_id, np.asarray(s.data), s.index)
                for s in val.addressable_shards
                if s.replica_id == 0
            ]
            arrays[name] = _HostShardedArray(shards, val.shape, val.dtype)
        else:
            arrays[name] = np.array(val, copy=True)

    box = {"value": None, "error": None}

    def _write():
        try:
            save_checkpoint(_HostScope(arrays), dirname, step=step,
                            extra=extra, keep_last=keep_last,
                            protect=protect)
            box["value"] = _step_dir(dirname, step)
        except BaseException as e:  # surfaced by result()
            box["error"] = e

    t = threading.Thread(target=_write, daemon=True)
    t.start()
    return AsyncCheckpoint(t, box)


__all__ += ["save_checkpoint_async", "AsyncCheckpoint"]


# ---------------------------------------------------------------------
# offline integrity scanner CLI:
#   python -m paddle_tpu.distributed.checkpoint verify <dir>
# walks every step dir, re-checks every shard CRC + metas-complete,
# prints per-step verdicts, exits non-zero on any failure — usable in
# CI and before committing a long job to a resume.
# ---------------------------------------------------------------------


def _cli(argv):
    if len(argv) != 2 or argv[0] != "verify":
        sys.stderr.write(
            "usage: python -m paddle_tpu.distributed.checkpoint "
            "verify <checkpoint-dir>\n")
        return 2
    dirname = argv[1]
    if not os.path.isdir(dirname):
        sys.stderr.write("verify: %s is not a directory\n" % dirname)
        return 2
    reports = verify_checkpoint(dirname)
    if not reports:
        sys.stderr.write("verify: no checkpoint steps under %s\n" % dirname)
        return 1
    bad = 0
    for r in reports:
        label = ("step %d" % r["step"]) if r["step"] is not None \
            else "flat layout"
        if r["ok"]:
            print("OK    %-12s %s" % (label, r["dir"]))
        else:
            bad += 1
            print("FAIL  %-12s %s" % (label, r["dir"]))
            for p in r["problems"]:
                print("        %s" % p)
    print("%d step(s) checked, %d failed" % (len(reports), bad))
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(_cli(sys.argv[1:]))
