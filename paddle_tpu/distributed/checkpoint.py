"""Durable training checkpoints: CRC-checked, atomic.

Go pserver parity (go/pserver/service.go:120-226,346): state is written
with CRC32 sidecars and the metadata commit is one atomic rename, so a
half-written checkpoint is never visible and a corrupt shard is rejected
at load. Serves the Fluid save/load_persistables job (fluid/io.py) with
optimizer state included — resume is exact.

Multi-host: each process writes its own data files and its own
`checkpoint.meta.p<idx>.json`, and loads only those back. Arrays must be
fully addressable from their saving process (single-controller or
per-host-replicated state); saving partially-addressable sharded arrays
shard-by-shard is future work.
"""

from __future__ import annotations

import json
import os
import zlib

import jax
import numpy as np

__all__ = ["save_checkpoint", "load_checkpoint"]

def _meta_name() -> str:
    return "checkpoint.meta.p%d.json" % jax.process_index()


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def save_checkpoint(scope, dirname: str, step: int = 0, extra: dict = None):
    """Write every scope entry (params + optimizer state + BN stats) to
    `dirname`. Safe against interruption: data files land first, then the
    meta file commits the checkpoint with one atomic rename."""
    os.makedirs(dirname, exist_ok=True)
    pidx = jax.process_index()
    entries = {}
    for name in sorted(scope.keys()):
        val = scope.get(name)
        if val is None:
            continue
        arr = np.asarray(val)
        fname = "%s.p%d.npy" % (name.replace("/", "__"), pidx)
        tmp = os.path.join(dirname, fname + ".tmp")
        with open(tmp, "wb") as fh:  # np.save(path) would append ".npy"
            np.save(fh, arr)
        os.replace(tmp, os.path.join(dirname, fname))
        entries[name] = {
            "file": fname,
            "crc32": _crc(arr),
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        }
    meta = {
        "step": int(step),
        "process": pidx,
        "entries": entries,
        "extra": extra or {},
    }
    tmp = os.path.join(dirname, _meta_name() + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(dirname, _meta_name()))
    return meta


def load_checkpoint(scope, dirname: str, strict: bool = True) -> dict:
    """Restore a checkpoint into `scope`, verifying every CRC (reference
    LoadCheckpoint rejects corrupt shards). Returns the meta dict."""
    with open(os.path.join(dirname, _meta_name())) as f:
        meta = json.load(f)
    for name, ent in meta["entries"].items():
        path = os.path.join(dirname, ent["file"])
        if not os.path.exists(path):
            if strict:
                raise FileNotFoundError(path)
            continue
        arr = np.load(path)
        if _crc(arr) != ent["crc32"]:
            raise IOError(
                "checkpoint entry %r failed its CRC check (corrupt file %s)"
                % (name, path)
            )
        scope.set(name, arr)
    return meta
