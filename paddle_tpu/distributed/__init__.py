"""Job-level distributed services.

Replaces the reference's Go cloud layer (SURVEY.md §1.2): the master's
fault-tolerant data-task queue (go/master/service.go) becomes
`coordinator.Coordinator`, and the Go pserver's CRC-checksummed atomic
checkpoints (go/pserver/service.go:120-226) become `checkpoint`. Gradient
aggregation itself needs no service at all on TPU — it is a psum over ICI
(see paddle_tpu.parallel); what remains job-level is exactly this: elastic
data dispatch and durable state.
"""

from .coordinator import (Coordinator, CoordinatorServer, MasterClient,
                          RemoteCoordinator, Task)
from .checkpoint import (AsyncCheckpoint, load_checkpoint,
                         save_checkpoint, save_checkpoint_async)
from .fault_injection import (FaultInjected, FaultInjector, corrupt_file,
                              default_injector)

__all__ = [
    "Coordinator",
    "CoordinatorServer",
    "RemoteCoordinator",
    "MasterClient",
    "Task",
    "save_checkpoint",
    "save_checkpoint_async",
    "AsyncCheckpoint",
    "FaultInjected",
    "FaultInjector",
    "default_injector",
    "corrupt_file",
    "load_checkpoint",
]
