"""Job-level distributed services.

Replaces the reference's Go cloud layer (SURVEY.md §1.2): the master's
fault-tolerant data-task queue (go/master/service.go) becomes
`coordinator.Coordinator`, and the Go pserver's CRC-checksummed atomic
checkpoints (go/pserver/service.go:120-226) become `checkpoint`. Gradient
aggregation itself needs no service at all on TPU — it is a psum over ICI
(see paddle_tpu.parallel); what remains job-level is exactly this: elastic
data dispatch, durable state, and the `supervisor` loop that composes the
two with heartbeat liveness into restart-from-checkpoint fault tolerance
(the role etcd TTL keys + the cluster controller play in the reference,
go/pserver/etcd_client.go).
"""

from .coordinator import (Coordinator, CoordinatorServer, MasterClient,
                          RemoteCoordinator, Task)
from .checkpoint import (AsyncCheckpoint, load_checkpoint, resume_or_init,
                         retain, save_checkpoint, save_checkpoint_async)
from .fault_injection import (FaultInjected, FaultInjector, corrupt_file,
                              default_injector, netsplit_active)
from .sentinel import (SENTINEL_EXIT_CODE, DivergenceDetector, SentinelTrip,
                       TrainingSentinel, chunks_consumed, known_good_step,
                       quarantine_chunks, quarantined_chunks)
from .supervisor import Supervisor, WorkerHandle

__all__ = [
    "Coordinator",
    "CoordinatorServer",
    "RemoteCoordinator",
    "MasterClient",
    "Task",
    "save_checkpoint",
    "save_checkpoint_async",
    "AsyncCheckpoint",
    "FaultInjected",
    "FaultInjector",
    "default_injector",
    "corrupt_file",
    "netsplit_active",
    "load_checkpoint",
    "retain",
    "resume_or_init",
    "Supervisor",
    "WorkerHandle",
    "DivergenceDetector",
    "TrainingSentinel",
    "SentinelTrip",
    "SENTINEL_EXIT_CODE",
    "chunks_consumed",
    "known_good_step",
    "quarantine_chunks",
    "quarantined_chunks",
]
