"""Coordinator: fault-tolerant data-task dispatch (Go master parity).

Reference behavior being reproduced (go/master/service.go):
  - SetDataset partitions data into tasks               (service.go:280,106)
  - GetTask leases a task with a timeout                (service.go:368)
  - task timeout -> re-queue                            (service.go:341,313)
  - TaskFailed / failure count > failureMax -> discard  (service.go:455,313)
  - TaskFinished; pass rollover when todo+pending drain (service.go:411)
  - full state snapshot after every mutation, recovered
    on restart                                          (service.go:166,207)

Differences by design: no etcd (snapshots go to a local/NFS path with
atomic rename — the single-controller JAX runtime makes a distributed
lock service unnecessary); tasks name data shards (file paths, record
ranges) rather than RecordIO chunk handles.
"""

from __future__ import annotations

import json
import os
import random
import socket
import socketserver
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import fault_injection as _fi

__all__ = [
    "Task", "Coordinator", "MasterClient", "CoordinatorServer",
    "RemoteCoordinator",
]


@dataclass
class Task:
    task_id: int
    payload: Any  # JSON-serializable shard description
    epoch: int = 0
    failures: int = 0
    # records of this task already DELIVERED (and durably absorbed) by a
    # previous lease holder: a re-leased task resumes here instead of
    # replaying the whole chunk (offset-aware leases, ISSUE 3). Reported
    # via task_progress/task_failed; reset at epoch rollover.
    offset: int = 0
    # lease generation: bumped every time the task is handed out. Holder
    # calls (progress/finished/failed) that present a stale generation
    # are refused — after an expiry + re-lease, the ORIGINAL holder can
    # no longer ack, renew, or fail the new holder's lease (the
    # fencing-token pattern; without it "held" answers by task_id alone
    # and a zombie holder silently keeps a lost lease alive).
    lease: int = 0
    deadline: float = field(default=0.0, compare=False)

    def to_json(self):
        return {
            "task_id": self.task_id,
            "payload": self.payload,
            "epoch": self.epoch,
            "failures": self.failures,
            "offset": self.offset,
            "lease": self.lease,
        }

    @staticmethod
    def from_json(d):
        return Task(
            task_id=d["task_id"], payload=d["payload"], epoch=d["epoch"],
            failures=d["failures"], offset=d.get("offset", 0),
            lease=d.get("lease", 0),
        )


class Coordinator(object):
    """Single-controller task-lease service (thread-safe; serve over any
    RPC you like — in-process for tests, matching SURVEY §4.4's lesson to
    keep distributed paths CI-testable in one process)."""

    def __init__(self, timeout_s: float = 60.0, failure_max: int = 3,
                 snapshot_path: Optional[str] = None,
                 heartbeat_timeout_s: float = 30.0):
        self._lock = threading.Lock()
        self.timeout_s = timeout_s
        self.failure_max = failure_max
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.snapshot_path = snapshot_path
        # queue state below is served to many worker threads at once;
        # every mutation must hold _lock (enforced by
        # paddle_tpu.analysis lock_lint)
        self.todo: List[Task] = []              # guarded-by: _lock
        self.pending: Dict[int, Task] = {}      # guarded-by: _lock
        self.done: List[Task] = []              # guarded-by: _lock
        self.discarded: List[Task] = []         # guarded-by: _lock
        self.epoch = 0                          # guarded-by: _lock
        self._next_id = 0                       # guarded-by: _lock
        # worker liveness registry (reference: trainers announce
        # themselves in etcd and the master watches their keys,
        # go/pserver/etcd_client.go:70-150). Ephemeral BY DESIGN: a
        # restarted coordinator sees workers re-register on their next
        # heartbeat, so membership is not snapshotted.
        self.workers: Dict[str, dict] = {}      # guarded-by: _lock
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()

    # --- dataset ------------------------------------------------------
    def set_dataset(self, shards: List[Any]):
        """Partition `shards` (any JSON-serializable descriptions) into
        tasks (reference SetDataset / partition)."""
        with self._lock:
            if self.todo or self.pending:
                return  # idempotent, like the reference's once.Do
            for payload in shards:
                self.todo.append(Task(task_id=self._next_id, payload=payload))
                self._next_id += 1
            self._snapshot()

    # --- lease protocol ----------------------------------------------
    def get_task(self, epoch_limit: Optional[int] = None) -> Optional[Task]:
        """Lease a task; None when this epoch's work is fully leased/done
        (pass end — the reference signals it with ErrPassAfter). Reclaims
        expired leases first (reference checkTimeoutFunc). Rollover into
        the next pass happens only when `epoch_limit` allows it, so bare
        `while get_task()` drain loops always terminate — and a caller's
        `epoch_limit` also caps what it can POP: a worker still draining
        pass e must not be handed tasks a faster peer already rolled to
        pass e+1 (epoch_limit=None places no cap)."""
        with self._lock:
            reclaimed = self._reclaim_expired()
            if not self.todo:
                if not self.pending and (self.done or self.discarded):
                    if epoch_limit is None or self.epoch + 1 > epoch_limit:
                        if reclaimed:
                            self._snapshot()
                        return None
                    self._next_epoch()
                if not self.todo:
                    if reclaimed:
                        self._snapshot()
                    return None
            if epoch_limit is not None and self.todo[0].epoch > epoch_limit:
                # a peer rolled the queue into a later pass than this
                # caller is on: for THIS caller the current pass is over
                if reclaimed:
                    self._snapshot()
                return None
            task = self.todo.pop(0)
            task.deadline = time.time() + self.timeout_s
            task.lease += 1  # fence out the previous holder, if any
            self.pending[task.task_id] = task
            self._snapshot()
            return task

    def task_finished(self, task_id: int, lease: Optional[int] = None):
        """Mark a lease done. A stale `lease` generation (expired +
        re-leased to someone else) is refused: the new holder still owns
        the task. lease=None skips the fence (single-holder callers)."""
        with self._lock:
            task = self.pending.get(task_id)
            if task is None:
                return
            if lease is not None and task.lease != lease:
                return  # zombie holder: the task moved on without it
            del self.pending[task_id]
            self.done.append(task)
            self._snapshot()

    def task_failed(self, task_id: int, offset: Optional[int] = None,
                    lease: Optional[int] = None):
        """Failure count + requeue or discard (reference
        processFailedTask). `offset` records how many of the task's
        records the failing holder already delivered durably — the next
        lease resumes there instead of replaying them. A stale `lease`
        is a no-op (a zombie holder must not fail — or move the offset
        of — the lease the task was re-issued under)."""
        with self._lock:
            task = self.pending.get(task_id)
            if task is None:
                return
            if lease is not None and task.lease != lease:
                return
            del self.pending[task_id]
            if offset is not None:
                task.offset = max(task.offset, int(offset))
            task.failures += 1
            if task.failures >= self.failure_max:
                self.discarded.append(task)
            else:
                self.todo.append(task)
            self._snapshot()

    def task_progress(self, task_id: int, offset: int,
                      lease: Optional[int] = None) -> dict:
        """Record durable delivery progress on a HELD lease (and renew
        its deadline — progress is also a keepalive). A lease that
        expires later requeues with this offset, so the next holder
        never re-delivers committed records. Returns {"held": False}
        when the lease is no longer pending — or is pending under a
        NEWER lease generation than the caller presents (expired and
        re-leased: the caller is a zombie) — and the caller must stop
        delivering from it; the committed offset travels with the
        requeued task instead."""
        with self._lock:
            task = self.pending.get(task_id)
            if task is None:
                return {"held": False}
            if lease is not None and task.lease != lease:
                return {"held": False}
            changed = int(offset) > task.offset
            task.offset = max(task.offset, int(offset))
            task.deadline = time.time() + self.timeout_s
            if changed:
                # deadline renewal alone is not persisted (deadlines do
                # not survive recovery anyway): pure keepalives must not
                # rewrite a byte-identical snapshot every poll
                self._snapshot()
            return {"held": True, "offset": task.offset}

    # --- worker liveness (elastic supervisor protocol) ---------------
    def _new_worker_record(self, now: float, incarnation: int = 1,
                           meta: Optional[dict] = None) -> dict:
        return {
            "incarnation": incarnation,
            "registered_at": now,
            "last_seen": now,
            "deadline": now + self.heartbeat_timeout_s,
            "step": 0,
            "meta": meta or {},
        }

    def register_worker(self, worker_id: str, meta: Optional[dict] = None):
        """(Re-)announce a worker. Each registration bumps the worker's
        incarnation — a supervisor restart of the same worker id is a NEW
        liveness lease, so a stale pre-crash heartbeat can never vouch
        for the replacement process."""
        with self._lock:
            now = time.time()
            prev = self.workers.get(worker_id)
            self.workers[worker_id] = self._new_worker_record(
                now, incarnation=(prev["incarnation"] + 1) if prev else 1,
                meta=meta,
            )
            return {"incarnation": self.workers[worker_id]["incarnation"]}

    def heartbeat(self, worker_id: str, step: Optional[int] = None):
        """Extend a worker's liveness deadline (auto-registers unknown
        ids so a worker that outlived a coordinator restart keeps its
        membership). Returns the new deadline so clients can observe
        clock skew."""
        with self._lock:
            w = self.workers.get(worker_id)
            if w is None:
                w = self.workers[worker_id] = self._new_worker_record(
                    time.time()
                )
            w["last_seen"] = time.time()
            w["deadline"] = w["last_seen"] + self.heartbeat_timeout_s
            if step is not None:
                w["step"] = int(step)
            return {"deadline": w["deadline"]}

    def membership(self) -> Dict[str, dict]:
        """Snapshot of every known worker with a computed `alive` flag
        (deadline not yet passed). The supervisor polls this to find hung
        workers: a process that is running but past its deadline gets
        killed and restarted."""
        with self._lock:
            now = time.time()
            out = {}
            for wid, w in self.workers.items():
                d = dict(w)
                d["alive"] = w["deadline"] > now
                out[wid] = d
            return out

    # --- internals ----------------------------------------------------
    def _reclaim_expired(self) -> bool:
        now = time.time()
        expired = [t for t in self.pending.values() if t.deadline <= now]
        for t in expired:
            del self.pending[t.task_id]
            t.failures += 1
            if t.failures >= self.failure_max:
                self.discarded.append(t)
            else:
                self.todo.append(t)
        return bool(expired)

    def _next_epoch(self):
        self.epoch += 1
        rollover = self.done + self.discarded
        rollover.sort(key=lambda t: t.task_id)
        for t in rollover:
            t.epoch = self.epoch
            t.failures = 0
            t.offset = 0  # a new pass delivers every record again
        self.todo = rollover
        self.done = []
        self.discarded = []

    # --- durability (reference snapshot/recover) ----------------------
    def _snapshot(self):
        if not self.snapshot_path:
            return
        state = {
            "epoch": self.epoch,
            "next_id": self._next_id,
            "todo": [t.to_json() for t in self.todo],
            "pending": [t.to_json() for t in self.pending.values()],
            "done": [t.to_json() for t in self.done],
            "discarded": [t.to_json() for t in self.discarded],
        }
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self.snapshot_path)  # atomic, like the etcd put

    def _recover(self):
        with open(self.snapshot_path) as f:
            state = json.load(f)
        self.epoch = state["epoch"]
        self._next_id = state["next_id"]
        self.todo = [Task.from_json(d) for d in state["todo"]]
        # pending leases do not survive a restart: their workers are gone,
        # so they go straight back to todo (reference re-queues on recover)
        self.todo += [Task.from_json(d) for d in state["pending"]]
        self.done = [Task.from_json(d) for d in state["done"]]
        self.discarded = [Task.from_json(d) for d in state["discarded"]]


class CoordinatorServer(object):
    """TCP/JSON transport for a Coordinator: task leases survive process
    boundaries, making the coordinator a SERVICE like the reference Go
    master (go/master/service.go:280,368 serves net/rpc; here the frames
    are newline-delimited JSON — no proto toolchain needed at runtime).

    Wire format, one JSON object per line:
      -> {"method": "get_task", "params": {...}}
      <- {"ok": true, "result": ...} | {"ok": false, "error": "..."}
    """

    _METHODS = ("set_dataset", "get_task", "task_finished", "task_failed",
                "task_progress", "ping", "register_worker", "heartbeat",
                "membership")

    def __init__(self, coordinator: Coordinator, host: str = "127.0.0.1",
                 port: int = 0):
        self.coordinator = coordinator
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    try:
                        req = json.loads(line)
                        resp = outer._dispatch(req)
                    except Exception as e:  # malformed frame / internal
                        resp = {"ok": False, "error": str(e)}
                    self.wfile.write(
                        (json.dumps(resp) + "\n").encode()
                    )
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = None

    @property
    def address(self) -> str:
        return "%s:%d" % (self.host, self.port)

    def _dispatch(self, req):
        method = req.get("method")
        params = req.get("params") or {}
        if method not in self._METHODS:
            return {"ok": False, "error": "unknown method %r" % method}
        if method == "ping":
            return {"ok": True, "result": "pong"}
        if method == "set_dataset":
            self.coordinator.set_dataset(params["shards"])
            return {"ok": True, "result": None}
        if method == "get_task":
            task = self.coordinator.get_task(
                epoch_limit=params.get("epoch_limit")
            )
            return {"ok": True,
                    "result": task.to_json() if task else None}
        if method == "task_finished":
            self.coordinator.task_finished(int(params["task_id"]),
                                           lease=params.get("lease"))
            return {"ok": True, "result": None}
        if method == "task_progress":
            return {"ok": True, "result": self.coordinator.task_progress(
                int(params["task_id"]), int(params["offset"]),
                lease=params.get("lease"))}
        if method == "register_worker":
            return {"ok": True, "result": self.coordinator.register_worker(
                str(params["worker_id"]), meta=params.get("meta"))}
        if method == "heartbeat":
            return {"ok": True, "result": self.coordinator.heartbeat(
                str(params["worker_id"]), step=params.get("step"))}
        if method == "membership":
            return {"ok": True, "result": self.coordinator.membership()}
        self.coordinator.task_failed(
            int(params["task_id"]),
            offset=params.get("offset"),
            lease=params.get("lease"),
        )
        return {"ok": True, "result": None}

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def serve_forever(self):
        self._server.serve_forever()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class RemoteCoordinator(object):
    """Client-side proxy with the Coordinator's lease API, usable by
    MasterClient unchanged (reference go/master/client.go over net/rpc).

    Transport failures retry with exponential backoff + full jitter
    under a per-call deadline (the reference trainer's etcd client loops
    the same way while the master key is absent,
    go/pserver/etcd_client.go:70-110) — a coordinator restart, a dropped
    TCP session, or an injected netsplit all heal transparently as long
    as the service returns within `retry_deadline_s`. Lease safety under
    retries comes from the SERVER-side lease timeout, not the transport:
    a get_task whose response was lost leases a task nobody works on,
    and that lease expires and requeues like any other dead worker's.
    """

    def __init__(self, address: str, timeout_s: float = 30.0,
                 retry_deadline_s: Optional[float] = None,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0):
        host, _, port = address.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        self.timeout_s = timeout_s
        self.retry_deadline_s = (
            timeout_s if retry_deadline_s is None else retry_deadline_s
        )
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        # the connection pair is swapped by the retry loop; _lock also
        # serialises whole calls (one request/response in flight).
        # close() is the accepted exception — see baseline.txt.
        self._sock = None   # guarded-by: _lock
        self._file = None   # guarded-by: _lock
        self._lock = threading.Lock()

    def _connect(self, connect_timeout: Optional[float] = None):
        self.close()
        s = socket.create_connection(
            self.addr,
            timeout=min(self.timeout_s, connect_timeout or self.timeout_s),
        )
        s.settimeout(self.timeout_s)
        self._sock = s
        self._file = s.makefile("rwb")

    def _check_netsplit(self):
        # injected partition (PADDLE_FAULT=netsplit@N:dur): drop the live
        # connection and fail the attempt, exactly like losing the wire
        if _fi.netsplit_active():
            self.close()
            raise ConnectionError("netsplit fault active: connection dropped")

    def _call(self, method, **params):
        with self._lock:
            deadline = time.monotonic() + self.retry_deadline_s
            attempt = 0
            while True:
                try:
                    self._check_netsplit()
                    if self._file is None:
                        self._connect(
                            connect_timeout=max(
                                deadline - time.monotonic(), 0.01
                            )
                        )
                    # the write/readline below must also respect the
                    # per-call deadline: a server that accepts but never
                    # replies would otherwise hold the call for the full
                    # transport timeout_s regardless of retry_deadline_s
                    self._sock.settimeout(min(
                        self.timeout_s,
                        max(deadline - time.monotonic(), 0.01),
                    ))
                    self._file.write(
                        (json.dumps({"method": method, "params": params})
                         + "\n").encode()
                    )
                    self._file.flush()
                    line = self._file.readline()
                    if not line:
                        raise ConnectionError("server closed connection")
                    self._check_netsplit()  # split mid-flight: distrust resp
                    resp = json.loads(line)
                    break
                except (OSError, ConnectionError):
                    self.close()
                    attempt += 1
                    delay = min(
                        self.backoff_max_s,
                        self.backoff_base_s * (2 ** (attempt - 1)),
                    )
                    delay *= random.uniform(0.5, 1.5)  # jitter: no thundering herd
                    if time.monotonic() + delay >= deadline:
                        raise
                    time.sleep(delay)
        if not resp.get("ok"):
            raise RuntimeError(
                "coordinator error: %s" % resp.get("error")
            )
        return resp.get("result")

    # Coordinator lease API ------------------------------------------------
    def ping(self):
        return self._call("ping")

    def set_dataset(self, shards):
        return self._call("set_dataset", shards=shards)

    def get_task(self, epoch_limit: Optional[int] = None):
        d = self._call("get_task", epoch_limit=epoch_limit)
        return Task.from_json(d) if d is not None else None

    def task_finished(self, task_id: int, lease: Optional[int] = None):
        return self._call("task_finished", task_id=task_id, lease=lease)

    def task_failed(self, task_id: int, offset: Optional[int] = None,
                    lease: Optional[int] = None):
        return self._call("task_failed", task_id=task_id, offset=offset,
                          lease=lease)

    def task_progress(self, task_id: int, offset: int,
                      lease: Optional[int] = None):
        return self._call("task_progress", task_id=task_id, offset=offset,
                          lease=lease)

    def register_worker(self, worker_id: str, meta: Optional[dict] = None):
        return self._call("register_worker", worker_id=worker_id, meta=meta)

    def heartbeat(self, worker_id: str, step: Optional[int] = None):
        return self._call("heartbeat", worker_id=worker_id, step=step)

    def membership(self):
        return self._call("membership")

    def close(self):
        """Tear down the connection. Deliberately lock-free (baselined
        L001): taking _lock here would block shutdown for up to the
        full retry deadline behind an in-flight _call, and the
        transport already tolerates a torn connection — a raced _call
        attempt fails like a dropped wire and reconnects."""
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class MasterClient(object):
    """Trainer-side iterator over coordinator tasks (reference
    go/master/client.go NextRecord / python master.client:29).

    `record_fn(payload)` maps a task payload to an iterable of records;
    records stream out while the lease is held, and the task is marked
    finished (or failed, on exception) automatically. A failure reports
    the per-task record offset (with the lease's fencing token), so a
    re-leased task skips the records already yielded instead of
    replaying them (offset-aware leases). `epoch_limit` permits epoch
    rollover up to that pass number (None: this pass only)."""

    def __init__(self, coordinator: Coordinator, record_fn,
                 epoch_limit: Optional[int] = None):
        self.coordinator = coordinator
        self.record_fn = record_fn
        self.epoch_limit = epoch_limit

    def __iter__(self):
        # one full pass over the dataset: no rollover into the next epoch
        # (the training loop drives passes; reference client.go pass_end)
        while True:
            task = self.coordinator.get_task(epoch_limit=self.epoch_limit)
            if task is None:
                return
            skip = getattr(task, "offset", 0)
            lease = getattr(task, "lease", None)
            delivered = 0
            try:
                for i, rec in enumerate(self.record_fn(task.payload)):
                    if i < skip:
                        continue  # delivered by a previous lease holder
                    yield rec
                    delivered += 1
            except Exception:
                self.coordinator.task_failed(task.task_id,
                                             offset=skip + delivered,
                                             lease=lease)
                continue
            self.coordinator.task_finished(task.task_id, lease=lease)

    def reader(self):
        """As a v2-style reader creator."""
        return lambda: iter(self)
