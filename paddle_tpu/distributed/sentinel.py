"""Training health sentinel: silent-failure tolerance for training.

The elastic stack (supervisor + checkpoint + loader) tolerates fail-stop
faults: a crashed or hung trainer restarts from its latest checkpoint at
the exact step. But three failures are SILENT — the process keeps
running (or keeps restarting into the same doom) while the run is
already ruined:

  divergence      the loss goes NaN/Inf, or spikes away from its recent
                  trajectory, and every later step trains on garbage —
                  the reference's FLAGS.check_nan_inf stops at "raise
                  and die" (executor.cc:132-140); a restart from the
                  LATEST checkpoint restores the already-poisoned state
  poisoned data   a corrupt/adversarial chunk re-poisons the run on
                  every pass over it: restart alone loops forever
  torn checkpoint a corrupted latest step dir makes even fail-stop
                  recovery raise instead of resuming

This module closes all three with one control loop:

  1. DETECTION (`DivergenceDetector`): a hard trip on any non-finite
     loss/grad-norm (the runtime numerics guard's verdict, upgraded
     from raise-and-die to detect-and-recover) plus a soft trip when
     the loss exceeds `spike_factor` x its EWMA for `hysteresis`
     consecutive steps (one noisy step decays out, PR-8 slow-replica
     style). Suspect losses are NOT folded into the EWMA, so a
     slow-motion blowup cannot drag its own baseline up.
  2. KNOWN-GOOD PROMOTION + ROLLBACK (`TrainingSentinel`): a checkpoint
     becomes *known-good* only after the run survives `promote_after`
     further healthy steps. On a trip, step dirs newer than known-good
     are set aside as `<dir>.diverged` (kept for forensics, invisible
     to resume) and the worker restarts from the known-good step with
     exact step/loader-cursor continuity — not from the latest, whose
     state already absorbed the divergence.
  3. POISONED-DATA QUARANTINE: each trip attributes its divergence
     window to the chunks consumed since the known-good cursor (the
     loader's deterministic (epoch, pos, offset) stream makes the set
     exact). After `rollback_budget` trips inside the same window the
     suspect chunk ids are journaled to the quarantine file, which
     `ShardedDataset`/the chunk sources skip deterministically on every
     later pass; the run abandons only if divergence persists with the
     chunks excluded.

Cross-incarnation memory (trip counts, known-good step, candidates)
lives in `<ckpt_dir>/sentinel.json`, committed atomically — it must
SURVIVE the rollback that restores everything else to the past. The
detector's EWMA state instead rides inside the checkpoint
(`stateful={"detector": sentinel.detector}`) so a rollback also
restores the pre-divergence loss baseline.

The sentinel itself is single-threaded trainer-loop state BY DESIGN
(like the Supervisor): it is called once per step from the training
loop and never from callbacks or timers, so its fields are domain-
annotated rather than locked.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, FrozenSet, List, Optional

from ..utils.detector import TripDetector

__all__ = [
    "DivergenceDetector", "TrainingSentinel", "SentinelTrip",
    "quarantine_chunks", "quarantine_entries", "quarantined_chunks",
    "chunks_consumed", "known_good_step",
]

_LOG = logging.getLogger(__name__)

STATE_FILE = "sentinel.json"

#: exit code a supervised worker uses to signal "orderly sentinel
#: rollback, respawn me" (EX_TEMPFAIL) — the Supervisor budgets these
#: separately from crash loops.
SENTINEL_EXIT_CODE = 75


class SentinelTrip(RuntimeError):
    """Raised by `TrainingSentinel.observe(raise_on_trip=True)`; carries
    the trip decision in `.decision`."""

    def __init__(self, decision: dict):
        super(SentinelTrip, self).__init__(
            "sentinel trip at step %d (%s): %s -> step %s" % (
                decision["step"], decision["verdict"],
                decision["action"], decision["rollback_to"]))
        self.decision = decision


class DivergenceDetector(TripDetector):
    """Per-step loss/grad-norm health verdicts.

    observe(loss, grad_norm) -> "ok" | "nonfinite" | "spike"

      nonfinite  any non-finite loss or grad norm: trips IMMEDIATELY
                 (a NaN is already in the parameters' future)
      spike      loss > spike_factor * EWMA(loss) for `hysteresis`
                 consecutive steps (after `warmup` healthy
                 observations seed the EWMA)

    Suspect steps never update the EWMA; a sub-hysteresis excursion
    resets the streak and decays normally. State is JSON-serializable
    (`state_dict`/`load_state_dict`) so it can ride in the checkpoint
    and roll BACK with the model on a sentinel rollback.

    The verdict machine itself is `utils.detector.TripDetector`
    (ISSUE 15 satellite): ONE hysteresis implementation shared with
    the serving integrity sentinel, so the two health loops cannot
    drift. This subclass only keeps the training-side signature
    (loss + grad_norm).
    """

    def observe(self, loss, grad_norm=None) -> str:
        return TripDetector.observe(self, loss, aux_finite=grad_norm)


# ---------------------------------------------------------------------
# quarantine journal: the durable, deterministic chunk blocklist
# ---------------------------------------------------------------------


def quarantine_entries(path: Optional[str]) -> List[dict]:
    """All journal entries (one JSON object per line), oldest first.
    Malformed lines are skipped — the journal must degrade, never wedge
    a resume."""
    if not path or not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ent = json.loads(line)
            except ValueError:
                continue
            if isinstance(ent, dict) and "chunk" in ent:
                out.append(ent)
    return out


def quarantined_chunks(path: Optional[str]) -> FrozenSet[int]:
    return frozenset(int(e["chunk"]) for e in quarantine_entries(path))


def quarantine_chunks(path: str, chunk_ids, **info) -> List[int]:
    """Journal `chunk_ids` to the quarantine file (idempotent: ids
    already journaled are skipped, so a chunk appears EXACTLY once no
    matter how many rollback rounds re-accuse it). The whole file is
    rewritten through an atomic rename — a crash mid-quarantine leaves
    the previous journal intact. Returns the newly journaled ids,
    sorted (deterministic across reruns of a deterministic job)."""
    have = quarantined_chunks(path)
    fresh = sorted(int(c) for c in set(chunk_ids) if int(c) not in have)
    if not fresh:
        return []
    lines = [json.dumps(e, sort_keys=True) for e in quarantine_entries(path)]
    for c in fresh:
        ent = {"chunk": c}
        ent.update(info)
        lines.append(json.dumps(ent, sort_keys=True))
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(lines) + "\n")
    os.replace(tmp, path)
    return fresh


def chunks_consumed(dataset, cur_from: Optional[dict],
                    cur_to: Optional[dict]) -> List[int]:
    """Chunk ids whose records were delivered between two loader
    cursors — the divergence-attribution window. Exact because the
    delivered stream is a pure function of (seed, epoch): chunks visit
    in `dataset.epoch_order(epoch)` order and a cursor (epoch, pos,
    offset) names the next undelivered record.

    The chunk at `cur_from` is included only while records remain in it
    (a cursor parked exactly on a chunk's end — offset == its record
    count, the shape a batch that completes a chunk leaves behind —
    consumed that chunk BEFORE the window); the chunk at `cur_to` is
    included only once records were actually taken from it
    (offset > 0). Quarantined chunks are excluded — they were never
    delivered."""
    if cur_from is None:
        cur_from = {"epoch": 0, "pos": 0, "offset": 0}
    if cur_to is None:
        return []
    e0, p0, o0 = int(cur_from["epoch"]), int(cur_from["pos"]), int(
        cur_from["offset"])
    e1, p1, o1 = int(cur_to["epoch"]), int(cur_to["pos"]), int(
        cur_to["offset"])
    out = set()
    for epoch in range(e0, e1 + 1):
        order = dataset.epoch_order(epoch)
        if epoch == e0:
            lo = p0
            if (p0 < len(order)
                    and o0 >= dataset.chunks[int(order[p0])].records):
                lo = p0 + 1  # left-edge chunk fully consumed pre-window
        else:
            lo = 0
        if epoch == e1:
            hi = p1 + 1 if o1 > 0 else p1
        else:
            hi = len(order)
        for i in range(lo, min(hi, len(order))):
            ci = int(order[i])
            if not dataset.is_quarantined(ci):
                out.add(ci)
    return sorted(out)


def known_good_step(ckpt_dir: str) -> Optional[int]:
    """The last promoted known-good step recorded in `ckpt_dir`'s
    sentinel state, or None (no sentinel ran / nothing promoted yet).
    The Supervisor's checkpoint GC consults this so `retain()` can
    never collect the one step a rollback needs."""
    state = _load_state(os.path.join(ckpt_dir, STATE_FILE))
    kg = state.get("known_good")
    return int(kg["step"]) if kg else None


def _load_state(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


class TrainingSentinel(object):
    """The training loop's health sentinel: detection + known-good
    promotion + rollback/quarantine decisions.

    Per-step protocol (see tests/sentinel_worker.py, bench.py
    training_sentinel)::

        decision = sentinel.observe(step, loss, cursor=loader.state_dict())
        if decision is not None:
            sys.exit(sentinel.SENTINEL_EXIT_CODE)   # supervisor respawns
        ...apply update, maybe checkpoint...
        if checkpointed:
            sentinel.on_checkpoint(step, cursor=loader.state_dict())

    On resume call `align(step)` with the restored step so candidates
    newer than the restored state are forgotten.

    Arguments:
      ckpt_dir         checkpoint root; `sentinel.json` lives here and
                       trip handling renames this root's diverged steps
      quarantine_path  chunk quarantine journal (None disables data
                       attribution/quarantine: trips only roll back)
      dataset          the ShardedDataset (epoch_order/is_quarantined)
                       used for window attribution; optional
      promote_after    healthy steps a checkpoint must survive before
                       it is promoted to known-good (K)
      rollback_budget  trips inside one divergence window before the
                       window's suspect chunks are quarantined (R)
      quarantine_rounds_max  quarantine rounds before the sentinel
                       abandons (divergence persists with chunks
                       excluded)
      detector         a DivergenceDetector (default-constructed when
                       omitted); checkpoint it via
                       `stateful={"detector": sentinel.detector}` so
                       the loss baseline rolls back with the model
    """

    def __init__(self, ckpt_dir: str, quarantine_path: Optional[str] = None,
                 dataset=None, promote_after: int = 10,
                 rollback_budget: int = 2,
                 quarantine_rounds_max: int = 3,
                 detector: Optional[DivergenceDetector] = None):
        if promote_after < 1:
            raise ValueError("promote_after must be >= 1")
        if rollback_budget < 1:
            raise ValueError("rollback_budget must be >= 1")
        self.ckpt_dir = ckpt_dir
        self.quarantine_path = quarantine_path
        self.dataset = dataset
        self.promote_after = int(promote_after)
        self.rollback_budget = int(rollback_budget)
        self.quarantine_rounds_max = int(quarantine_rounds_max)
        self.detector = detector if detector is not None \
            else DivergenceDetector()
        self._state_path = os.path.join(ckpt_dir, STATE_FILE)
        # cross-incarnation control state; mirrored to sentinel.json on
        # every mutation. Single-threaded trainer-loop state (see
        # module docstring) — domain-annotated, not locked.
        self._state = _load_state(self._state_path)  # guarded-by: trainer
        self._state.setdefault("version", 1)
        self._state.setdefault("known_good", None)
        self._state.setdefault("candidates", [])
        self._state.setdefault("rollbacks", None)
        self._state.setdefault("quarantine_rounds", 0)
        self._state.setdefault("trips", [])
        # cursor of the last genuinely healthy step THIS incarnation
        # (verdict ok, no open spike streak). Trip attribution starts
        # here when available — a hard NaN accuses only the chunks
        # entered since the last healthy step, not everything since
        # known-good; when no healthy step has been seen yet (fresh
        # resume) the known-good cursor is the conservative fallback.
        self._healthy_cursor = None  # guarded-by: trainer

    # --- introspection -------------------------------------------------
    @property
    def known_good_step(self) -> Optional[int]:
        kg = self._state["known_good"]
        return int(kg["step"]) if kg else None

    @property
    def known_good_cursor(self) -> Optional[dict]:
        kg = self._state["known_good"]
        return kg.get("cursor") if kg else None

    @property
    def trips(self) -> List[dict]:
        return list(self._state["trips"])

    def summary(self) -> dict:
        return {
            "known_good_step": self.known_good_step,
            "candidates": [c["step"] for c in self._state["candidates"]],
            "trips": len(self._state["trips"]),
            "quarantine_rounds": self._state["quarantine_rounds"],
        }

    # --- lifecycle -----------------------------------------------------
    def align(self, step: Optional[int]):
        """Call after resume with the restored step: candidates newer
        than the restored state no longer describe durable checkpoints
        on the resumed timeline."""
        if step is None:
            return
        cands = [c for c in self._state["candidates"]
                 if int(c["step"]) <= int(step)]
        if len(cands) != len(self._state["candidates"]):
            self._state["candidates"] = cands
            self._persist()

    def on_checkpoint(self, step: int, cursor: Optional[dict] = None):
        """Register a just-committed checkpoint as a promotion
        candidate. `cursor` is the loader state_dict at the commit —
        it becomes the attribution window's left edge once promoted."""
        self._state["candidates"].append(
            {"step": int(step), "cursor": dict(cursor) if cursor else None})
        self._persist()

    def observe(self, step: int, loss, grad_norm=None,
                cursor: Optional[dict] = None,
                raise_on_trip: bool = False) -> Optional[dict]:
        """Feed one step's health signals. Healthy steps promote ripe
        candidates and return None; a divergence returns the trip
        decision (after persisting it and setting diverged step dirs
        aside) — the caller's only job is to exit with
        SENTINEL_EXIT_CODE (or re-enter its incarnation loop)."""
        verdict = self.detector.observe(loss, grad_norm=grad_norm)
        if verdict == "ok":
            if cursor is not None and not self.detector.suspect:
                self._healthy_cursor = dict(cursor)
            self._promote(int(step))
            return None
        decision = self._trip(int(step), verdict, cursor)
        if raise_on_trip:
            raise SentinelTrip(decision)
        return decision

    # --- internals -----------------------------------------------------
    def _promote(self, step: int):
        ripe = [c for c in self._state["candidates"]
                if int(c["step"]) + self.promote_after <= step]
        if not ripe:
            return
        newest = max(ripe, key=lambda c: int(c["step"]))
        self._state["known_good"] = newest
        self._state["candidates"] = [
            c for c in self._state["candidates"]
            if int(c["step"]) > int(newest["step"])]
        # a freshly promoted checkpoint opens a FRESH divergence window:
        # trip counting restarts relative to the new left edge
        self._state["rollbacks"] = None
        self._persist()

    def _trip(self, step: int, verdict: str, cursor: Optional[dict]) -> dict:
        kg_step = self.known_good_step
        suspects: List[int] = []
        if self.dataset is not None and cursor is not None:
            left = self._healthy_cursor or self.known_good_cursor
            suspects = chunks_consumed(self.dataset, left, cursor)
        rb = self._state["rollbacks"]
        same_window = rb is not None and rb.get("window") == kg_step
        count = (rb["count"] + 1) if same_window else 1
        action = "rollback"
        quarantined: List[int] = []
        if count >= self.rollback_budget:
            if (self.quarantine_path and suspects
                    and self._state["quarantine_rounds"]
                    < self.quarantine_rounds_max):
                quarantined = quarantine_chunks(
                    self.quarantine_path, suspects, step=step,
                    window=[kg_step, step], verdict=verdict,
                    reason="divergence window tripped %d time(s)" % count)
                self._state["quarantine_rounds"] += 1
                if self.dataset is not None:
                    self.dataset.reload_quarantine()
                action = "quarantine"
                count = 0  # fresh budget with the chunks excluded
            else:
                # nothing left to blame: the divergence is not the data
                action = "abandon"
        self._state["rollbacks"] = {"window": kg_step, "count": count}
        decision = {
            "step": step,
            "verdict": verdict,
            "action": action,
            "rollback_to": kg_step,
            "suspects": suspects,
            "quarantined": quarantined,
        }
        self._state["trips"].append(decision)
        if action != "abandon":
            self._set_aside_diverged(kg_step)
        self._persist()
        _LOG.warning(
            "sentinel trip at step %d (%s): %s -> rollback to %s%s",
            step, verdict, action, kg_step,
            (", quarantined chunks %s" % quarantined) if quarantined else "")
        return decision

    def _set_aside_diverged(self, kg_step: Optional[int]):
        """Rename step dirs NEWER than known-good to `<dir>.diverged`:
        their state absorbed the divergence, so the next resume must not
        see them — but they are forensic evidence, never deleted."""
        from . import checkpoint as _ckpt

        for s, path in _ckpt._list_step_dirs(self.ckpt_dir):
            if kg_step is not None and s <= kg_step:
                continue
            target = path + ".diverged"
            n = 1
            while os.path.exists(target):
                target = path + ".diverged.%d" % n
                n += 1
            try:
                os.replace(path, target)
            except OSError:
                pass  # a racing rename already moved it

    def _persist(self):
        os.makedirs(self.ckpt_dir, exist_ok=True)
        tmp = self._state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._state, f, sort_keys=True)
        os.replace(tmp, self._state_path)
