"""Elastic job supervisor: the missing loop that composes the
coordinator's task leases, the heartbeat membership protocol, and the
CRC-checked elastic checkpoints into actual fault tolerance.

Reference parity: the Go cloud layer's elasticity is split between the
master's lease queue (go/master/service.go) and etcd — trainers announce
themselves under a TTL key, the cluster controller watches those keys
and respawns pods whose keys expire (go/pserver/etcd_client.go:70-150).
Here both halves live in one process tree so the whole story is
CI-testable (SURVEY §4.4): the Coordinator doubles as the membership
registry (heartbeat deadlines instead of etcd TTLs) and this Supervisor
is the controller — it spawns N worker processes, watches exits AND
heartbeat deadlines, and restarts casualties from their latest complete
checkpoint.

Failure taxonomy handled:

  crash/preempt   the process exits nonzero or is signalled -> restart;
                  the worker resumes via checkpoint.resume_or_init and
                  any lease it held times out server-side and requeues
  hang/livelock   the process is alive but stops heartbeating
                  (PADDLE_FAULT=hang@N) -> SIGKILL after the heartbeat
                  deadline passes, then restart as above
  crash loop      `restart_max` consecutive RAPID failures (the process
                  died before living `min_uptime_s`) -> abandon the
                  worker; the job degrades gracefully because the
                  coordinator requeues its shards to the survivors
  divergence      a worker whose training sentinel tripped exits with
                  `sentinel_exit_code` (75, EX_TEMPFAIL): an ORDERLY
                  rollback request, not a crash. It is budgeted
                  separately (`sentinel_rollback_max`, its own
                  exponential backoff) and never feeds
                  `rapid_failures` — divergence churn and crash loops
                  must stay distinguishable to operators
  netsplit        not the supervisor's problem: RemoteCoordinator rides
                  out partitions on exponential backoff

Every death is classified with a restart *reason* (`crash` /
`sentinel_rollback` / `hang`), kept in the handle's `restart_reasons`
audit trail, exported in `summary()`, and handed to the replacement
process as PADDLE_RESTART_REASON — workers put it in their
`register_worker(meta=...)` so the coordinator membership shows WHY
each incarnation exists.

The supervisor never parses worker output and the workers never talk to
the supervisor — liveness flows exclusively through the coordinator
membership, so the same supervisor drives local subprocess trees today
and remote launchers later.
"""

from __future__ import annotations

import os
import signal
import subprocess
import time
from typing import Callable, Dict, List, Optional

from . import checkpoint as _ckpt
from . import sentinel as _sentinel

__all__ = ["Supervisor", "WorkerHandle", "restart_backoff_s"]

_FAULT_ENV = "PADDLE_FAULT"


def restart_backoff_s(consecutive_failures: int, base: float = 0.1,
                      cap: float = 5.0) -> float:
    """The supervisor's exponential restart-backoff schedule as ONE
    shared function: `base * 2**(n-1)` seconds after the n-th
    consecutive rapid failure, capped at `cap`. The serving fleet's
    auto-refill and autoscaler spawn gates reuse it so replica
    respawn discipline cannot silently diverge from worker respawn
    discipline (a deterministically-failing replica must not
    crash/refill at monitor frequency forever, exactly like a
    crash-looping worker)."""
    return min(cap, base * (2 ** max(int(consecutive_failures) - 1, 0)))


class _BlindSpawn(object):
    """Sentinel for WorkerHandle.spawn_incarnation: the process was
    spawned while the membership view was blind (partition / bouncing
    coordinator), so NO baseline snapshot could be taken. It is replaced
    by a real snapshot on the first sweep with a visible view — without
    it, `spawn_incarnation=None` would let the dead predecessor's
    expired record (any incarnation != None) condemn the healthy new
    process the moment the partition heals."""

    def __repr__(self):
        return "<blind-spawn>"


_BLIND_SPAWN = _BlindSpawn()


class WorkerHandle(object):
    """Supervisor-side state for one logical worker id across all of its
    incarnations (process restarts)."""

    def __init__(self, worker_id: str):
        self.worker_id = worker_id
        self.proc: Optional[subprocess.Popen] = None
        self.spawned_at = 0.0
        self.restarts = 0          # successful respawns performed
        self.rapid_failures = 0    # consecutive deaths before min_uptime
        self.hang_kills = 0        # times killed for missed heartbeats
        self.sentinel_rollbacks = 0  # orderly divergence-rollback exits
        self.restart_reasons: List[str] = []  # crash|sentinel_rollback|hang
        self.last_restart_reason: Optional[str] = None
        self.exit_codes: List[int] = []
        self.abandoned = False
        self.done = False          # exited 0; will not be respawned
        self.next_spawn_at = 0.0   # restart backoff gate
        self.member_seen = 0.0     # last time membership showed THIS
                                   # incarnation (0 = never)
        self.spawn_incarnation = None  # membership incarnation present
                                       # when this process was spawned
                                       # (None = no record existed)

    @property
    def running(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def summary(self) -> dict:
        return {
            "restarts": self.restarts,
            "rapid_failures": self.rapid_failures,
            "hang_kills": self.hang_kills,
            "sentinel_rollbacks": self.sentinel_rollbacks,
            "restart_reasons": list(self.restart_reasons),
            "exit_codes": list(self.exit_codes),
            "abandoned": self.abandoned,
            "done": self.done,
        }


class Supervisor(object):
    """Spawn and babysit `worker_ids` subprocesses.

    Arguments:
      argv_for(worker_id) -> list[str]    command line for one worker
      worker_ids                          logical ids; stable across restarts
      env_for(worker_id) -> dict | None   base env for FIRST launch
                                          (default: inherited os.environ)
      coordinator                         object with membership() — the
                                          in-process Coordinator or a
                                          RemoteCoordinator; None disables
                                          hang detection (exit codes only)
      heartbeat_timeout_s                 the coordinator's heartbeat
                                          deadline, used ONLY as the
                                          detection-lag estimate when
                                          classifying a hang kill as rapid
                                          (liveness itself comes from the
                                          coordinator's own `alive` flag).
                                          Default: read from the
                                          coordinator when it exposes
                                          `heartbeat_timeout_s`, else 30 s
      restart_max                         consecutive rapid failures before
                                          a worker is abandoned
      min_uptime_s                        a death before this uptime counts
                                          as rapid (crash-loop evidence);
                                          surviving longer resets the count
      restart_backoff_s                   base of the exponential restart
                                          delay (doubles per consecutive
                                          rapid failure, capped at 5 s)
      fault_once                          strip PADDLE_FAULT from restart
                                          envs, so an injected fault fires
                                          in one incarnation only
      ckpt_dir_for(worker_id) -> str      when given, retain() is run on the
                                          worker's checkpoint dir after each
                                          restart (crash-loop disk GC). The
                                          sentinel's last known-good step
                                          (read from the dir's
                                          sentinel.json) is always passed
                                          as `protect` — GC can never eat
                                          a rollback target
      ckpt_keep_last                      complete steps retain() keeps
      sentinel_exit_code                  exit code workers use to request
                                          an orderly divergence rollback
                                          (sentinel.SENTINEL_EXIT_CODE);
                                          such deaths are classified
                                          `sentinel_rollback`, budgeted
                                          and backed off separately, and
                                          never count as rapid failures
      sentinel_rollback_max               total sentinel rollbacks before
                                          the worker is abandoned (the
                                          sentinel itself abandons first
                                          when quarantine cannot cure the
                                          divergence; this is the outer
                                          safety net)
    """

    def __init__(self, argv_for: Callable[[str], List[str]],
                 worker_ids, env_for=None, coordinator=None,
                 heartbeat_timeout_s: Optional[float] = None,
                 restart_max: int = 3, min_uptime_s: float = 2.0,
                 restart_backoff_s: float = 0.1,
                 fault_once: bool = True,
                 ckpt_dir_for: Optional[Callable[[str], str]] = None,
                 ckpt_keep_last: int = 2,
                 spawn_grace_s: float = 120.0,
                 poll_s: float = 0.05,
                 membership_deadline_s: float = 2.0,
                 sentinel_exit_code: int = _sentinel.SENTINEL_EXIT_CODE,
                 sentinel_rollback_max: int = 8):
        self.argv_for = argv_for
        self.worker_ids = [str(w) for w in worker_ids]
        self.env_for = env_for
        self.coordinator = coordinator
        if heartbeat_timeout_s is None:
            heartbeat_timeout_s = getattr(
                coordinator, "heartbeat_timeout_s", None
            ) or 30.0
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.restart_max = restart_max
        self.min_uptime_s = min_uptime_s
        self.restart_backoff_s = restart_backoff_s
        self.fault_once = fault_once
        self.ckpt_dir_for = ckpt_dir_for
        self.ckpt_keep_last = ckpt_keep_last
        self.spawn_grace_s = spawn_grace_s
        self.poll_s = poll_s
        self.membership_deadline_s = membership_deadline_s
        self.sentinel_exit_code = int(sentinel_exit_code)
        self.sentinel_rollback_max = int(sentinel_rollback_max)
        # supervision state is single-threaded BY DESIGN (the whole
        # point of the heartbeat/membership split: workers never talk
        # to the supervisor). A future callback/timer method must
        # declare its `# thread: <domain>` — lock_lint then flags its
        # mutations of `supervisor`-domain state (undeclared methods
        # are assumed to run on the owning domain).
        self.handles: Dict[str, WorkerHandle] = {
            wid: WorkerHandle(wid) for wid in self.worker_ids
        }  # guarded-by: supervisor
        # audit trail for tests/operators
        self.events: List[dict] = []  # guarded-by: supervisor

    # --- internals ----------------------------------------------------
    def _event(self, kind: str, worker_id: str, **info):
        info.update({"kind": kind, "worker": worker_id,
                     "t": time.time()})
        self.events.append(info)

    def _spawn(self, h: WorkerHandle, membership=None):
        env = dict(os.environ if self.env_for is None
                   else (self.env_for(h.worker_id) or os.environ))
        if h.restarts and self.fault_once:
            env.pop(_FAULT_ENV, None)
        env["PADDLE_WORKER_ID"] = h.worker_id
        env["PADDLE_RESTART_COUNT"] = str(h.restarts)
        # why the predecessor died (crash/sentinel_rollback/hang), so
        # the worker can announce it in its register_worker meta and
        # operators can tell divergence churn from crash loops in the
        # coordinator membership
        env["PADDLE_RESTART_REASON"] = h.last_restart_reason or "none"
        # snapshot whatever membership record is ALREADY there (the dead
        # predecessor's, usually): only a record with a different
        # incarnation can vouch for — or condemn — the new process. A
        # BLIND spawn (no view at all) defers the snapshot to the first
        # visible sweep via the sentinel — an empty view is a real
        # "no record" snapshot, a None view is not.
        if membership is None:
            h.spawn_incarnation = _BLIND_SPAWN
        else:
            m = membership.get(h.worker_id)
            h.spawn_incarnation = m["incarnation"] if m else None
        h.proc = subprocess.Popen(self.argv_for(h.worker_id), env=env)
        h.spawned_at = time.time()
        self._event("spawn", h.worker_id, pid=h.proc.pid,
                    restart=h.restarts)

    def _membership(self):
        """Fresh membership view, or None when there is no view at all
        (no coordinator configured, or it is partitioned/bouncing) —
        None disables hang detection for this sweep so that a blind
        supervisor never SIGKILLs a healthy worker. An EMPTY dict is a
        real view (nobody registered yet) and keeps the spawn grace
        armed.

        A RemoteCoordinator's per-call retry deadline is clamped to
        `membership_deadline_s` for this one call: supervision must keep
        sweeping (reaping exits, respawning) during a partition, not sit
        in the client's full 30 s backoff loop once per sweep."""
        if self.coordinator is None:
            return None
        c = self.coordinator
        prev = getattr(c, "retry_deadline_s", None)
        if prev is not None:
            c.retry_deadline_s = min(prev, self.membership_deadline_s)
        try:
            return c.membership()
        except Exception:
            return None
        finally:
            if prev is not None:
                c.retry_deadline_s = prev

    def _handle_death(self, h: WorkerHandle, rc: int, hang: bool = False,
                      detect_lag: float = 0.0):
        """`detect_lag` is how long the failure necessarily sat
        undetected (heartbeat deadline for a hang, spawn grace for a
        startup wedge): it is subtracted from uptime before the rapid
        test, so a worker that wedges INSTANTLY every incarnation still
        counts as crash-looping even though each kill lands minutes
        after the spawn."""
        uptime = time.time() - h.spawned_at
        h.exit_codes.append(rc)
        if rc == 0 and not hang:
            h.done = True
            self._event("done", h.worker_id, uptime=round(uptime, 3))
            return
        sentinel = (not hang) and rc == self.sentinel_exit_code
        if sentinel:
            # an ORDERLY rollback request, not a failure of the process:
            # budgeted on its own counter so divergence churn can never
            # masquerade as (or hide inside) a crash loop
            h.sentinel_rollbacks += 1
            reason = "sentinel_rollback"
            self._event("sentinel_rollback", h.worker_id, rc=rc,
                        uptime=round(uptime, 3),
                        rollbacks=h.sentinel_rollbacks)
        else:
            reason = "hang" if hang else "crash"
            rapid = (uptime - detect_lag) < self.min_uptime_s
            h.rapid_failures = h.rapid_failures + 1 if rapid else 0
            self._event("hang_kill" if hang else "crash", h.worker_id,
                        rc=rc, uptime=round(uptime, 3), rapid=rapid)
        h.last_restart_reason = reason
        h.restart_reasons.append(reason)
        if self.ckpt_dir_for is not None:
            try:
                ckpt_dir = self.ckpt_dir_for(h.worker_id)
                _ckpt.retain(ckpt_dir, keep_last=self.ckpt_keep_last,
                             protect=_sentinel.known_good_step(ckpt_dir))
            except OSError:
                pass  # GC is best-effort; the restart matters more
        if sentinel:
            if h.sentinel_rollbacks >= self.sentinel_rollback_max:
                h.abandoned = True
                h.proc = None
                self._event("abandon", h.worker_id,
                            sentinel_rollbacks=h.sentinel_rollbacks)
                return
            backoff_exp = h.sentinel_rollbacks - 1
        else:
            if h.rapid_failures >= self.restart_max:
                h.abandoned = True
                h.proc = None
                self._event("abandon", h.worker_id,
                            rapid_failures=h.rapid_failures)
                return
            backoff_exp = h.rapid_failures - 1
        h.restarts += 1
        delay = restart_backoff_s(backoff_exp + 1,
                                  base=self.restart_backoff_s)
        h.next_spawn_at = time.time() + delay
        h.proc = None

    def _check_hang(self, h: WorkerHandle, membership):
        m = membership.get(h.worker_id)
        now = time.time()
        if h.spawn_incarnation is _BLIND_SPAWN:
            # first visible sweep after a blind spawn: take the baseline
            # snapshot _spawn could not. Whatever record is here now is
            # treated as predating this process (the dead predecessor's,
            # usually) — only a LATER registration can vouch for or
            # condemn it. Never kill on the sweep the view healed; if
            # the record is actually this process's own registration,
            # hang detection degrades to the spawn-grace path, which is
            # safe (conservative) rather than lethal.
            h.spawn_incarnation = m["incarnation"] if m else None
            return False
        if m is not None and m.get("incarnation") != h.spawn_incarnation:
            # the registry holds a record NEWER than whatever was there
            # when this process spawned, so THIS incarnation registered
            # itself — attribution by incarnation counter, never by
            # comparing the coordinator's clock against ours (clock skew
            # must not let a dead predecessor's record condemn a fresh
            # restart). Trust the coordinator's liveness deadline.
            h.member_seen = now
            if not m["alive"]:
                return True
        elif h.member_seen >= h.spawned_at:
            # this incarnation WAS in membership but vanished: the
            # coordinator restarted and lost its (ephemeral) registry.
            # The worker is not suspect — it re-registers on its next
            # heartbeat; killing it here would punish a healthy worker
            # for a coordinator bounce.
            return False
        elif now - h.spawned_at > self.spawn_grace_s:
            if m is not None and m["alive"]:
                # an actively-refreshed record under OUR worker id can
                # only be this process (the supervisor runs one process
                # per id and reaped the predecessor): an incarnation
                # collision after a coordinator bounce must not read as
                # "never registered". Don't kill — and don't attribute
                # either: if the refreshes stop, the expiry lands here.
                return False
            # never registered (or only the predecessor's stale record
            # remains): wedged during startup (import deadlock, bad
            # address). The grace is generous because interpreter + jit
            # warmup legitimately take many seconds.
            return True
        return False

    # --- lifecycle ----------------------------------------------------
    def start(self):
        """Spawn workers that are not already running. Idempotent, so
        start()+run() (run() calls start() itself) cannot double-spawn a
        worker and orphan the first process."""
        membership = self._membership()
        for wid in self.worker_ids:
            h = self.handles[wid]
            if not (h.running or h.done or h.abandoned):
                self._spawn(h, membership)
        return self

    def poll(self) -> bool:
        """One supervision sweep. Returns True when every worker is
        either done or abandoned (the job cannot change state again)."""
        membership = self._membership()
        for h in self.handles.values():
            if h.done or h.abandoned:
                continue
            if h.proc is None:
                if time.time() >= h.next_spawn_at:
                    self._spawn(h, membership)
                continue
            rc = h.proc.poll()
            if rc is not None:
                self._handle_death(h, rc)
                continue
            if membership is not None and self._check_hang(h, membership):
                # the failure predates its detection by the heartbeat
                # deadline (registered worker gone silent) or the spawn
                # grace (never-registered wedge) — tell _handle_death so
                # deterministic hang/wedge loops still read as rapid
                lag = (self.heartbeat_timeout_s
                       if h.member_seen >= h.spawned_at
                       else self.spawn_grace_s)
                h.hang_kills += 1
                h.proc.send_signal(signal.SIGKILL)
                h.proc.wait()
                self._handle_death(h, -signal.SIGKILL, hang=True,
                                   detect_lag=lag)
        return all(h.done or h.abandoned for h in self.handles.values())

    def run(self, deadline_s: float = 600.0) -> dict:
        """Supervise until the job drains (all workers done/abandoned) or
        the deadline passes; always reaps children. Returns the report:

            {"ok": bool,            # all done, nobody abandoned
             "timed_out": bool,
             "workers": {wid: {restarts, hang_kills, abandoned, ...}},
             "events": [...]}
        """
        deadline = time.monotonic() + deadline_s
        self.start()
        try:
            timed_out = False
            while not self.poll():
                if time.monotonic() > deadline:
                    timed_out = True
                    break
                time.sleep(self.poll_s)
        finally:
            self.stop()
        return {
            "ok": (not timed_out
                   and all(h.done for h in self.handles.values())),
            "timed_out": timed_out,
            "workers": {
                wid: h.summary() for wid, h in self.handles.items()
            },
            "events": list(self.events),
        }

    def stop(self):
        """Kill every still-running worker (shutdown / deadline path)."""
        for h in self.handles.values():
            if h.proc is not None and h.proc.poll() is None:
                h.proc.send_signal(signal.SIGKILL)
                h.proc.wait()
