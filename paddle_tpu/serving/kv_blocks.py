"""Host-side allocator for the paged KV block pool (ISSUE 7).

The serving engine's KV cache is a device-resident pool of fixed-size
token blocks ([num_blocks, block_tokens, H, Dh] per layer); this class
owns the HOST bookkeeping: which physical blocks are free, how many
table rows / prefix-trie nodes reference each block, and how many
blocks are *reserved* for admitted requests but not yet materialised.

Reservation vs allocation is the whole point (the reference's
PoolAllocator.h/MemoryHandle discipline recast, PARITY.md PR 7):

  * admission RESERVES the request's worst case
    (ceil((T0 + max_new) / block_tokens) blocks, minus blocks it
    aliases from the prefix trie), so an admitted request can never
    deadlock mid-decode waiting for a block;
  * blocks are ALLOCATED on demand as the sequence actually grows
    (prefill chunks / decode crossing a block boundary), so
    `blocks_in_use` — the HBM actually resident — tracks tokens
    written, not the worst case;
  * retirement frees the allocated blocks (ref-counted: a block shared
    with the prefix trie or another slot survives) and releases the
    unreached reservation tail, so an early-EOS request returns
    capacity it never touched.

Ref-counts make sharing safe: a prefix-cache hit writes the SAME
physical block id into a second slot's table (zero-copy aliasing) and
increfs it; the trie holds its own ref on published blocks. A block
returns to the free list only when the last reference drops.

Pure host bookkeeping — no jax, unit-testable without a device. All
state is confined to the engine's scheduler thread (same discipline as
the engine side-bands; lock_lint checks the annotations).
"""

from __future__ import annotations

import numpy as np

__all__ = ["KVBlockAllocator"]


class KVBlockAllocator(object):
    """Free-list + ref-count + reservation accounting over `num_blocks`
    physical KV blocks of `block_tokens` tokens each."""

    def __init__(self, num_blocks: int, block_tokens: int,
                 block_bytes=None):
        if int(num_blocks) < 1:
            raise ValueError("num_blocks must be >= 1")
        if int(block_tokens) < 1:
            raise ValueError("block_tokens must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        # one block's HBM cost (payload over all layers + any quant
        # scale side-bands — the engine computes it from the STORAGE
        # dtype, ISSUE 14), so stats() can report bytes honestly for
        # int8/fp8 pools; None = unknown (host-only unit tests)
        self.block_bytes = None if block_bytes is None else int(block_bytes)
        # LIFO free list (ascending ids pop first — deterministic
        # layouts for the fixed-seed drills)
        self._free = list(range(self.num_blocks - 1, -1, -1))  # guarded-by: scheduler
        self._refs = np.zeros(self.num_blocks, np.int32)  # guarded-by: scheduler
        self._reserved = 0                    # guarded-by: scheduler
        # O(1) counters (ServingMetrics discipline)
        self.allocated_total = 0              # guarded-by: scheduler
        self.freed_total = 0                  # guarded-by: scheduler

    # -- capacity -------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def available(self) -> int:
        """Blocks an admission may still reserve: free minus what other
        admitted requests have reserved but not yet allocated."""
        return len(self._free) - self._reserved

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - len(self._free)

    @property
    def reserved(self) -> int:
        return self._reserved

    # -- reservations ---------------------------------------------------
    def reserve(self, n: int) -> bool:
        """Reserve `n` blocks for a request's worst case; False (and no
        state change) when the pool cannot cover it — the caller keeps
        the request queued (backpressure, never a raise)."""
        if n < 0:
            raise ValueError("reserve needs n >= 0")
        if self.available < n:
            return False
        self._reserved += n
        return True

    def release_reservation(self, n: int):
        """Return `n` reserved-but-never-allocated blocks (the
        unreached tail of a retiring request)."""
        if n < 0 or n > self._reserved:
            raise ValueError(
                "release_reservation(%d) with %d outstanding"
                % (n, self._reserved))
        self._reserved -= n

    # -- allocation / ref-counts ---------------------------------------
    def alloc_reserved(self) -> int:
        """Materialise one previously reserved block (refcount 1)."""
        if self._reserved < 1:
            raise RuntimeError("alloc_reserved without a reservation")
        if not self._free:
            # structurally impossible while every allocation is backed
            # by a reservation — kept as a loud invariant check
            raise RuntimeError("block pool free list empty under "
                               "outstanding reservations")
        self._reserved -= 1
        bid = self._free.pop()
        self._refs[bid] = 1
        self.allocated_total += 1
        return bid

    def try_alloc(self):
        """Reserve-and-materialise one block in a single call, or None
        when the pool cannot cover it (backpressure, never a raise).
        The handoff-import and store-warm paths allocate OUTSIDE any
        admission's worst-case reservation, so each block is its own
        reserve+alloc pair."""
        if not self.reserve(1):
            return None
        return self.alloc_reserved()

    def incref(self, bid: int):  # band-verb: alias
        if self._refs[bid] < 1:
            raise ValueError("incref on free block %d" % bid)
        self._refs[bid] += 1

    def decref(self, bid: int) -> bool:  # band-verb: retire
        """Drop one reference; returns True when the block was freed
        back to the pool."""
        if self._refs[bid] < 1:
            raise ValueError("decref on free block %d" % bid)
        self._refs[bid] -= 1
        if self._refs[bid] == 0:
            self._free.append(int(bid))
            self.freed_total += 1
            return True
        return False

    def refcount(self, bid: int) -> int:
        return int(self._refs[bid])

    def stats(self) -> dict:
        out = {
            "num_blocks": self.num_blocks,
            "block_tokens": self.block_tokens,
            "blocks_in_use": self.blocks_in_use,
            "free_blocks": self.free_blocks,
            "reserved": self._reserved,
            "allocated_total": self.allocated_total,
            "freed_total": self.freed_total,
        }
        if self.block_bytes is not None:
            out["block_bytes"] = self.block_bytes
            out["bytes_in_use"] = self.block_bytes * self.blocks_in_use
        return out
