"""Continuous-batching serving engine: slotted KV cache, prefix-cached
chunked prefill, and ONE compiled decode step for many concurrent
requests.

The training path sits at the HBM roof (PERF.md r5); the unclaimed
serving throughput is workload shape — one request per batch underfills
the lanes and every new prompt length recompiles. This engine
reproduces Orca-style iteration-level scheduling (Yu et al., OSDI '22)
and vLLM-style slot management (Kwon et al., SOSP '23) in JAX/XLA
idiom: static shapes everywhere, slots instead of dynamic allocation.
On top of that base (PR 2), admission now reuses and bounds prefill
work (PR 4):

  * Slotted KV cache — one fixed [MAX_SLOTS, max_len] cache per layer
    holds many independent requests; per-slot `pos`/`alive` side-bands
    and the per-row mask in models/transformer._cached_attention make a
    dead or stale slot contribute exactly 0 to live rows.
  * Prefix cache — completed prompt prefixes are published (up to the
    request's publish boundary) into a trie-keyed block pool
    (prefix_cache.py, RadixAttention-style); admission matches the
    longest cached chain and device-copies it into the slot — a
    dynamic_update_slice per block instead of recomputing the header
    every request shares.
  * Chunked prefill — the uncached suffix runs through
    models/transformer.prefill_chunk in chunks of
    `prefill_chunk_tokens`, interleaved with batched decode steps
    (Sarathi-Serve, Agrawal et al., OSDI '24): a long prompt no longer
    stalls every in-flight decode for its whole duration. Chunks pad to
    pow-2 buckets (the same discipline as executor.py _lod_bucket), so
    distinct compiled prefill shapes stay O(log max_len).
  * One jitted decode step — advances all MAX_SLOTS slots at once with
    per-slot positions, temperatures, and sampling keys; cache buffers
    are donated. Traced exactly once per engine lifetime (guarded by
    tests/test_serving_engine.py's compile-count test). The six host
    side-band arrays are device-resident between steps: the decode
    step returns the advanced tok/pos/counts bands, and only bands a
    scheduler event dirtied (_admit activation, retirement) are
    re-uploaded — the steady decode loop does zero h2d band traffic.
  * Iteration-level scheduling — ServingEngine.step() retires a slot
    the moment its request emits EOS or exhausts its budget and refills
    it from the FCFS queue on the SAME step; a new request never waits
    for the whole batch to drain. A pending slot advances at most ONE
    chunk per step (chunks always interleave with decodes — the
    Sarathi policy); `max_prefills_per_step` additionally caps the
    TOTAL chunks across slots per step (None = every pending slot
    advances, 1 = only the FCFS head — the flattest decode latency).

Correctness bar (tested): greedy engine output per request is
bit-identical to sequential models/transformer.generate() at every
slot count and admission order, for every cache path — cold miss,
full hit, partial hit, and post-eviction re-admit. (Identity is at the
TOKEN level: padded/chunked prefill drifts from the unpadded oracle in
the last ~2 float bits — reduction order under masked padding, present
since PR 2 — which never moves an argmax in practice and is pinned by
the fixed-seed drills.) Sampled requests use a per-request
fold_in(key, token_index) schedule — deterministic per request and
independent of slot assignment, but not the same key schedule as
generate(temperature>0).
"""

from __future__ import annotations

import collections
import os
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..distributed import fault_injection as _fi
from ..fluid.core.kernels_sequence import bucket_pow2
from ..models import transformer as tlm
from .metrics import ServingMetrics
from .prefix_cache import PrefixCache

__all__ = ["ServingEngine", "ServingHandle", "EngineFailed"]

_BANDS = ("tok", "pos", "alive", "temps", "counts", "base_keys")


class EngineFailed(RuntimeError):
    """The engine (or the fleet replica driving it) died with requests
    pending. Raised by `ServingHandle.result()` instead of blocking
    forever, and by `ServingEngine.step()` on every call after the
    failure (the compiled steps donate their cache buffers, so a step
    that died mid-dispatch leaves the cache unusable — the latch keeps
    a half-donated cache from being stepped again). `replica` names the
    failing replica when the engine serves inside a fleet."""

    def __init__(self, msg: str, replica=None):
        super().__init__(msg)
        self.replica = replica


class ServingHandle(object):
    """Per-request future: filled in by the engine as steps run.
    `result()` drives the owning engine until this request completes
    (single-threaded engines have no background loop to wait on)."""

    def __init__(self, engine, rid, prompt, max_new_tokens, temperature,
                 eos_id, seed, publish_len):
        self._engine = engine
        self.rid = rid
        self.prompt = prompt  # np.int32 [T0]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.seed = seed
        # publish boundary: how many leading prompt tokens may be
        # published back to the prefix pool (None = whole prompt)
        self.publish_len = publish_len
        self.tokens: List[int] = []  # generated tokens (may include eos)
        self.done = False
        self.finish_reason: Optional[str] = None  # 'eos' | 'budget'
        # set by ServingEngine.abort() when the engine dies with this
        # request pending: result() raises it instead of spinning on a
        # dead engine forever (ISSUE 6 satellite)
        self.error: Optional[BaseException] = None
        self.submit_t = time.monotonic()
        self.queue_wait_s: Optional[float] = None
        self.ttft_s: Optional[float] = None

    def result(self) -> np.ndarray:
        """Block (by stepping the engine) until done; returns the full
        sequence [T0 + n_generated] — prompt then generated tokens.
        Raises `EngineFailed` (naming the failing replica when the
        engine serves in a fleet) if the engine died with this request
        pending — including when a BACKGROUND thread owned the engine
        and crashed: the failure is propagated into the handle, never
        an indefinite block."""
        while not self.done:
            if self.error is not None:
                raise self.error
            if not self._engine.step():
                raise RuntimeError(
                    "engine made no progress but request %r is not done"
                    % self.rid
                )
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)]
        )


class ServingEngine(object):
    """Continuous-batching engine over a transformer LM's decode
    primitives. Knobs: `max_slots` (concurrent requests in the batched
    decode), `max_len` (per-slot KV capacity, bounded by the positional
    table), `min_bucket` (smallest prefill pad length),
    `max_prefills_per_step` (total prefill chunks per step across
    slots; each pending slot advances at most one chunk per step
    regardless, so None = all pending slots advance, 1 = only the FCFS
    head — latency-biased for in-flight decodes),
    `prefill_chunk_tokens` (max tokens per prefill chunk;
    None = whole suffix in one chunk), `prefix_cache_tokens` (token
    budget of the shared prefix KV pool; None/0 disables reuse), and
    `prefix_block_tokens` (pool block granularity — prefixes cache and
    match in whole blocks)."""

    def __init__(self, params, cfg, max_slots=8, max_len=None,
                 min_bucket=8, max_prefills_per_step=None, donate=True,
                 prefill_chunk_tokens=None, prefix_cache_tokens=None,
                 prefix_block_tokens=16, replica_id=None,
                 fault_injector=None):
        self._params = params
        self._cfg = cfg
        if getattr(cfg, "moe_experts", 0):
            # reference_moe's capacity cutoff couples rows: padded
            # chunk rows would compete with real rows for expert slots
            # and silently change real outputs (prefill_chunk
            # docstring) — refuse loudly instead
            raise ValueError(
                "ServingEngine serves dense models only; MoE configs "
                "(moe_experts > 0) are not bit-stable under "
                "padded/chunked prefill")
        S = int(max_slots)
        if S < 1:
            raise ValueError("max_slots must be >= 1")
        self.max_slots = S
        # the positional table bounds every position (same clamp as
        # generate: a gather past it would silently clamp, not error)
        L = int(max_len or cfg.max_len)
        L = min(L, int(params["pos"].shape[0]))
        self.max_len = L
        self.min_bucket = int(min_bucket)
        if max_prefills_per_step is not None and max_prefills_per_step < 1:
            raise ValueError("max_prefills_per_step must be >= 1 or None")
        self.max_prefills_per_step = max_prefills_per_step
        if prefill_chunk_tokens is not None and prefill_chunk_tokens < 1:
            raise ValueError("prefill_chunk_tokens must be >= 1 or None")
        self.prefill_chunk_tokens = prefill_chunk_tokens
        self.metrics = ServingMetrics(S)
        self.prefix_cache: Optional[PrefixCache] = None
        if prefix_cache_tokens:
            self.prefix_cache = PrefixCache(
                int(prefix_cache_tokens),
                block_tokens=int(prefix_block_tokens),
            )
            self.metrics.prefix_cache = self.prefix_cache

        self._cache = tlm.init_kv_cache(cfg, S, max_len=L)
        # host-side truth of the per-slot side-bands; device copies are
        # kept across steps and re-uploaded only when dirtied. All
        # scheduler state below is confined to the thread driving
        # step()/submit() (the engine has no background loop). A future
        # background method must declare its `# thread: <domain>` —
        # lock_lint then flags its mutations of scheduler state
        # (undeclared methods are assumed to run on the owning domain).
        self._tok = np.zeros(S, np.int32)     # guarded-by: scheduler
        self._pos = np.zeros(S, np.int32)     # guarded-by: scheduler
        self._alive = np.zeros(S, bool)       # guarded-by: scheduler
        self._temps = np.zeros(S, np.float32)  # guarded-by: scheduler
        self._counts = np.zeros(S, np.int32)  # guarded-by: scheduler
        self._base_keys = np.zeros((S, 2), np.uint32)  # guarded-by: scheduler
        self._dev: Dict[str, Any] = {}        # guarded-by: scheduler
        self._dirty = set(_BANDS)             # guarded-by: scheduler
        self._slot_req: List[Optional[ServingHandle]] = [None] * S  # guarded-by: scheduler
        # per-slot chunked-prefill cursors + FCFS order of pending slots
        self._prefill_state: Dict[int, dict] = {}  # guarded-by: scheduler
        self._prefill_q: collections.deque = collections.deque()  # guarded-by: scheduler

        self._queue: collections.deque = collections.deque()  # guarded-by: scheduler
        self._next_rid = 0                    # guarded-by: scheduler
        self._donate = bool(donate)
        self._chunk_fns: Dict[int, Any] = {}
        self._decode_fn = self._make_decode()
        self._copy_fn = None
        self._extract_fn = None
        # failure latch (abort() docstring) + fleet attribution
        self.replica_id = replica_id
        self._failed: Optional[EngineFailed] = None  # guarded-by: scheduler
        # fault-injection tick source for step(): an explicit injector
        # (fleet drills give each replica its own), or — resolved
        # lazily on the first step — the process-wide default_injector
        # when PADDLE_FAULT is set, else an inert one (same contract as
        # the trainer CLI's per-batch tick; see fault_injection.py)
        self._injector = fault_injector       # guarded-by: scheduler

    # ------------------------------------------------------------------
    # compiled steps
    # ------------------------------------------------------------------
    def _make_decode(self):
        cfg, metrics, L = self._cfg, self.metrics, self.max_len

        def _decode(params, cache, tok, pos, alive, temps, counts,
                    base_keys):
            metrics.count_trace("decode_step")  # trace-time side effect
            # dead slots park their write out of range: scatter DROPS
            # out-of-bounds rows, so a retired slot can never dirty the
            # cache a future prefill will claim
            write_pos = jnp.where(alive, pos, jnp.int32(L))
            logits, cache = tlm.decode_step(
                params, tok, write_pos, cache, cfg
            )
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            keys = jax.vmap(jax.random.fold_in)(base_keys, counts)
            safe_t = jnp.where(temps > 0, temps, 1.0)
            sampled = jax.vmap(
                lambda k, l, t: jax.random.categorical(
                    k, l.astype(jnp.float32) / t
                )
            )(keys, logits, safe_t).astype(jnp.int32)
            nxt = jnp.where(temps > 0, sampled, greedy)
            # advance the device-resident bands in-step: the steady
            # decode loop re-uploads nothing (satellite: h2d dispatch
            # off the hot path). Dead rows advance by 0, matching the
            # untouched host mirrors.
            live = alive.astype(jnp.int32)
            return cache, nxt, pos + live, counts + live

        kw = {"donate_argnums": (1,)} if self._donate else {}
        return jax.jit(_decode, **kw)

    def _chunk_fn(self, Cb):
        """One compiled prefill-chunk step per pow-2 bucket: extends a
        slot's cached prefix by a [Cb]-padded chunk and returns the
        would-be first generated token (meaningful only when the chunk
        completes the prompt)."""
        fn = self._chunk_fns.get(Cb)
        if fn is not None:
            return fn
        cfg, metrics = self._cfg, self.metrics

        def _chunk(params, cache, padded, start, slot, true_len, temp,
                   key):
            metrics.count_trace("prefill_T%d" % Cb)
            logits, cache = tlm.prefill_chunk(
                params, cache, padded, start, slot, cfg,
                true_len=true_len,
            )
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            sampled = jax.random.categorical(
                key,
                logits.astype(jnp.float32)
                / jnp.where(temp > 0, temp, 1.0),
            ).astype(jnp.int32)
            first = jnp.where(temp > 0, sampled, greedy)
            return cache, first

        kw = {"donate_argnums": (1,)} if self._donate else {}
        fn = jax.jit(_chunk, **kw)
        self._chunk_fns[Cb] = fn
        return fn

    def _make_copy_fn(self):
        """Device-side prefix reuse: one dynamic_update_slice per layer
        writes a cached [B, H, Dh] block into the slot at its depth.
        ONE compiled shape total (fixed block size) — reuse adds no
        pressure on the pow-2 prefill bucket budget."""
        metrics = self.metrics

        def _copy(cache, kk, vv, slot, pos):
            metrics.count_trace("prefix_copy")
            new = []
            for i, kv in enumerate(cache):
                ck = jax.lax.dynamic_update_slice(
                    kv["k"], kk[i][None].astype(kv["k"].dtype),
                    (slot, pos, 0, 0))
                cv = jax.lax.dynamic_update_slice(
                    kv["v"], vv[i][None].astype(kv["v"].dtype),
                    (slot, pos, 0, 0))
                new.append({"k": ck, "v": cv})
            return new

        kw = {"donate_argnums": (0,)} if self._donate else {}
        return jax.jit(_copy, **kw)

    def _make_extract_fn(self):
        """Publish path: slice one block's per-layer K/V out of a slot
        into stacked [layers, B, H, Dh] pool payloads. Not donated —
        the engine keeps using the cache it reads from."""
        metrics = self.metrics
        B = self.prefix_cache.block_tokens
        H = self._cfg.heads
        dh = self._cfg.dim // self._cfg.heads

        def _extract(cache, slot, pos):
            metrics.count_trace("prefix_extract")
            kk = jnp.stack([
                jax.lax.dynamic_slice(
                    kv["k"], (slot, pos, 0, 0), (1, B, H, dh))[0]
                for kv in cache])
            vv = jnp.stack([
                jax.lax.dynamic_slice(
                    kv["v"], (slot, pos, 0, 0), (1, B, H, dh))[0]
                for kv in cache])
            return kk, vv

        return jax.jit(_extract)

    # ------------------------------------------------------------------
    # device-resident side-bands
    # ------------------------------------------------------------------
    def _band(self, name):
        if name in self._dirty:
            self._dev[name] = jnp.asarray(getattr(self, "_" + name))
            self._dirty.discard(name)
            self.metrics.band_uploads += 1
        return self._dev[name]

    def _mark_dirty(self, *names):
        self._dirty.update(names or _BANDS)

    # ------------------------------------------------------------------
    # scheduler
    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens, temperature=0.0, eos_id=None,
               seed=0, publish_len=None) -> ServingHandle:
        """Enqueue one request (FCFS). Returns a handle whose `.tokens`
        fills in as the engine steps; `handle.result()` drives the
        engine to completion of this request. `publish_len` is the
        publish-boundary tag: at most this many leading prompt tokens
        are published to the prefix pool once prefill completes (None =
        the whole prompt; pass the shared-header length to keep
        request-unique tails out of the pool)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        T0 = prompt.shape[0]
        if T0 < 1:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if T0 + int(max_new_tokens) > self.max_len:
            raise ValueError(
                "request needs T0+max_new <= max_len (%d + %d > %d)"
                % (T0, int(max_new_tokens), self.max_len)
            )
        if publish_len is not None and publish_len < 0:
            raise ValueError("publish_len must be >= 0 or None")
        h = ServingHandle(self, self._next_rid, prompt, max_new_tokens,
                          temperature, eos_id, seed, publish_len)
        self._next_rid += 1
        self._queue.append(h)
        return h

    def _free_slot(self) -> Optional[int]:
        for s in range(self.max_slots):
            if self._slot_req[s] is None:
                return s
        return None

    def _bucket(self, T0: int) -> int:
        return min(bucket_pow2(T0, floor=self.min_bucket), self.max_len)

    def _retire(self, s: int, reason: str):
        h = self._slot_req[s]
        h.done = True
        h.finish_reason = reason
        self._slot_req[s] = None
        self._alive[s] = False
        self._mark_dirty("alive")

    def _emit(self, s: int, token: int) -> bool:
        """Append one generated token to slot s's request; retire on EOS
        or budget (EOS on the budget-exhausting step reports 'eos').
        Returns True if the slot was retired."""
        h = self._slot_req[s]
        h.tokens.append(int(token))
        self._counts[s] += 1
        self.metrics.tokens_out += 1
        if h.eos_id is not None and int(token) == int(h.eos_id):
            self._retire(s, "eos")
            return True
        if len(h.tokens) >= h.max_new_tokens:
            self._retire(s, "budget")
            return True
        return False

    def _admit(self, h: ServingHandle, s: int):
        """Assign a free slot: match the longest cached prefix,
        device-copy it into the slot (zero recompute), and queue the
        uncached suffix for chunked prefill. No model compute happens
        here — chunks run in step()'s prefill phase."""
        h.queue_wait_s = time.monotonic() - h.submit_t
        self.metrics.queue_wait_s.append(h.queue_wait_s)
        T0 = h.prompt.shape[0]
        matched = 0
        if self.prefix_cache is not None:
            # cap at T0-1: the last prompt token must be COMPUTED — its
            # logits seed the first generated token
            with self.prefix_cache.match(h.prompt[:T0 - 1]) as m:
                if m.length:
                    if self._copy_fn is None:
                        self._copy_fn = self._make_copy_fn()
                    B = self.prefix_cache.block_tokens
                    for d, (kk, vv) in enumerate(m.payloads):
                        self._cache = self._copy_fn(
                            self._cache, kk, vv, jnp.int32(s),
                            jnp.int32(d * B))
                matched = m.length
            # the match is ref-held until here: eviction during a
            # concurrent publish cannot free a block mid-copy
            self.metrics.prefix_hit_tokens.append(matched)
        self._slot_req[s] = h
        # the first-token sampling key is per-request, not per-chunk:
        # computed once here, consumed on the prompt's final chunk
        self._prefill_state[s] = {
            "handle": h, "cursor": matched,
            "key": jax.random.fold_in(jax.random.PRNGKey(h.seed), 0),
        }
        self._prefill_q.append(s)

    def _publish(self, s: int, h: ServingHandle):
        """Publish the finished prompt's prefix blocks (up to the
        request's publish boundary) back to the pool. Extraction runs
        only for blocks the trie does not already hold."""
        pc = self.prefix_cache
        if pc is None:
            return
        T0 = h.prompt.shape[0]
        bound = T0 if h.publish_len is None else min(h.publish_len, T0)
        n_blocks = bound // pc.block_tokens
        if n_blocks < 1:
            return
        if self._extract_fn is None:
            self._extract_fn = self._make_extract_fn()
        pc.publish(
            h.prompt, n_blocks,
            lambda d: self._extract_fn(
                self._cache, jnp.int32(s),
                jnp.int32(d * pc.block_tokens)),
        )

    def _run_chunk(self, s: int) -> bool:
        """Advance slot s's prefill by one chunk; on the final chunk,
        publish the prefix, activate the slot, and emit the first
        token. Returns True when the prefill completed."""
        st = self._prefill_state[s]
        h = st["handle"]
        T0 = h.prompt.shape[0]
        cursor = st["cursor"]
        c = T0 - cursor
        if self.prefill_chunk_tokens is not None:
            c = min(c, self.prefill_chunk_tokens)
        Cb = self._bucket(c)
        padded = np.zeros(Cb, np.int32)
        padded[:c] = h.prompt[cursor:cursor + c]
        fn = self._chunk_fn(Cb)
        t0 = time.monotonic()
        self._cache, first = fn(
            self._params, self._cache, jnp.asarray(padded),
            jnp.int32(cursor), jnp.int32(s), jnp.int32(c),
            jnp.float32(h.temperature), st["key"],
        )
        st["cursor"] = cursor + c
        self.metrics.prefill_chunks += 1
        self.metrics.prefill_tokens_computed += c
        if st["cursor"] < T0:
            # mid-prompt chunk: dispatch only, nothing to read back —
            # the batched decode below overlaps with it
            self.metrics.span("prefill_T%d" % Cb, time.monotonic() - t0)
            return False
        first = int(np.asarray(first))  # blocks: first token is real
        now = time.monotonic()
        h.ttft_s = now - h.submit_t
        self.metrics.ttft_s.append(h.ttft_s)
        self.metrics.span("prefill_T%d" % Cb, now - t0)
        self.metrics.prefills += 1
        self._publish(s, h)
        del self._prefill_state[s]

        self._tok[s] = first
        self._pos[s] = T0
        self._alive[s] = True
        self._temps[s] = h.temperature
        self._counts[s] = 0
        self._base_keys[s] = np.asarray(jax.random.PRNGKey(h.seed))
        self._mark_dirty()  # all bands: slot s changed everywhere
        self._emit(s, first)  # may retire immediately (max_new==1 / eos)
        return True

    def abort(self, exc: BaseException):
        """Latch the engine as failed and propagate `exc` into every
        pending handle (queued, prefilling, or decoding): their
        `result()` raises instead of blocking forever. Called
        internally when a step dies, and externally by whatever thread
        drives the engine (a fleet replica loop) when IT dies between
        steps. Idempotent; the first failure wins."""
        if self._failed is None:
            if isinstance(exc, EngineFailed):
                self._failed = exc
            else:
                self._failed = EngineFailed(
                    "engine%s failed: %r" % (
                        "" if self.replica_id is None
                        else " (replica %s)" % self.replica_id,
                        exc),
                    replica=self.replica_id)
                self._failed.__cause__ = exc
        for h in list(self._queue) + list(self._slot_req):
            if h is not None and not h.done and h.error is None:
                h.error = self._failed

    def step(self) -> bool:
        """One scheduler iteration: admit queued requests into free
        slots (prefix match + device copy), advance pending prefills by
        up to `max_prefills_per_step` chunks (FCFS), then ONE batched
        decode advancing every live slot; retirements free slots for
        the next step's admissions. Returns False when there was
        nothing to do (queue empty, no pending prefill, no live
        slots).

        Each call ticks the fault injector (PADDLE_FAULT, or the
        engine's own `fault_injector`) BEFORE doing work, so
        `kill@N`/`exc@N`/`delay@N:dur` specs land mid-decode — the
        fleet kill drills' step boundary. Any failure (injected or
        real) aborts every pending handle and latches the engine: the
        compiled steps donate their cache buffers, so a step that died
        mid-dispatch must never run again on the half-donated cache."""
        if self._failed is not None:
            raise self._failed
        inj = self._injector
        if inj is None:
            inj = self._injector = (
                _fi.default_injector()
                if os.environ.get(_fi.ENV_VAR) else _fi.FaultInjector("")
            )
        try:
            if inj.active:
                inj.tick()
            return self._step_inner()
        except Exception as exc:
            self.abort(exc)
            raise

    def _step_inner(self) -> bool:
        progressed = False
        while self._queue:
            s = self._free_slot()
            if s is None:
                break
            self._admit(self._queue.popleft(), s)
            progressed = True

        cap = self.max_prefills_per_step
        chunks = 0
        for s in list(self._prefill_q):
            if cap is not None and chunks >= cap:
                break
            if self._run_chunk(s):
                self._prefill_q.remove(s)
            chunks += 1
            progressed = True

        if not self._alive.any():
            return progressed

        t0 = time.monotonic()
        self._cache, nxt_d, pos_d, counts_d = self._decode_fn(
            self._params, self._cache,
            self._band("tok"), self._band("pos"), self._band("alive"),
            self._band("temps"), self._band("counts"),
            self._band("base_keys"),
        )
        nxt = np.asarray(nxt_d)  # blocks; tokens are real
        # the decode step advanced tok/pos/counts on device; adopt its
        # outputs so an admission-free step re-uploads nothing. (Dead
        # rows: device tok holds this step's don't-care sample, host
        # keeps the stale final token — both are masked and parked, and
        # an admission re-dirties every band anyway.)
        self._dev["tok"], self._dev["pos"], self._dev["counts"] = (
            nxt_d, pos_d, counts_d)
        self._dirty.difference_update(("tok", "pos", "counts"))
        self.metrics.span("decode_step", time.monotonic() - t0)
        self.metrics.decode_steps += 1
        self.metrics.occupancy.append(
            float(self._alive.sum()) / self.max_slots
        )

        live = np.nonzero(self._alive)[0]
        self._pos[live] += 1  # the token just cached sat at pos
        for s in live:
            self._tok[s] = nxt[s]
            self._emit(s, nxt[s])
        return True

    def run(self) -> Dict[int, np.ndarray]:
        """Drive the engine until the queue drains and every slot
        retires; returns {request_id: full sequence} for every request
        completed during this call."""
        finished: Dict[int, np.ndarray] = {}
        # a retired handle never lingers in _slot_req, so everything
        # in-flight or queued right now is exactly this call's work
        pending = list(self._queue) + [
            h for h in self._slot_req if h is not None
        ]
        while self.step():
            pass
        for h in pending:
            if h.done:
                finished[h.rid] = np.concatenate(
                    [h.prompt, np.asarray(h.tokens, np.int32)]
                )
        return finished

    # ------------------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def live_slots(self) -> int:
        return int(self._alive.sum())

    @property
    def prefilling_slots(self) -> int:
        return len(self._prefill_q)
