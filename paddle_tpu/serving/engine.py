"""Continuous-batching serving engine: slotted KV cache, bucketed
prefill, and ONE compiled decode step for many concurrent requests.

The training path sits at the HBM roof (PERF.md r5); the unclaimed
serving throughput is workload shape — one request per batch underfills
the lanes and every new prompt length recompiles. This engine
reproduces Orca-style iteration-level scheduling (Yu et al., OSDI '22)
and vLLM-style slot management (Kwon et al., SOSP '23) in JAX/XLA
idiom: static shapes everywhere, slots instead of dynamic allocation.

  * Slotted KV cache — one fixed [MAX_SLOTS, max_len] cache per layer
    holds many independent requests; per-slot `pos`/`alive` side-bands
    and the per-row mask in models/transformer._cached_attention make a
    dead or stale slot contribute exactly 0 to live rows.
  * Bucketed prefill — prompts pad to pow-2 length buckets (the same
    discipline as executor.py _lod_bucket) and write into a free slot
    via dynamic_update_slice, so distinct compiled prefill shapes are
    O(log max_len), not O(#prompts). Causality + the exp(-inf)==0 mask
    make the padded prefill BIT-IDENTICAL to an unpadded one at the
    true last prompt position.
  * One jitted decode step — advances all MAX_SLOTS slots at once with
    per-slot positions, temperatures, and sampling keys; cache buffers
    are donated. Traced exactly once per engine lifetime (guarded by
    tests/test_serving_engine.py's compile-count test).
  * Iteration-level scheduling — ServingEngine.step() retires a slot
    the moment its request emits EOS or exhausts its budget and refills
    it from the FCFS queue on the SAME step; a new request never waits
    for the whole batch to drain. `max_prefills_per_step` bounds how
    much prefill work may delay in-flight decodes (the prefill-vs-
    decode interleave policy).

Correctness bar (tested): greedy engine output per request is
bit-identical to sequential models/transformer.generate() at every
slot count and admission order. Sampled requests use a per-request
fold_in(key, token_index) schedule — deterministic per request and
independent of slot assignment, but not the same key schedule as
generate(temperature>0).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..fluid.core.kernels_sequence import bucket_pow2
from ..models import transformer as tlm
from .metrics import ServingMetrics

__all__ = ["ServingEngine", "ServingHandle"]


class ServingHandle(object):
    """Per-request future: filled in by the engine as steps run.
    `result()` drives the owning engine until this request completes
    (single-threaded engines have no background loop to wait on)."""

    def __init__(self, engine, rid, prompt, max_new_tokens, temperature,
                 eos_id, seed):
        self._engine = engine
        self.rid = rid
        self.prompt = prompt  # np.int32 [T0]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.seed = seed
        self.tokens: List[int] = []  # generated tokens (may include eos)
        self.done = False
        self.finish_reason: Optional[str] = None  # 'eos' | 'budget'
        self.submit_t = time.monotonic()
        self.queue_wait_s: Optional[float] = None
        self.ttft_s: Optional[float] = None

    def result(self) -> np.ndarray:
        """Block (by stepping the engine) until done; returns the full
        sequence [T0 + n_generated] — prompt then generated tokens."""
        while not self.done:
            if not self._engine.step():
                raise RuntimeError(
                    "engine made no progress but request %r is not done"
                    % self.rid
                )
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)]
        )


class ServingEngine(object):
    """Continuous-batching engine over a transformer LM's decode
    primitives. Knobs: `max_slots` (concurrent requests in the batched
    decode), `max_len` (per-slot KV capacity, bounded by the positional
    table), `min_bucket` (smallest prefill pad length), and
    `max_prefills_per_step` (admission per step; None = fill every free
    slot — throughput-biased; 1 = latency-biased for in-flight decodes).
    """

    def __init__(self, params, cfg, max_slots=8, max_len=None,
                 min_bucket=8, max_prefills_per_step=None, donate=True):
        self._params = params
        self._cfg = cfg
        S = int(max_slots)
        if S < 1:
            raise ValueError("max_slots must be >= 1")
        self.max_slots = S
        # the positional table bounds every position (same clamp as
        # generate: a gather past it would silently clamp, not error)
        L = int(max_len or cfg.max_len)
        L = min(L, int(params["pos"].shape[0]))
        self.max_len = L
        self.min_bucket = int(min_bucket)
        if max_prefills_per_step is not None and max_prefills_per_step < 1:
            raise ValueError("max_prefills_per_step must be >= 1 or None")
        self.max_prefills_per_step = max_prefills_per_step
        self.metrics = ServingMetrics(S)

        self._cache = tlm.init_kv_cache(cfg, S, max_len=L)
        # host-side truth of the per-slot side-bands; uploaded per step
        self._tok = np.zeros(S, np.int32)     # last emitted, not yet cached
        self._pos = np.zeros(S, np.int32)     # its write position
        self._alive = np.zeros(S, bool)
        self._temps = np.zeros(S, np.float32)
        self._counts = np.zeros(S, np.int32)  # tokens generated so far
        self._base_keys = np.zeros((S, 2), np.uint32)  # per-request keys
        self._slot_req: List[Optional[ServingHandle]] = [None] * S

        self._queue: collections.deque = collections.deque()
        self._next_rid = 0
        self._donate = bool(donate)
        self._prefill_fns: Dict[int, Any] = {}
        self._decode_fn = self._make_decode()

    # ------------------------------------------------------------------
    # compiled steps
    # ------------------------------------------------------------------
    def _make_decode(self):
        cfg, metrics, L = self._cfg, self.metrics, self.max_len

        def _decode(params, cache, tok, pos, alive, temps, counts,
                    base_keys):
            metrics.count_trace("decode_step")  # trace-time side effect
            # dead slots park their write out of range: scatter DROPS
            # out-of-bounds rows, so a retired slot can never dirty the
            # cache a future prefill will claim
            write_pos = jnp.where(alive, pos, jnp.int32(L))
            logits, cache = tlm.decode_step(
                params, tok, write_pos, cache, cfg
            )
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            keys = jax.vmap(jax.random.fold_in)(base_keys, counts)
            safe_t = jnp.where(temps > 0, temps, 1.0)
            sampled = jax.vmap(
                lambda k, l, t: jax.random.categorical(
                    k, l.astype(jnp.float32) / t
                )
            )(keys, logits, safe_t).astype(jnp.int32)
            nxt = jnp.where(temps > 0, sampled, greedy)
            return cache, nxt

        kw = {"donate_argnums": (1,)} if self._donate else {}
        return jax.jit(_decode, **kw)

    def _prefill_fn(self, Tb):
        fn = self._prefill_fns.get(Tb)
        if fn is not None:
            return fn
        cfg, metrics = self._cfg, self.metrics

        def _prefill(params, cache, padded, true_len, slot, temp, key):
            metrics.count_trace("prefill_T%d" % Tb)
            sink: list = []
            # reuses forward()'s block math exactly; last_index picks
            # the TRUE last prompt row out of the padded bucket
            last = tlm.forward(
                params, padded, cfg, mesh=None, attn_impl="reference",
                kv_sink=sink, last_index=true_len - 1,
            )[0]  # [vocab]
            new_cache = []
            for kv, (k, v) in zip(cache, sink):
                ck = jax.lax.dynamic_update_slice(
                    kv["k"], k.astype(kv["k"].dtype), (slot, 0, 0, 0)
                )
                cv = jax.lax.dynamic_update_slice(
                    kv["v"], v.astype(kv["v"].dtype), (slot, 0, 0, 0)
                )
                new_cache.append({"k": ck, "v": cv})
            greedy = jnp.argmax(last, axis=-1).astype(jnp.int32)
            sampled = jax.random.categorical(
                key,
                last.astype(jnp.float32) / jnp.where(temp > 0, temp, 1.0),
            ).astype(jnp.int32)
            first = jnp.where(temp > 0, sampled, greedy)
            return new_cache, first

        kw = {"donate_argnums": (1,)} if self._donate else {}
        fn = jax.jit(_prefill, **kw)
        self._prefill_fns[Tb] = fn
        return fn

    # ------------------------------------------------------------------
    # scheduler
    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens, temperature=0.0, eos_id=None,
               seed=0) -> ServingHandle:
        """Enqueue one request (FCFS). Returns a handle whose `.tokens`
        fills in as the engine steps; `handle.result()` drives the
        engine to completion of this request."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        T0 = prompt.shape[0]
        if T0 < 1:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if T0 + int(max_new_tokens) > self.max_len:
            raise ValueError(
                "request needs T0+max_new <= max_len (%d + %d > %d)"
                % (T0, int(max_new_tokens), self.max_len)
            )
        h = ServingHandle(self, self._next_rid, prompt, max_new_tokens,
                          temperature, eos_id, seed)
        self._next_rid += 1
        self._queue.append(h)
        return h

    def _free_slot(self) -> Optional[int]:
        for s in range(self.max_slots):
            if self._slot_req[s] is None:
                return s
        return None

    def _bucket(self, T0: int) -> int:
        return min(bucket_pow2(T0, floor=self.min_bucket), self.max_len)

    def _retire(self, s: int, reason: str):
        h = self._slot_req[s]
        h.done = True
        h.finish_reason = reason
        self._slot_req[s] = None
        self._alive[s] = False

    def _emit(self, s: int, token: int) -> bool:
        """Append one generated token to slot s's request; retire on EOS
        or budget (EOS on the budget-exhausting step reports 'eos').
        Returns True if the slot was retired."""
        h = self._slot_req[s]
        h.tokens.append(int(token))
        self._counts[s] += 1
        self.metrics.tokens_out += 1
        if h.eos_id is not None and int(token) == int(h.eos_id):
            self._retire(s, "eos")
            return True
        if len(h.tokens) >= h.max_new_tokens:
            self._retire(s, "budget")
            return True
        return False

    def _admit(self, h: ServingHandle, s: int):
        t0 = time.monotonic()
        h.queue_wait_s = t0 - h.submit_t
        self.metrics.queue_wait_s.append(h.queue_wait_s)
        T0 = h.prompt.shape[0]
        Tb = self._bucket(T0)
        padded = np.zeros((1, Tb), np.int32)
        padded[0, :T0] = h.prompt
        fn = self._prefill_fn(Tb)
        key = jax.random.fold_in(jax.random.PRNGKey(h.seed), 0)
        self._cache, first = fn(
            self._params, self._cache, jnp.asarray(padded),
            jnp.int32(T0), jnp.int32(s),
            jnp.float32(h.temperature), key,
        )
        first = int(np.asarray(first))  # blocks: first token is real
        now = time.monotonic()
        h.ttft_s = now - h.submit_t
        self.metrics.ttft_s.append(h.ttft_s)
        self.metrics.span("prefill_T%d" % Tb, now - t0)
        self.metrics.prefills += 1

        self._slot_req[s] = h
        self._tok[s] = first
        self._pos[s] = T0
        self._alive[s] = True
        self._temps[s] = h.temperature
        self._counts[s] = 0
        self._base_keys[s] = np.asarray(jax.random.PRNGKey(h.seed))
        self._emit(s, first)  # may retire immediately (max_new==1 / eos)

    def step(self) -> bool:
        """One scheduler iteration: admit queued requests into free
        slots (bounded by max_prefills_per_step), then ONE batched
        decode advancing every live slot; retirements free slots for
        the next step's admissions. Returns False when there was
        nothing to do (queue empty and no live slots)."""
        admitted = 0
        cap = self.max_prefills_per_step
        while self._queue and (cap is None or admitted < cap):
            s = self._free_slot()
            if s is None:
                break
            self._admit(self._queue.popleft(), s)
            admitted += 1

        if not self._alive.any():
            return admitted > 0

        t0 = time.monotonic()
        self._cache, nxt = self._decode_fn(
            self._params, self._cache,
            jnp.asarray(self._tok), jnp.asarray(self._pos),
            jnp.asarray(self._alive), jnp.asarray(self._temps),
            jnp.asarray(self._counts), jnp.asarray(self._base_keys),
        )
        nxt = np.asarray(nxt)  # blocks; tokens are real
        self.metrics.span("decode_step", time.monotonic() - t0)
        self.metrics.decode_steps += 1
        self.metrics.occupancy.append(
            float(self._alive.sum()) / self.max_slots
        )

        live = np.nonzero(self._alive)[0]
        self._pos[live] += 1  # the token just cached sat at pos
        for s in live:
            self._tok[s] = nxt[s]
            self._emit(s, nxt[s])
        return True

    def run(self) -> Dict[int, np.ndarray]:
        """Drive the engine until the queue drains and every slot
        retires; returns {request_id: full sequence} for every request
        completed during this call."""
        finished: Dict[int, np.ndarray] = {}
        # a retired handle never lingers in _slot_req, so everything
        # in-flight or queued right now is exactly this call's work
        pending = list(self._queue) + [
            h for h in self._slot_req if h is not None
        ]
        while self.step():
            pass
        for h in pending:
            if h.done:
                finished[h.rid] = np.concatenate(
                    [h.prompt, np.asarray(h.tokens, np.int32)]
                )
        return finished

    # ------------------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def live_slots(self) -> int:
        return int(self._alive.sum())
